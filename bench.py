"""Headline benchmark: LoRA SFT tokens/sec/chip (BASELINE.md north-stars).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Orchestration (round 3, per VERDICT next-round #1 and #3):
- Pre-flight probes the default device in a subprocess, RETRYING over a
  window (the tunneled relay wedges transiently) before degrading to CPU.
- A CPU fallback line is explicitly marked ``"cpu_fallback": true`` with
  ``"vs_baseline": null`` so a smoke run can never read as a TPU result;
  if a dated in-repo TPU artifact exists (BENCH_TPU.json) its headline is
  referenced in ``"tpu_evidence"``.
- On TPU the headline is the NORTH-STAR metric — Llama-2-7B QLoRA
  tokens/sec/chip (scripts/bench_7b.py, BASELINE.json metric) — with the
  tinyllama-1.1b line (rounds 1-2 continuity) embedded as ``"secondary"``.
  Both are persisted with timestamp+config to BENCH_TPU.json.
- Each measurement runs in its own subprocess: a wedge mid-bench costs that
  child's timeout, not the whole artifact.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
denominator is this project's own prior recorded measurement — values > 1.0
mean speedup over that round. 7B line: round-2's 709 tok/s/chip (XLA dequant
path). tinyllama line: round-1's 12,996 tok/s/chip.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))

DEADLINE_S = float(os.environ.get("DTX_BENCH_TIMEOUT_S", "480"))
PREFLIGHT_TIMEOUT_S = float(os.environ.get("DTX_BENCH_PREFLIGHT_S", "60"))
PREFLIGHT_TRIES = int(os.environ.get("DTX_BENCH_PREFLIGHT_TRIES", "4"))
PREFLIGHT_SLEEP_S = float(os.environ.get("DTX_BENCH_PREFLIGHT_SLEEP_S", "15"))

# Prior-round recorded tokens/sec/chip on TPU v5e-1 (see BASELINE.md); update
# only alongside BASELINE.md.
ROUND1_TINYLLAMA_TOKS = 12996.0  # round 1, xla attention, B8xT1024
ROUND2_7B_TOKS = 709.0           # round 2, nf4 XLA dequant path, B4xT1024


# --------------------------------------------------------------- child mode

def child_tinyllama():
    """Measure tinyllama-1.1b LoRA SFT tokens/sec on the default backend and
    print one JSON line. Run in a subprocess by the orchestrator."""
    import jax

    if os.environ.get("DTX_BENCH_FORCE_CPU"):
        # env-var platform selection is intercepted by the tunnel's
        # sitecustomize; config.update is the only reliable CPU escape
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from datatunerx_tpu.models import get_config, init_params
    from datatunerx_tpu.training import TrainConfig, Trainer
    from datatunerx_tpu.training.loss import IGNORE_INDEX

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        model, B, T, steps = "tinyllama-1.1b", 8, 1024, 20
        B = int(os.environ.get("DTX_BENCH_BATCH", B))
    else:  # CPU smoke so the artifact always carries a line
        model, B, T, steps = "debug", 8, 128, 5

    # perf knobs: the Pallas flash kernel is Mosaic-validated on the v5e
    # (scripts/tpu_validate.py 8/8, BASELINE.md round-2 pass) and is 1.34x
    # the xla-attention round-1 number — it is the TPU default. CPU smoke
    # keeps xla (flash off-TPU would dispatch interpret mode: slow, no signal).
    attention = os.environ.get("DTX_BENCH_ATTENTION",
                               "flash" if on_tpu else "xla")
    remat = os.environ.get("DTX_BENCH_REMAT", "dots")
    cfg = get_config(model, remat=remat, attention_impl=attention)
    tr = Trainer(
        cfg,
        TrainConfig(
            finetuning_type="lora", lora_rank=8, lora_alpha=32.0,
            lora_dropout=0.05, lora_targets=("q_proj", "v_proj"),
            learning_rate=2e-4, scheduler="cosine", optimizer="adamw",
            total_steps=1000, compute_dtype=jnp.bfloat16,
        ),
    )
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    state = tr.init_state(params, jax.random.PRNGKey(1))

    rng = jax.random.PRNGKey(2)
    toks = jax.random.randint(rng, (B, T), 0, cfg.vocab_size, jnp.int32)
    labels = jnp.where(
        jnp.arange(T)[None, :] < T // 8, IGNORE_INDEX, toks
    )  # prompt-masked SFT batch shape
    batch = {"input_ids": toks, "labels": labels}

    # warmup / compile. NOTE: sync via host value fetch, not block_until_ready —
    # the tunneled TPU backend's block_until_ready can return before remote
    # execution finishes, which inflates throughput by ~5000x.
    state, m = tr.train_step(state, batch)
    float(m["loss"])

    # DTX_BENCH_PIPELINE=1: feed the steps through the pipelined input path
    # (data/prefetch.py — host batch build in a background thread + batch N+1
    # placed while step N runs), the same machinery tuning/train.py uses. The
    # default path keeps the static-batch measurement for round-over-round
    # continuity; the pipelined line carries the pipeline wait stats so input
    # stalls are visible next to the throughput number.
    pipelined = bool(os.environ.get("DTX_BENCH_PIPELINE"))
    pipe_stats = None
    if pipelined:
        import numpy as np

        from datatunerx_tpu.data.prefetch import PipelineStats, prefetch_batches
        from datatunerx_tpu.parallel.sharding import place_batch
        from datatunerx_tpu.training.loss import IGNORE_INDEX as _II

        host_rng = np.random.default_rng(3)

        def host_batches():
            for _ in range(steps):
                t = host_rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)
                lab = np.where(np.arange(T)[None, :] < T // 8, _II, t)
                yield {"input_ids": t, "labels": lab.astype(np.int32)}

        pipe_stats = PipelineStats()
        batches, host_pf = prefetch_batches(
            host_batches,
            place_fn=lambda b: place_batch(b, tr.mesh),
            depth=int(os.environ.get("DTX_BENCH_PREFETCH_DEPTH", "2")),
            stats=pipe_stats,
        )
        t0 = time.perf_counter()
        try:
            for b in batches:
                state, m = tr.train_step(state, b)
        finally:
            host_pf.close()
        float(m["loss"])
        dt = time.perf_counter() - t0
    else:
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = tr.train_step(state, batch)
        float(m["loss"])  # device-to-host fetch = true pipeline drain
        dt = time.perf_counter() - t0

    toks_per_sec = B * T * steps / dt
    vs = toks_per_sec / ROUND1_TINYLLAMA_TOKS if on_tpu else None
    tag = (f",{attention}" if attention != "xla" else "") + (
        f",remat={remat}" if remat != "dots" else "")
    tag += f",B{B}" if B != 8 else ""
    tag += ",pipelined" if pipelined else ""
    line = {
        "metric": f"lora_sft_tokens_per_sec_per_chip[{model},B{B}xT{T}{tag}]",
        "value": round(toks_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs, 3) if vs is not None else None,
        # explicit provenance so a CPU-only round can never be read as TPU
        # signal: the MEASURED platform, straight from the device that ran
        "platform": jax.devices()[0].platform,
        "cpu_fallback": not on_tpu,
    }
    if pipe_stats is not None:
        line["pipeline"] = {k: round(v, 3)
                            for k, v in pipe_stats.snapshot().items()}
    print(json.dumps(line))


def _pct(xs, q):
    """Nearest-sample percentile over an (un)sorted list — the one
    implementation every bench mode's p50/p95/p99 shares."""
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))] if xs else 0.0


def child_serve(preflight=None):
    """DTX_BENCH_SERVE=1: continuous-batching serve bench. A mixed long/short
    chat workload runs through one BatchedEngine (paged KV cache + chunked
    prefill by default; DTX_BENCH_SERVE_PAGED=0 compares the dense cache) and
    the line carries the three serving north-stars: aggregate tokens/s, TTFT
    (time to first streamed token, where chunked prefill + the prefill token
    budget bite), and TPOT (inter-token time, where a long admission stalling
    decode would show). CPU numbers are smoke-only, like the pipeline bench.
    """
    import jax

    if os.environ.get("DTX_BENCH_FORCE_CPU"):
        # env-var platform selection is intercepted by the tunnel's
        # sitecustomize; config.update is the only reliable CPU escape
        jax.config.update("jax_platforms", "cpu")
    import threading

    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        model, max_seq, short_new, long_new = "tinyllama-1.1b", 1024, 48, 32
        n_short, n_long = 12, 4
    else:  # CPU smoke: tiny model, tiny workload, same code path
        model, max_seq, short_new, long_new = "debug", 256, 12, 8
        n_short, n_long = 6, 2
    slots = int(os.environ.get("DTX_BENCH_SERVE_SLOTS", "4"))
    paged = os.environ.get("DTX_BENCH_SERVE_PAGED", "1") != "0"
    block = int(os.environ.get("DTX_BENCH_BLOCK_SIZE", "16"))
    budget = int(os.environ.get("DTX_BENCH_PREFILL_BUDGET", "256"))
    # decode path: auto = Pallas in-place kernel on TPU, XLA gather
    # elsewhere; "on" forces the kernel (interpret-mode on CPU — slower,
    # smoke-only) so the kernel-vs-gather contract runs on every platform
    kernel_mode = os.environ.get("DTX_BENCH_SERVE_KERNEL", "auto")
    # adapter-churn mode: M synthetic tenant adapters rotate through a
    # P-slot pool with M > P, so the run exercises load-on-miss + LRU
    # eviction under mixed traffic and reports adapter hit rate + load
    # latency next to tokens/s (the capacity story of the dynamic plane)
    n_adapters = int(os.environ.get("DTX_BENCH_SERVE_ADAPTERS", "0"))
    adapter_pool = int(os.environ.get(
        "DTX_BENCH_ADAPTER_POOL", str(max(1, n_adapters // 2))))
    adapter_names = []
    adapter_ckpts = {}
    tmpdir = None
    if n_adapters > 0:
        import tempfile

        from datatunerx_tpu.serving.adapters import make_adapter_sweep

        tmpdir = tempfile.mkdtemp(prefix="dtx-bench-adapters-")
        adapter_ckpts = make_adapter_sweep(tmpdir, f"preset:{model}",
                                           n_adapters)
        adapter_names = sorted(adapter_ckpts)
    decode_chunk = int(os.environ.get("DTX_BENCH_DECODE_CHUNK", "8"))
    engine_kw = dict(
        template="vanilla", max_seq_len=max_seq, slots=slots,
        decode_chunk=decode_chunk,
        adapters=adapter_ckpts or None,
        adapter_pool=adapter_pool if n_adapters else 0,
        kv_block_size=block if paged else 0,
        prefill_token_budget=budget if paged else 0,
    )
    eng = BatchedEngine(f"preset:{model}",
                        paged_kernel=kernel_mode if paged else "auto",
                        **engine_kw)
    decode_parity_checked = False
    try:
        tok = eng.tokenizer
        short_ids = tok.encode("a quick question about the weather today")
        long_ids = tok.encode("background context " * (max_seq // 4))
        eng.generate(short_ids, max_new_tokens=2)  # compile prefill+decode
        eng.generate(long_ids, max_new_tokens=2)

        if eng.paged_kernel:
            # a fast-but-wrong number must be unreportable: before the
            # clock starts, the kernel engine's outputs are asserted
            # token-identical (greedy AND fixed-seed sampled) against a
            # gather-oracle twin sharing every other knob
            oracle = BatchedEngine(f"preset:{model}", paged_kernel="off",
                                   **engine_kw)
            try:
                for ids in (short_ids, long_ids[: max_seq // 4]):
                    for kw in ({}, {"temperature": 0.8, "top_p": 0.9,
                                    "seed": 11}):
                        want = oracle.generate(ids, max_new_tokens=8, **kw)
                        got = eng.generate(ids, max_new_tokens=8, **kw)
                        assert got == want, (
                            "paged kernel diverged from the gather oracle "
                            f"(kw={kw}): {got} != {want}")
            finally:
                oracle.close()
            decode_parity_checked = True

        lock = threading.Lock()
        per_req = []  # (t_submit, [token arrival times])

        def consume(req, t0):
            stamps = []
            while True:
                t = req.stream.get()
                if t is None:
                    break
                stamps.append(time.perf_counter())
            with lock:
                per_req.append((t0, stamps, req.error))

        threads = []
        wall0 = time.perf_counter()
        # interleave: every 3rd request is a long prompt, arriving while
        # short decodes are in flight — the head-of-line-blocking shape
        workload = []
        li = si = 0
        while li < n_long or si < n_short:
            if si < n_short:
                workload.append((short_ids, short_new)); si += 1
            if si % 2 == 0 and li < n_long:
                workload.append((long_ids, long_new)); li += 1
        for i, (ids, max_new) in enumerate(workload):
            t0 = time.perf_counter()
            # churn mode: requests cycle the adapter population (every 4th
            # stays on base) so residency is constantly contested
            adapter = (adapter_names[i % len(adapter_names)]
                       if adapter_names and i % 4 else "")
            req = eng.submit(ids, max_new_tokens=max_new, adapter=adapter)
            th = threading.Thread(target=consume, args=(req, t0), daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=600)
        wall = time.perf_counter() - wall0
    finally:
        eng.close()

    errors = [e for _, _, e in per_req if e]
    ttfts = sorted((s[0] - t0) for t0, s, e in per_req if s and not e)
    tpots = sorted((s[-1] - s[0]) / (len(s) - 1)
                   for _, s, e in per_req if len(s) > 1 and not e)
    total_tokens = sum(len(s) for _, s, _ in per_req)
    mean = lambda xs: sum(xs) / len(xs) if xs else 0.0
    pct = _pct
    decode_path = eng.decode_path
    tag = (f"{model},slots{slots}," +
           (f"paged,bs{block},budget{budget}" if paged else "dense") +
           (",kernel" if decode_path == "pallas" else "") +
           (f",adapters{n_adapters}/pool{adapter_pool}"
            if n_adapters else ""))
    line = {
        "metric": f"serve_tokens_per_sec[{tag}]",
        "value": round(total_tokens / wall, 1) if wall > 0 else 0.0,
        "unit": "tokens/s",
        "vs_baseline": None,  # no prior serve-bench round to compare against
        # explicit provenance so a CPU-only round can never be read as TPU
        # signal: the MEASURED platform, straight from the device that ran
        "platform": jax.devices()[0].platform,
        "cpu_fallback": not on_tpu,
        # decode-path provenance next to platform/cpu_fallback: which
        # attention read served this number (pallas kernel / XLA gather /
        # dense), and whether the kernel run passed its pre-clock
        # token-parity gate against the gather oracle
        "paged_kernel": decode_path == "pallas",
        "decode_path": decode_path,
        "serve": {
            "requests": len(per_req),
            "errors": len(errors),
            "tokens": total_tokens,
            "decode_parity_checked": decode_parity_checked,
            "ttft_ms_mean": round(mean(ttfts) * 1e3, 1),
            "ttft_ms_p50": round(pct(ttfts, 0.5) * 1e3, 1),
            "ttft_ms_p95": round(pct(ttfts, 0.95) * 1e3, 1),
            "ttft_ms_p99": round(pct(ttfts, 0.99) * 1e3, 1),
            "tpot_ms_mean": round(mean(tpots) * 1e3, 2),
            "tpot_ms_p50": round(pct(tpots, 0.5) * 1e3, 2),
            "tpot_ms_p95": round(pct(tpots, 0.95) * 1e3, 2),
            "tpot_ms_p99": round(pct(tpots, 0.99) * 1e3, 2),
            "prefill_stats": dict(eng.prefill_stats),
        },
    }
    occ = eng.adapter_occupancy() if n_adapters else None
    if occ is not None:
        lookups = occ["hits"] + occ["misses"]
        load_ms = sorted(occ.get("load_ms") or [])
        line["serve"]["adapters"] = {
            "count": n_adapters,
            "pool_slots": occ["slots"],
            "hit_rate": round(occ["hits"] / lookups, 3) if lookups else None,
            "loads": occ["loads"],
            "evictions": occ["evictions"],
            "load_ms_p50": round(pct(load_ms, 0.5), 1),
            "load_ms_p95": round(pct(load_ms, 0.95), 1),
        }
    if preflight is not None:
        line["preflight"] = preflight
    print(json.dumps(line), flush=True)


def child_serve_capacity(preflight=None):
    """DTX_BENCH_SERVE_CAPACITY=1: KV-overcommit capacity twin bench. The
    same reservation-heavy mixed workload (short prompts with generous
    ``max_new`` budgets — the shape where eager reserve strands the most
    blocks — interleaved with longer prompts) runs on TWIN engines over
    ONE block budget: eager reserve (``kv_overcommit off``, today's
    ceil((prompt+max_new)/bs) admission) vs overcommit (lazy reserve +
    on-demand growth + youngest-first preemption). The scoreboard is MAX
    CONCURRENT IN-FLIGHT SESSIONS at token parity, plus blocks-per-session
    p50/p95, preemption/resume counts, and tokens/s.

    Before the clock starts, the overcommit twin's outputs are asserted
    token-identical (greedy AND fixed-seed sampled) against the eager twin
    — preemption/growth must be invisible in the tokens, or the capacity
    number is unreportable. The run also asserts the acceptance bar: the
    overcommit twin admits >= 1.5x the eager twin's peak concurrent
    sessions on the same pool, with zero errors (no preemption deadlock).
    CPU numbers are smoke-only, like the serve bench."""
    import jax

    if os.environ.get("DTX_BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import threading

    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        model, max_seq, short_new, long_new = "tinyllama-1.1b", 1024, 192, 32
        n_short, n_long = 10, 3
    else:
        model, max_seq, short_new, long_new = "debug", 256, 64, 16
        n_short, n_long = 6, 2
    slots = int(os.environ.get("DTX_BENCH_SERVE_SLOTS", "4"))
    block = int(os.environ.get("DTX_BENCH_BLOCK_SIZE", "16"))
    # a pool sized so EAGER reserve is the binding constraint: roughly two
    # short sessions' eager reserve, while lazy reserve fits all `slots`
    blocks = int(os.environ.get(
        "DTX_BENCH_KV_BLOCKS",
        str(2 * (-(-(64 + short_new) // block)) + 4 if not on_tpu else
            2 * (-(-(256 + short_new) // block)) + 4)))
    engine_kw = dict(
        template="vanilla", max_seq_len=max_seq, slots=slots,
        decode_chunk=int(os.environ.get("DTX_BENCH_DECODE_CHUNK", "8")),
        kv_block_size=block, kv_blocks=blocks)
    pct = _pct

    def run_workload(eng):
        tok = eng.tokenizer
        short_ids = tok.encode("a quick question about the weather today")
        long_ids = tok.encode("background context " * (max_seq // 8))
        lock = threading.Lock()
        per_req = []

        def consume(req, t0):
            stamps = []
            while True:
                t = req.stream.get()
                if t is None:
                    break
                stamps.append(time.perf_counter())
            with lock:
                per_req.append((t0, stamps, req.error))

        workload = []
        li = si = 0
        while li < n_long or si < n_short:
            if si < n_short:
                workload.append((short_ids, short_new)); si += 1
            # longs arrive after the first slot-filling wave of shorts, so
            # the peak-concurrency comparison measures RESERVE pessimism
            # (the thing overcommit removes), not long-prompt FIFO waits
            if (si % 5 == 0 or si >= n_short) and li < n_long:
                workload.append((long_ids, long_new)); li += 1
        threads = []
        wall0 = time.perf_counter()
        for ids, max_new in workload:
            t0 = time.perf_counter()
            req = eng.submit(ids, max_new_tokens=max_new)
            th = threading.Thread(target=consume, args=(req, t0), daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=600)
        wall = time.perf_counter() - wall0
        # the LIVENESS gate proper: a deadlocked session would hang its
        # consumer past the join timeout and silently vanish from per_req —
        # every submitted request must have terminated, or the capacity
        # number is unreportable
        assert len(per_req) == len(workload) and \
            not any(th.is_alive() for th in threads), (
            f"{len(workload) - len(per_req)} session(s) never terminated "
            "— preemption deadlock")
        tokens = sum(len(s) for _, s, _ in per_req)
        errors = [e for _, _, e in per_req if e]
        sess_blocks = sorted(eng.kv_stats["session_blocks"])
        preempts = dict(eng.preempt_stats)
        return {
            "requests": len(per_req), "errors": len(errors),
            "tokens": tokens,
            "tokens_per_sec": round(tokens / wall, 1) if wall > 0 else 0.0,
            "peak_sessions": eng.kv_stats["peak_sessions"],
            "blocks_per_session_p50": pct(sess_blocks, 0.5),
            "blocks_per_session_p95": pct(sess_blocks, 0.95),
            "preemptions": preempts.get("exported", 0)
            + preempts.get("requeued_prefill", 0),
            "resumes": preempts.get("resumed", 0),
            "overcommit_peak_ratio": None,
        }

    eager = BatchedEngine(f"preset:{model}", kv_overcommit="off",
                          **engine_kw)
    over = BatchedEngine(f"preset:{model}", kv_overcommit="on",
                         **engine_kw)
    try:
        tok = eager.tokenizer
        probes = [tok.encode("a quick question about the weather today"),
                  tok.encode("tell me something entirely different")]
        # pre-clock token-parity gate: growth + preemption must be
        # invisible in the tokens before any capacity number is reportable
        for ids in probes:
            for kw in ({}, {"temperature": 0.8, "top_p": 0.9, "seed": 11}):
                want = eager.generate(ids, max_new_tokens=12, **kw)
                got = over.generate(ids, max_new_tokens=12, **kw)
                assert got == want, (
                    f"overcommit diverged from the eager twin (kw={kw}): "
                    f"{got} != {want}")
        eager_stats = run_workload(eager)
        over_stats = run_workload(over)
    finally:
        eager.close()
        over.close()

    assert over_stats["errors"] == 0 and eager_stats["errors"] == 0, (
        "capacity workload dropped sessions (preemption deadlock?): "
        f"{over_stats} vs {eager_stats}")
    ratio = (over_stats["peak_sessions"]
             / max(1, eager_stats["peak_sessions"]))
    over_stats["overcommit_peak_ratio"] = round(ratio, 2)
    assert ratio >= 1.5, (
        "overcommit admitted no more concurrent sessions than eager "
        f"reserve on the same pool: {over_stats['peak_sessions']} vs "
        f"{eager_stats['peak_sessions']} (ratio {ratio:.2f} < 1.5)")
    tag = f"{model},slots{slots},bs{block},blocks{blocks}"
    line = {
        "metric": f"serve_capacity_sessions[{tag}]",
        "value": over_stats["peak_sessions"],
        "unit": "sessions",
        "vs_baseline": None,
        "platform": jax.devices()[0].platform,
        "cpu_fallback": not on_tpu,
        "decode_path": over.decode_path,
        "capacity": {
            "parity_checked": True,
            "kv_blocks": blocks, "block_size": block, "slots": slots,
            "peak_ratio": round(ratio, 2),
            "overcommit": over_stats,
            "eager": eager_stats,
        },
    }
    if preflight is not None:
        line["preflight"] = preflight
    print(json.dumps(line), flush=True)


def child_serve_spec(preflight=None):
    """DTX_BENCH_SERVE_SPEC=1: speculative-decoding serve bench. The same
    mixed greedy workload runs on TWIN engines — spec-on (take:N
    self-speculative draft) vs spec-off — over the same model, twice:

    - **aligned**: the target's post-draft layers' output projections are
      scaled toward zero (residual passthrough), so the truncated draft is
      a faithful approximation of the target — the trained-draft regime
      where speculation pays. The line reports acceptance rate, mean
      accepted length, and the TPOT p50/p95 delta vs the spec-off twin.
    - **adversarial**: raw random deep layers — the draft is noise and
      acceptance collapses. The run asserts the adaptive-k controller
      demonstrably DISABLES speculation (plain pending-form fallback) so
      TPOT cannot regress vs spec-off.

    A TREE sub-run rides along (``--spec_tree WxD`` at depth D == chain k,
    so the draft cost is identical): a contested mediocre-draft regime
    compares tree vs chain accept-length p50 (the tree must not lose — its
    branch 0 IS the chain path) and TPOT ratios, and an adversarial tree
    run asserts the controller stands tree speculation down too.

    Before the clock starts, every spec-on engine's greedy outputs are
    asserted token-identical to the spec-off twin (the PR 13 kernel-gate
    pattern): a fast-but-wrong number must be unreportable. The JSON line
    carries ``spec_mode``/``spec_draft``/``spec_tree``/``decode_path``
    provenance next to ``platform``/``cpu_fallback``. CPU numbers are
    smoke-only.
    """
    import dataclasses

    import jax

    if os.environ.get("DTX_BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import threading

    from datatunerx_tpu.models.config import PRESETS
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    on_tpu = jax.default_backend() == "tpu"
    layers = int(os.environ.get("DTX_BENCH_SPEC_LAYERS", "6"))
    take = int(os.environ.get("DTX_BENCH_SPEC_TAKE", "1"))
    k = int(os.environ.get("DTX_BENCH_SPEC_K", "4"))
    slots = int(os.environ.get("DTX_BENCH_SERVE_SLOTS", "4"))
    block = int(os.environ.get("DTX_BENCH_BLOCK_SIZE", "16"))
    max_seq, short_new, long_new = 256, 24, 16
    n_short, n_long = 6, 2
    if "bench-spec" not in PRESETS:
        PRESETS["bench-spec"] = dataclasses.replace(
            PRESETS["debug"], name="bench-spec", num_layers=layers)
    engine_kw = dict(
        template="vanilla", max_seq_len=max_seq, slots=slots,
        decode_chunk=int(os.environ.get("DTX_BENCH_DECODE_CHUNK", "8")),
        kv_block_size=block)

    def align_params(params, alpha):
        """Scale post-draft layers' OUTPUT projections toward zero: the
        residual stream passes through them near-unchanged, so take:N
        approximates the full target while the target still pays every
        layer's compute. Layers < take are untouched, so the draft (sliced
        at engine construction) stays numerically identical to the
        target's early layers. alpha sets how faithful the draft is:
        1e-3 ~ trained-draft regime, ~0.3 a mediocre draft whose chain
        proposals diverge early (the regime tree drafts exist for)."""
        layers_t = dict(params["layers"])
        for name in ("o_proj", "down_proj"):
            sub = dict(layers_t[name])
            sub["kernel"] = sub["kernel"].at[take:].multiply(alpha)
            layers_t[name] = sub
        out = dict(params)
        out["layers"] = layers_t
        return out

    pct = _pct

    def run_workload(eng):
        tok = eng.tokenizer
        short_ids = tok.encode("a quick question about the weather today")
        long_ids = tok.encode("background context " * (max_seq // 8))
        lock = threading.Lock()
        per_req = []

        def consume(req, t0):
            stamps = []
            while True:
                t = req.stream.get()
                if t is None:
                    break
                stamps.append(time.perf_counter())
            with lock:
                per_req.append((t0, stamps, req.error))

        workload = []
        li = si = 0
        while li < n_long or si < n_short:
            if si < n_short:
                workload.append((short_ids, short_new)); si += 1
            if si % 2 == 0 and li < n_long:
                workload.append((long_ids, long_new)); li += 1
        threads = []
        wall0 = time.perf_counter()
        for ids, max_new in workload:
            t0 = time.perf_counter()
            req = eng.submit(ids, max_new_tokens=max_new)
            th = threading.Thread(target=consume, args=(req, t0), daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=600)
        wall = time.perf_counter() - wall0
        tokens = sum(len(s) for _, s, _ in per_req)
        errors = [e for _, _, e in per_req if e]
        tpots = [(s[-1] - s[0]) / (len(s) - 1)
                 for _, s, e in per_req if len(s) > 1 and not e]
        return {
            "requests": len(per_req), "errors": len(errors),
            "tokens": tokens,
            "tokens_per_sec": round(tokens / wall, 1) if wall > 0 else 0.0,
            "tpot_ms_p50": round(pct(tpots, 0.5) * 1e3, 2),
            "tpot_ms_p95": round(pct(tpots, 0.95) * 1e3, 2),
        }

    def run_pair(alpha, tree=None, mode="auto", epilogue="auto",
                 learned=True):
        from datatunerx_tpu.obs.metrics import (
            Registry,
            spec_accept_len_histogram,
        )

        reg = Registry()
        off = BatchedEngine("preset:bench-spec",
                            sampling_epilogue=epilogue, **engine_kw)
        on = BatchedEngine("preset:bench-spec", spec_draft=f"take:{take}",
                           spec_k=k, spec_mode=mode, spec_tree=tree,
                           spec_tree_learned=learned,
                           sampling_epilogue=epilogue,
                           registry=reg, **engine_kw)
        try:
            if alpha is not None:
                off.params = align_params(off.params, alpha)
                on.params = align_params(on.params, alpha)
            tok = off.tokenizer
            probes = [tok.encode("a quick question about the weather today"),
                      tok.encode("tell me something entirely different")]
            # pre-clock token-parity gate (greedy): the spec engine's
            # output must be IDENTICAL to the non-spec twin before any
            # number it produces is reportable
            for ids in probes:
                want = off.generate(ids, max_new_tokens=12)
                got = on.generate(ids, max_new_tokens=12)
                assert got == want, (
                    f"spec-on diverged from spec-off twin: {got} != {want}")
            off_stats = run_workload(off)
            on_stats = run_workload(on)
            info = on.spec_info() or {}
            proposed = info.get("proposed", 0)
            accepted = info.get("accepted", 0)
            row_steps = info.get("row_steps", 0)
            h_len = spec_accept_len_histogram(reg)
            out = {
                "parity_checked": True,
                "accept_rate": (round(accepted / proposed, 3)
                                if proposed else None),
                # true mean accepted length per verify event — robust to
                # the controller shrinking k mid-run (proposed tracks the
                # ACTUAL per-step k, so accepted*k/proposed would inflate)
                "mean_accept_len": (round(accepted / row_steps, 2)
                                    if row_steps else None),
                # per-row accepted-length p50 from the same histogram the
                # server exports — the tree-vs-chain comparison statistic
                "accept_len_p50": (round(h_len.percentile(0.5), 2)
                                   if h_len.count else None),
                "spec_steps": info.get("spec_steps", 0),
                "plain_steps": info.get("plain_steps", 0),
                "controller_active": bool(info.get("active")),
                "disabled_events": info.get("disabled_events", 0),
                "on": on_stats, "off": off_stats,
                "tpot_p50_ratio": (
                    round(on_stats["tpot_ms_p50"] / off_stats["tpot_ms_p50"],
                          3) if off_stats["tpot_ms_p50"] else None),
            }
            out["sampling_epilogue"] = on.sampling_epilogue
            out["epilogue_impl"] = on._epilogue_impl
            out["fused_steps"] = on.sampling_stats["fused_steps"]
            if tree is not None:
                out["tree_steps"] = info.get("tree_steps", 0)
                out["tree"] = info.get("tree")
            return out, on.decode_path
        finally:
            off.close()
            on.close()

    aligned, decode_path = run_pair(alpha=1e-3)
    adversarial, _ = run_pair(alpha=None)
    # the adaptive controller's contract: on the adversarial workload
    # speculation must demonstrably stand down (plain fallback carries the
    # traffic), so its TPOT cannot drift from the spec-off twin's
    assert adversarial["plain_steps"] >= adversarial["spec_steps"], (
        "adaptive-k controller failed to disable spec on the adversarial "
        f"workload: {adversarial}")
    adversarial["controller_disabled"] = True

    # ---- tree-draft sub-run: same draft cost (depth D == chain k draft
    # forwards), contested regime (mediocre draft, spec pinned on so both
    # shapes keep drafting). Greedy tree branch 0 IS the chain path, so per
    # row tree acceptance dominates chain acceptance structurally — the
    # accept-length lift is the tree's whole value proposition.
    tree_spec_s = os.environ.get("DTX_BENCH_SPEC_TREE", f"2x{k}")
    contested_alpha = float(os.environ.get("DTX_BENCH_SPEC_ALPHA", "0.12"))
    chain_c, _ = run_pair(alpha=contested_alpha, mode="on")
    # learned=False pins the fixed WxD rectangle controller — the
    # chain-vs-tree statistic keeps its pre-learned-shapes meaning
    tree_c, _ = run_pair(alpha=contested_alpha, tree=tree_spec_s, mode="on",
                         learned=False)
    # adversarial run keeps the LEARNED controller (default) — standing
    # down must hold for the controller that actually ships
    tree_adv, _ = run_pair(alpha=None, tree=tree_spec_s)
    # never-slower carries over to trees: adversarial drafts stand down
    assert tree_adv["plain_steps"] >= tree_adv["spec_steps"], (
        "adaptive controller failed to disable TREE spec on the "
        f"adversarial workload: {tree_adv}")
    tree_adv["controller_disabled"] = True
    if (tree_c["accept_len_p50"] is not None
            and chain_c["accept_len_p50"] is not None):
        # 0.5 slack: p50 is bucketed and concurrent submits batch rows
        # slightly differently between the twin runs
        assert tree_c["accept_len_p50"] >= chain_c["accept_len_p50"] - 0.5, (
            "tree drafts failed to lift accept_len p50 over the chain at "
            f"equal draft cost: tree={tree_c['accept_len_p50']} "
            f"chain={chain_c['accept_len_p50']}")
    tree_block = {
        "spec_tree": tree_spec_s,
        "contested_alpha": contested_alpha,
        "chain_contested": chain_c,
        "contested": tree_c,
        "adversarial": tree_adv,
        "accept_len_p50_lift": (
            round(tree_c["accept_len_p50"] - chain_c["accept_len_p50"], 2)
            if (tree_c["accept_len_p50"] is not None
                and chain_c["accept_len_p50"] is not None) else None),
        # TPOT p50 ratio vs the spec-off twin: tree should sit at or below
        # the chain's ratio (reported, not asserted — CPU timing is noise)
        "tpot_ratio_le_chain": (
            tree_c["tpot_p50_ratio"] <= chain_c["tpot_p50_ratio"]
            if (tree_c["tpot_p50_ratio"] is not None
                and chain_c["tpot_p50_ratio"] is not None) else None),
    }

    # ---- learned-vs-fixed tree sub-run (PR 20): the SAME contested twin,
    # learned per-depth widths (AdaptiveTree) vs the fixed WxD rectangle.
    # The learned controller prunes dead branches (draft FLOPs the fixed
    # rectangle burns for nothing), so tokens/s must not regress. 0.85
    # slack: CPU smoke timing is noisy; TPU runs separate cleanly.
    tree_l, _ = run_pair(alpha=contested_alpha, tree=tree_spec_s, mode="on",
                         learned=True)
    l_tps = tree_l["on"]["tokens_per_sec"]
    f_tps = tree_c["on"]["tokens_per_sec"]
    assert not f_tps or l_tps >= 0.85 * f_tps, (
        "learned tree shapes regressed tokens/s vs the fixed rectangle: "
        f"learned={l_tps} fixed={f_tps}")
    tree_block["learned"] = tree_l
    tree_block["fixed"] = tree_c
    tree_block["learned_tps_ratio"] = (round(l_tps / f_tps, 3)
                                       if f_tps else None)
    tree_block["learned_ge_fixed"] = bool(not f_tps or l_tps >= f_tps)
    learned_widths = (tree_l.get("tree") or {}).get("widths")

    # ---- fused-epilogue sub-run (PR 20): the aligned twin again, spec-on
    # engine forced through the fused sampling epilogue vs explicitly off.
    # run_pair's pre-clock parity gate doubles as the engine-level
    # fused-vs-legacy token-exactness proof; the greedy fused path skips
    # the legacy sampler's full-vocab sort, so TPOT must not regress
    # (1.2 noise guard on CPU smoke; the ≤1.0 verdict is reported).
    ep_on, _ = run_pair(alpha=1e-3, epilogue="on")
    ep_off, _ = run_pair(alpha=1e-3, epilogue="off")
    assert ep_on["fused_steps"] > 0, (
        "epilogue-on run never took the fused path: "
        f"{ep_on['epilogue_impl']}")
    assert ep_off["fused_steps"] == 0, "epilogue-off run took the fused path"
    ep_ratio = (round(ep_on["on"]["tpot_ms_p50"] /
                      ep_off["on"]["tpot_ms_p50"], 3)
                if ep_off["on"]["tpot_ms_p50"] else None)
    assert ep_ratio is None or ep_ratio <= 1.2, (
        "fused sampling epilogue regressed TPOT p50 vs the legacy sampler: "
        f"ratio={ep_ratio}")
    epilogue_block = {
        "impl": ep_on["epilogue_impl"],
        "on": ep_on["on"], "off": ep_off["on"],
        "fused_steps": ep_on["fused_steps"],
        "tpot_p50_ratio": ep_ratio,
        "tpot_le_off": ep_ratio is not None and ep_ratio <= 1.0,
    }
    tag = (f"bench-spec,L{layers},take{take},k{k},tree{tree_spec_s},"
           f"slots{slots},bs{block}")
    line = {
        "metric": f"serve_spec_tokens_per_sec[{tag}]",
        "value": aligned["on"]["tokens_per_sec"],
        "unit": "tokens/s",
        "vs_baseline": None,
        "platform": jax.devices()[0].platform,
        "cpu_fallback": not on_tpu,
        # provenance: which decode path (spec verify runs the multi-token
        # gather path even under --paged_kernel; the Pallas kernel is the
        # T=1 non-spec fast path) and which draft produced these numbers
        "decode_path": decode_path,
        "spec_mode": "auto",
        "spec_draft": f"take:{take}",
        "spec_tree": tree_spec_s,
        # PR 20 provenance: which sampler path produced these numbers and
        # the tree shape the learned controller settled on
        "sampling_epilogue": epilogue_block["impl"],
        "tree_shape": (",".join(str(w) for w in learned_widths)
                       if learned_widths else tree_spec_s),
        "spec": {"k": k, "target_layers": layers, "draft_layers": take,
                 "aligned": aligned, "adversarial": adversarial,
                 "tree": tree_block, "epilogue": epilogue_block},
    }
    if preflight is not None:
        line["preflight"] = preflight
    print(json.dumps(line), flush=True)


def child_replay(preflight=None):
    """DTX_BENCH_REPLAY=1: the trace-driven load-replay + chaos harness
    (datatunerx_tpu/loadgen/) against a 2-replica in-process fleet of REAL
    BatchedEngines behind a real Gateway, with a drain fired MID-STREAM
    (the chaos action waits for in-flight work) — judged by the SLO
    epilogue. Runs TWICE: with the KV session handoff on (drained
    sessions migrate; the run asserts ZERO dropped sessions and ZERO
    re-prefills via the engines' prefill-counter delta) and with it off
    plus an export-kill (today's reap-deadline behavior: sessions die
    mid-stream and fail over cold, re-prefilling — the counted baseline
    the handoff removes). The line carries both runs' numbers and the SLO
    verdict with any violated objective NAMED, which
    scripts/bench_job_summary.py lifts into the GH job summary. CPU
    numbers are smoke-only, like the serve bench."""
    import jax

    if os.environ.get("DTX_BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway
    from datatunerx_tpu.loadgen.chaos import ChaosInjector
    from datatunerx_tpu.loadgen.replay import (
        LocalClient,
        ReplayRunner,
        drain_when_busy,
        slo_epilogue,
    )
    from datatunerx_tpu.loadgen.workload import WorkloadModel, summarize
    from datatunerx_tpu.obs.slo import SLOEvaluator, default_slos
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    on_tpu = jax.default_backend() == "tpu"
    model = "tinyllama-1.1b" if on_tpu else "debug"
    max_seq = 1024 if on_tpu else 256
    n_requests = int(os.environ.get("DTX_BENCH_REPLAY_REQUESTS",
                                    "24" if on_tpu else "12"))
    rps = float(os.environ.get("DTX_BENCH_REPLAY_RPS", "8"))

    def one_run(handoff: bool):
        engines = [
            BatchedEngine(f"preset:{model}", template="vanilla",
                          max_seq_len=max_seq, slots=2, decode_chunk=4)
            for _ in range(2)  # shared program memo: second engine is cheap
        ]
        pool = ReplicaPool([InProcessReplica(f"replica-{i}", e)
                            for i, e in enumerate(engines)])
        gw = Gateway(pool, model_name=f"preset:{model}",
                     session_handoff=handoff)
        try:
            # tiny prompts: the replay measures the HARNESS + scheduler
            # under churn, not model quality; compile before the clock
            engines[0].generate(engines[0].tokenizer.encode("warm up"),
                                max_new_tokens=2)
            admits0 = sum(sum(e.prefill_stats.values()) for e in engines)
            wl = WorkloadModel(requests=n_requests, sessions=3, rps=rps,
                               seed=7, prompt_chars=40,
                               prompt_cap_chars=200,
                               output_tokens=24, output_cap_tokens=48)
            events = wl.generate()
            mid = events[-1]["t"] * 0.5

            def _drain(op):
                out = drain_when_busy(gw, op["replica"])
                if not handoff:
                    # today's reap-deadline kill: in-flight sessions on
                    # the drained replica die mid-stream and fail over
                    # on the cold (re-prefill) path. Loop briefly — a
                    # session still in its prefill isn't exportable yet.
                    killed, deadline = 0, time.monotonic() + 2.0
                    while killed == 0 and time.monotonic() < deadline:
                        killed = len(
                            engines[1].export_sessions()["sessions"])
                        if killed == 0:
                            time.sleep(0.02)
                    out["killed"] = killed
                return out

            chaos = ChaosInjector(
                [{"t": round(mid, 3), "op": "drain",
                  "replica": "replica-1"}],
                {"drain": _drain})
            runner = ReplayRunner(LocalClient(gw), max_inflight=8)
            evaluator = SLOEvaluator(runner.registry,
                                     default_slos("loadgen"))
            t0 = time.perf_counter()
            report = runner.run(events, chaos=chaos)
            wall = time.perf_counter() - t0
            verdict = slo_epilogue(evaluator, since_t=0.0,
                                   out=lambda s: print(s, file=sys.stderr))
            admissions = (sum(sum(e.prefill_stats.values())
                              for e in engines) - admits0)
            # each request cold-admits exactly once; anything beyond is a
            # session that re-prefilled after the drain
            re_prefills = max(0, admissions - report["requests"])
            return {
                "workload": summarize(events),
                "requests": report["requests"],
                "errors": report["errors"],
                "codes": report["codes"],
                "ttft_ms_p50": report["ttft_ms_p50"],
                "ttft_ms_p95": report["ttft_ms_p95"],
                "ttft_ms_p99": report["ttft_ms_p99"],
                "latency_ms_p99": report["latency_ms_p99"],
                "chaos": report.get("chaos", []),
                "handoff": gw.handoff_stats(),
                "admissions": admissions,
                "re_prefills": re_prefills,
                "slo_pass": verdict["pass"],
                "slo_violations": verdict["violations"],
                "wall_s": wall,
            }
        finally:
            gw.close()

    hot = one_run(handoff=True)
    # the drain-mid-stream acceptance assertions: handoff on = nothing
    # dropped, nothing re-prefilled
    assert hot["errors"] == 0, \
        f"handoff-on replay dropped sessions: {hot['codes']}"
    assert hot["re_prefills"] == 0, \
        f"handoff-on replay re-prefilled {hot['re_prefills']} session(s)"
    cold = one_run(handoff=False)

    line = {
        "metric": f"replay_requests_per_sec[{model},2replicas,drain]",
        "value": (round(hot["requests"] / hot["wall_s"], 2)
                  if hot["wall_s"] > 0 else 0.0),
        "unit": "req/s",
        "vs_baseline": None,
        "platform": jax.devices()[0].platform,
        "cpu_fallback": not on_tpu,
        "replay": {k: v for k, v in hot.items() if k != "wall_s"},
        "replay_cold": {
            "errors": cold["errors"],
            "codes": cold["codes"],
            "re_prefills": cold["re_prefills"],
            "handoff": cold["handoff"],
            "slo_pass": cold["slo_pass"],
        },
    }
    if preflight is not None:
        line["preflight"] = preflight
    print(json.dumps(line), flush=True)


def child_disagg(preflight=None):
    """DTX_BENCH_DISAGG=1: disaggregated-serving twin bench. The same
    mixed workload — short interactive requests plus long prompts sharing
    one long document preamble — runs against TWIN in-process fleets of
    REAL BatchedEngines at EQUAL chips:

    - **uniform**: two mixed replicas, role-blind least-busy routing
      (PR 15 behavior; no fleet plane).
    - **disagg**: one prefill specialist + one decode replica, the
      router's prompt-token threshold steering longs at the specialist,
      the fleet-shared prefix tier on, and (by default) the fleet
      handoff plane re-homing decode-ready sessions onto the decode
      replica mid-run.

    Before the clock starts, a token-parity gate (greedy AND fixed-seed
    sampled, engine-level; plus one greedy probe through each gateway)
    asserts the disagg twin's outputs byte-identical to the uniform twin
    — role routing, prefix sharing and handoff must be invisible in the
    tokens or the numbers are unreportable. The run then asserts the
    disaggregation claim at equal chips: TTFT p95 no worse AND tokens/s
    no worse than uniform, with zero errors on both twins. The win is
    structural — longs pay their shared-prefix prefill ONCE on the
    specialist instead of once per replica, and shorts on the decode
    replica never queue behind a long prefill. CPU numbers are
    smoke-only, like the serve bench."""
    import jax

    if os.environ.get("DTX_BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import threading

    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    on_tpu = jax.default_backend() == "tpu"
    model = "tinyllama-1.1b" if on_tpu else "debug"
    max_seq = 1024 if on_tpu else 256
    n_short = int(os.environ.get("DTX_BENCH_DISAGG_SHORT",
                                 "10" if on_tpu else "8"))
    n_long = int(os.environ.get("DTX_BENCH_DISAGG_LONG", "4"))
    short_new = 24 if on_tpu else 12
    long_new = 32 if on_tpu else 12
    handoff_on = os.environ.get("DTX_BENCH_DISAGG_HANDOFF", "1") != "0"

    def build(disagg: bool, threshold: int):
        from datatunerx_tpu.gateway.admission import AdmissionController

        engines = [
            BatchedEngine(f"preset:{model}", template="vanilla",
                          max_seq_len=max_seq, slots=2, decode_chunk=4,
                          # local prefix cache ON for BOTH twins (fair):
                          # the comparison is prefix LOCALITY via role
                          # routing, not cache-on vs cache-off
                          prefix_cache=4)
            for _ in range(2)  # shared program memo: 2nd engine is cheap
        ]
        roles = ["prefill", "decode"] if disagg else ["mixed", "mixed"]
        pool = ReplicaPool([
            InProcessReplica(f"replica-{i}", e, role=roles[i])
            for i, e in enumerate(engines)])
        # tokenizer-exact admission (both twins): the routing threshold
        # then compares true token counts, not the chars/4 heuristic
        tok = engines[0].tokenizer
        adm = AdmissionController(
            count_tokens=lambda s: len(tok.encode(s)))
        gw = Gateway(pool, model_name=f"preset:{model}", admission=adm,
                     prefill_threshold=threshold if disagg else 0,
                     fleet_prefix_bytes=(8 << 20) if disagg else 0,
                     fleet_handoff=disagg and handoff_on)
        return gw, engines

    def run_twin(gw):
        lock = threading.Lock()
        per_req = []

        def one(req, idx):
            t0 = time.perf_counter()
            ttft = None
            toks = 0
            err = None
            try:
                for _ in gw.chat_stream(dict(req),
                                        trace_id=f"disagg-{idx}"):
                    if ttft is None:
                        ttft = time.perf_counter() - t0
                    toks += 1
            except Exception as e:  # noqa: BLE001 — an error IS the data
                err = f"{type(e).__name__}: {e}"
            if ttft is None:
                # tiny presets can hit EOS before the first delta — the
                # queue+prefill wait is still the number being measured,
                # so fall back to end-to-end completion time
                ttft = time.perf_counter() - t0
            with lock:
                per_req.append((ttft, toks, err))

        # longs first (they are the work that must not block shorts),
        # shorts right behind — everything in flight together
        workload = long_reqs + short_reqs
        threads = []
        wall0 = time.perf_counter()
        for i, req in enumerate(workload):
            th = threading.Thread(target=one, args=(req, i), daemon=True)
            th.start()
            threads.append(th)
            time.sleep(0.01)
        for th in threads:
            th.join(timeout=600)
        wall = time.perf_counter() - wall0
        assert len(per_req) == len(workload) and \
            not any(th.is_alive() for th in threads), \
            "disagg workload: session(s) never terminated"
        ttfts = sorted(t * 1e3 for t, _, _ in per_req if t is not None)
        # LOGICAL tokens — each request's prompt plus its decoded deltas.
        # Identical prompt work is credited to both twins, so tokens/s is
        # a pure wall-clock comparison at equal work; the disagg twin's
        # skipped re-prefills (prefix extends on the specialist) show up
        # as the shorter wall, not as a smaller numerator
        tokens = prompt_toks_total + sum(n for _, n, _ in per_req)
        errors = [e for _, _, e in per_req if e]
        return {
            "requests": len(per_req), "errors": len(errors),
            "error_detail": errors[:3],
            "tokens": tokens,
            "tokens_per_sec": round(tokens / wall, 1) if wall > 0 else 0.0,
            "ttft_ms_p50": _pct(ttfts, 0.5),
            "ttft_ms_p95": _pct(ttfts, 0.95),
            "wall_s": round(wall, 3),
        }

    probe_req = {"messages": [{"role": "user", "content": "parity probe"}],
                 "max_tokens": 8}
    gw_u, eng_u = build(disagg=False, threshold=0)
    # size the shared preamble in MEASURED tokens (the debug preset's
    # tokenizer is near char-level): it must fit max_seq with decode
    # room, or the engine truncates it and the prefix is never shared.
    # The preamble rides in the USER turn — the vanilla template renders
    # only the final query, so a system turn would be dropped on the
    # floor and the longs would not actually be long
    tok = eng_u[0].tokenizer
    base = "clause and subclause policy detail. "
    # bucket math bounds the preamble: prepare_prompt pads plen to
    # DECODE_BUCKET (64) multiples and a prefix EXTEND appends a further
    # padded suffix bucket, so the warm entry's cursor + 64 must still
    # leave decode room under max_seq — 0.35*max_seq keeps the CPU
    # preset's warm plen at 128 of 256 (extend cursor 192, room 64)
    target = int(max_seq * (0.6 if on_tpu else 0.35))
    preamble = "You are a meticulous assistant. "
    while len(tok.encode(preamble + base)) < target:
        preamble += base
    long_reqs = [{"messages": [
        {"role": "user", "content": f"{preamble}\nsummarize item {i}."}],
        "max_tokens": long_new} for i in range(n_long)]
    short_reqs = [{"messages": [
        {"role": "user", "content": f"quick question {i}?"}],
        "max_tokens": short_new} for i in range(n_short)]
    # the prefix-cache win is only real if the warm prompt's tokens are a
    # STRICT prefix of every long's tokens (longest_prefix is a trie walk
    # over whole cached keys) — assert it, or a tokenizer merging across
    # the preamble/suffix boundary silently degrades extends to full
    # prefills and the bench measures nothing
    pre_ids = list(tok.encode(preamble))
    for r in long_reqs:
        ids = list(tok.encode(r["messages"][0]["content"]))
        assert len(ids) > len(pre_ids) and ids[:len(pre_ids)] == pre_ids, \
            "warm preamble does not token-prefix the long prompts"
    prompt_toks_total = sum(
        len(tok.encode(m["content"]))
        for r in long_reqs + short_reqs for m in r["messages"])
    threshold = int(os.environ.get(
        "DTX_BENCH_DISAGG_THRESHOLD", str(target // 2)))
    gw_d, eng_d = build(disagg=True, threshold=threshold)
    try:
        # pre-clock token-parity gate (engine level, greedy + seeded
        # sampled): the twins must be the same model before the clock
        # may compare them
        ids = eng_u[0].tokenizer.encode("a quick question about weather")
        for kw in ({}, {"temperature": 0.8, "top_p": 0.9, "seed": 11}):
            want = eng_u[0].generate(ids, max_new_tokens=12, **kw)
            got = eng_d[0].generate(ids, max_new_tokens=12, **kw)
            assert got == want, (
                f"disagg twin diverged from uniform (kw={kw}): "
                f"{got} != {want}")
        # gateway-level greedy probe: role routing must not change tokens
        want = gw_u.chat(dict(probe_req), trace_id="parity-u")
        got = gw_d.chat(dict(probe_req), trace_id="parity-d")
        assert got == want, (
            f"gateway routing changed tokens: {got!r} != {want!r}")
        if gw_d.fleet is not None:
            gw_d.fleet.start(0.05)
        # steady-state warm phase (both twins, pre-clock): the BARE
        # preamble has been seen before the measured burst, and its
        # cached entry strict-prefixes every long — the clocked
        # comparison is prefix LOCALITY (disagg: every long lands where
        # the prefix is hot and pays a suffix-only extend; uniform:
        # role-blind spread re-prefills the preamble per replica), not
        # first-ever-prefill cost
        warm = {"messages": [{"role": "user", "content": preamble}],
                "max_tokens": 4}
        gw_u.chat(dict(warm), trace_id="warm-u")
        gw_d.chat(dict(warm), trace_id="warm-d")
        uniform = run_twin(gw_u)
        disagg = run_twin(gw_d)
        fleet_stats = gw_d.fleet.stats() if gw_d.fleet is not None else {}
        role_routes = dict(getattr(gw_d.router, "role_routes", {}))
    finally:
        gw_u.close()
        gw_d.close()

    assert uniform["errors"] == 0 and disagg["errors"] == 0, (
        "disagg twin bench dropped requests: "
        f"uniform={uniform['error_detail']} "
        f"disagg={disagg['error_detail']}")
    assert disagg["ttft_ms_p95"] <= uniform["ttft_ms_p95"], (
        "disaggregation did NOT hold TTFT p95 at equal chips: "
        f"{disagg['ttft_ms_p95']}ms vs uniform {uniform['ttft_ms_p95']}ms")
    assert disagg["tokens_per_sec"] >= uniform["tokens_per_sec"], (
        "disaggregation did NOT hold tokens/s at equal chips: "
        f"{disagg['tokens_per_sec']} vs uniform "
        f"{uniform['tokens_per_sec']}")
    tag = f"{model},2replicas,thr{threshold}"
    line = {
        "metric": f"serve_disagg_tokens_per_sec[{tag}]",
        "value": disagg["tokens_per_sec"],
        "unit": "tok/s",
        "vs_baseline": round(disagg["tokens_per_sec"]
                             / max(uniform["tokens_per_sec"], 1e-9), 3),
        "platform": jax.devices()[0].platform,
        "cpu_fallback": not on_tpu,
        "disagg": {
            "parity_checked": True,
            "handoff_enabled": handoff_on,
            "threshold_tokens": threshold,
            "workload": {"long": n_long, "short": n_short},
            "uniform": uniform,
            "disaggregated": disagg,
            "fleet": fleet_stats,
            "role_routes": role_routes,
        },
    }
    if preflight is not None:
        line["preflight"] = preflight
    print(json.dumps(line), flush=True)


def child_tenant(preflight=None):
    """DTX_BENCH_TENANT=1: multi-tenant QoS twin bench. The same mixed
    two-tenant workload — a pinned interactive tenant (plat, one adapter,
    a TTFT objective) sharing the fleet with a 3x-heavier bulk tenant
    (batch, two adapters churning the pool, a KV-block quota) — runs
    against TWIN in-process fleets of REAL BatchedEngines at equal chips:

    - **off**: no tenant directory, no host tier (PR 16 behavior): the
      tenant tags ride the requests but price nothing, every adapter
      fights the same LRU, and every evict→reload pays the orbax read.
    - **on**: the tenancy plane (datatunerx_tpu/tenancy/): plat's adapter
      pinned against eviction, batch priced against its block quota at
      admission, and the host-RAM adapter tier catching evicted weights
      so reloads skip orbax.

    One replica per twin ON PURPOSE: with two replicas the router's
    residency-affinity would park each bulk adapter on its own replica
    and the pool would never churn — the single 2-slot pool (pinned
    adapter + 1 contested slot under 2 bulk adapters) makes the
    evict→reload cycle the bench exists to price deterministic. The line
    reports the pinned tenant's TTFT p95 on both twins plus the host
    tier's hit rate, and asserts: zero 5xx on both twins; the pinned
    adapter still resident after the churn; the churn actually evicted;
    and every re-load after the first came from host RAM (each adapter
    paid orbax AT MOST ONCE). CPU numbers are smoke-only, like the serve
    bench."""
    import tempfile

    import jax

    if os.environ.get("DTX_BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway
    from datatunerx_tpu.loadgen.replay import LocalClient, ReplayRunner
    from datatunerx_tpu.loadgen.workload import WorkloadModel, summarize
    from datatunerx_tpu.serving.adapters import make_adapter_checkpoint
    from datatunerx_tpu.serving.batched_engine import BatchedEngine

    on_tpu = jax.default_backend() == "tpu"
    model = "tinyllama-1.1b" if on_tpu else "debug"
    max_seq = 1024 if on_tpu else 256
    n_requests = int(os.environ.get("DTX_BENCH_TENANT_REQUESTS",
                                    "24" if on_tpu else "12"))
    rps = float(os.environ.get("DTX_BENCH_TENANT_RPS", "3"))

    tmp = tempfile.mkdtemp(prefix="dtx-tenant-bench-")
    cks = {name: make_adapter_checkpoint(
               os.path.join(tmp, name), f"preset:{model}",
               seed=i + 3, rank=4)
           for i, name in enumerate(("plat-a", "batch-a", "batch-b"))}
    tenants_cfg = {
        "plat": {"tier": "pinned", "adapters": ["plat-a"], "share": 4.0,
                 "ttft_p95_ms": 2000.0},
        "batch": {"tier": "bulk", "adapters": ["batch-a", "batch-b"],
                  "share": 1.0, "kv_block_quota": 24},
    }
    mix = {"plat": {"adapters": ["plat-a"], "weight": 1.0},
           "batch": {"adapters": ["batch-a", "batch-b"], "weight": 3.0}}

    def tenant_p95(tstats: dict, name: str):
        """→ (p95_ms, source): a tenant's TTFT p95, falling back to its
        latency p95 when no request streamed a delta — the tiny debug
        model can sample EOS as the first token, which leaves every
        ttft_ms None and would report a meaningless 0.0. Real models on
        TPU stream, so there the headline is true TTFT."""
        t = tstats.get(name) or {}
        if t.get("ttft_ms_p95"):
            return t["ttft_ms_p95"], "ttft"
        return t.get("latency_ms_p95") or 0.0, "latency"

    def one_run(qos: bool):
        # a roomy block pool: the bench prices the TENANT quota, not the
        # fleet-wide block gate (dense-parity default would shed everyone)
        eng = BatchedEngine(
            f"preset:{model}", template="vanilla", max_seq_len=max_seq,
            slots=2, decode_chunk=4, adapters=cks, adapter_pool=2,
            adapter_rank_max=8, kv_block_size=16, kv_blocks=256,
            tenants=tenants_cfg if qos else None,
            host_adapter_cache_mb=64.0 if qos else 0.0)
        pool = ReplicaPool([InProcessReplica("replica-0", eng)])
        gw = Gateway(pool, model_name=f"preset:{model}",
                     tenants=tenants_cfg if qos else None)
        try:
            # compile + warm OUTSIDE the clock, identically on both
            # twins: the base decode step, every adapter's first pool
            # insert, and one LoRA-apply step each pay one-time jit
            # compiles that would otherwise all land on whichever twin
            # runs first and swamp its latencies. plat-a loads LAST so
            # the pinned adapter starts resident on both twins.
            eng.generate(eng.tokenizer.encode("warm up"), max_new_tokens=2)
            for name in ("batch-a", "batch-b", "plat-a"):
                eng.load_adapter(name, cks[name], preload=True)
                eng.chat([{"role": "user", "content": "warm"}],
                         max_new_tokens=2, adapter=name)
            wl = WorkloadModel(requests=n_requests, sessions=3, rps=rps,
                               seed=11, prompt_chars=30,
                               prompt_cap_chars=120, output_tokens=8,
                               output_cap_tokens=16, base_every=0,
                               tenants=mix)
            # ...and one full UNTIMED replay of the exact workload: the
            # per-adapter warm chats are single-slot and short-prompt, so
            # the measured pass would still pay first-compiles for the
            # long multi-turn prefill buckets and two-slot concurrency —
            # ~1.5s each on CPU, all billed to whichever twin runs first
            ReplayRunner(LocalClient(gw), max_inflight=8).run(wl.generate())
            events = wl.generate()
            runner = ReplayRunner(LocalClient(gw), max_inflight=8)
            t0 = time.perf_counter()
            report = runner.run(events)
            wall = time.perf_counter() - t0
            occ = eng.adapter_occupancy() or {}
            host = (eng.adapter_registry.host_tier_stats()
                    if eng.adapter_registry is not None else None)
            hits = (host or {}).get("host_hits", 0)
            orbax = (host or {}).get("orbax_loads", 0)
            tstats = report.get("tenants") or {}
            plat_p95, plat_src = tenant_p95(tstats, "plat")
            batch_p95, _ = tenant_p95(tstats, "batch")
            return {
                "workload": summarize(events),
                "requests": report["requests"],
                "errors": report["errors"],
                "codes": report["codes"],
                "tenants": tstats,
                "plat_ttft_ms_p95": plat_p95,
                "plat_p95_source": plat_src,
                "batch_ttft_ms_p95": batch_p95,
                "pool_evictions": occ.get("evictions", 0),
                "pinned_resident_at_end":
                    "plat-a" in (occ.get("resident_adapters") or []),
                "host_tier": host,
                "host_hit_rate": (round(hits / max(hits + orbax, 1), 3)
                                  if host is not None else None),
                "wall_s": wall,
            }
        finally:
            gw.close()

    qos_on = one_run(qos=True)
    qos_off = one_run(qos=False)
    assert qos_on["errors"] == 0 and qos_off["errors"] == 0, (
        "tenant twin bench dropped requests: "
        f"on={qos_on['codes']} off={qos_off['codes']}")
    for run, label in ((qos_on, "on"), (qos_off, "off")):
        plat = (run["tenants"].get("plat") or {})
        assert plat.get("ok", 0) >= 1, (
            f"pinned tenant served nothing on the qos-{label} twin "
            f"({plat}) — its TTFT p95 is meaningless")
    on_plat = qos_on["tenants"].get("plat") or {}
    assert not on_plat.get("shed"), (
        "the tenancy twin shed pinned-tenant traffic: "
        f"{on_plat} — quota pricing leaked onto the wrong tenant")
    assert qos_on["pool_evictions"] >= 1, (
        "bulk adapter churn never evicted — the host-tier hit rate "
        "measures nothing")
    assert qos_on["pinned_resident_at_end"], (
        "the pinned tenant's adapter was evicted despite the pin tier")
    host = qos_on["host_tier"] or {}
    assert host.get("host_hits", 0) >= 1, (
        f"no evict→reload came from the host tier: {host}")
    assert host.get("orbax_loads", 0) <= len(cks), (
        "an adapter paid the orbax read twice despite the host tier: "
        f"{host}")

    tag = f"{model},1replica,pool2,3adapters"
    on_p95 = qos_on["plat_ttft_ms_p95"] or 0.0
    off_p95 = qos_off["plat_ttft_ms_p95"] or 0.0
    assert on_p95 > 0 and off_p95 > 0, (
        "pinned-tenant p95 degenerated to 0 despite the latency "
        f"fallback: on={qos_on['tenants']} off={qos_off['tenants']}")
    line = {
        "metric": f"tenant_pinned_ttft_p95_ms[{tag}]",
        "value": on_p95,
        "unit": "ms",
        "vs_baseline": round(on_p95 / max(off_p95, 1e-9), 3),
        "platform": jax.devices()[0].platform,
        "cpu_fallback": not on_tpu,
        "tenant": {
            "workload": qos_on["workload"],
            "host_hit_rate": qos_on["host_hit_rate"],
            "p95_source": qos_on["plat_p95_source"],
            "qos_on": {k: v for k, v in qos_on.items()
                       if k not in ("wall_s", "workload")},
            "qos_off": {k: v for k, v in qos_off.items()
                        if k not in ("wall_s", "workload")},
        },
    }
    if preflight is not None:
        line["preflight"] = preflight
    print(json.dumps(line), flush=True)


# ------------------------------------------------------------- orchestrator

# The probe reports each phase AS IT COMPLETES (one JSON line, flushed), so
# when the backend wedges the parent can read the partial stdout of the
# killed child and name the phase that hung — backend init, the first XLA
# compile, the first execution, or the first PALLAS (Mosaic) compile+run.
# That turns the ROADMAP "TPU hang since r03" line from a mystery into a
# diagnosis: if the plain-XLA phases pass but pallas_execute hangs, the
# Mosaic pipeline (which the paged-decode kernel rides) is the suspect —
# not the backend.
PREFLIGHT_PHASES = ("backend_init", "first_compile", "first_execute",
                    "pallas_execute")

_PREFLIGHT_CODE = """\
import json, os, time
t0 = time.perf_counter()
import jax
if os.environ.get("DTX_BENCH_FORCE_CPU"):
    jax.config.update("jax_platforms", "cpu")
dev = jax.devices()[0]
t1 = time.perf_counter()
print(json.dumps({"phase": "backend_init", "ms": round((t1 - t0) * 1e3, 1),
                  "platform": dev.platform}), flush=True)
import jax.numpy as jnp
x = jnp.ones((256, 256), jnp.float32)
compiled = jax.jit(lambda a: a @ a).lower(x).compile()
t2 = time.perf_counter()
print(json.dumps({"phase": "first_compile",
                  "ms": round((t2 - t1) * 1e3, 1)}), flush=True)
out = float(compiled(x)[0, 0])
t3 = time.perf_counter()
print(json.dumps({"phase": "first_execute", "ms": round((t3 - t2) * 1e3, 1),
                  "result": out}), flush=True)
# tiny Pallas kernel through the real Mosaic pipeline on TPU (interpret
# emulation elsewhere) — self-contained so the probe needs no repo import;
# engineered to reproduce the matmul phases' 256.0 check value
from jax.experimental import pallas as pl
def _k(a_ref, o_ref):
    o_ref[:] = a_ref[:] + a_ref[:]
a = jnp.full((128, 128), 128.0, jnp.float32)
pk = pl.pallas_call(_k, out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
                    interpret=dev.platform != "tpu")
out = float(pk(a)[0, 0])
t4 = time.perf_counter()
print(json.dumps({"phase": "pallas_execute", "ms": round((t4 - t3) * 1e3, 1),
                  "result": out}), flush=True)
"""


def _preflight_probe():
    """Probe the default device in a SUBPROCESS with per-phase timing,
    retrying over a window.

    The tunneled TPU backend wedges by hanging (not erroring), and once a
    process has initialized the wedged platform it cannot recover — so each
    probe must be isolated. The wedge is transient (VERDICT r2 weak #1), so
    one failed probe is not evidence: retry a few times before degrading.

    Returns a report dict written into the bench JSON: ``ok``, ``attempts``,
    ``phases_ms`` (per completed phase), ``platform``, and — on failure —
    ``timed_out_phase`` / ``failed_phase`` naming where the probe died.
    """
    report = {"ok": False, "attempts": 0, "phases_ms": {}, "platform": None,
              "timed_out_phase": None, "failed_phase": None}
    for attempt in range(PREFLIGHT_TRIES):
        report["attempts"] = attempt + 1
        timed_out = False
        try:
            p = subprocess.run(
                [sys.executable, "-c", _PREFLIGHT_CODE],
                timeout=PREFLIGHT_TIMEOUT_S, capture_output=True, text=True,
            )
            stdout = p.stdout or ""
        except subprocess.TimeoutExpired as e:
            timed_out = True
            stdout = e.stdout or b""
            if isinstance(stdout, bytes):
                stdout = stdout.decode("utf-8", "replace")
        phases, result = {}, None
        for ln in stdout.splitlines():
            try:
                obj = json.loads(ln)
            except ValueError:
                continue
            if isinstance(obj, dict) and "phase" in obj:
                phases[obj["phase"]] = obj.get("ms")
                report["platform"] = obj.get("platform",
                                             report["platform"])
                result = obj.get("result", result)
        report["phases_ms"] = phases
        if all(ph in phases for ph in PREFLIGHT_PHASES) and result == 256.0:
            report.update(ok=True, timed_out_phase=None, failed_phase=None)
            return report
        # the phase the child died in: the first that never reported done
        hung = next((ph for ph in PREFLIGHT_PHASES if ph not in phases),
                    PREFLIGHT_PHASES[-1])
        report["timed_out_phase" if timed_out else "failed_phase"] = hung
        done = [ph for ph in PREFLIGHT_PHASES if ph in phases]
        print(f"[bench] pre-flight attempt {attempt + 1}/{PREFLIGHT_TRIES}: "
              f"device {'hung' if timed_out else 'errored'} in phase "
              f"'{hung}' (completed: {', '.join(done) or 'none'})",
              file=sys.stderr)
        if attempt + 1 < PREFLIGHT_TRIES:
            time.sleep(PREFLIGHT_SLEEP_S)
    return report


def _run_child(argv, timeout_s, env_extra=None):
    """Run a bench child; return its parsed last JSON stdout line or None."""
    env = dict(os.environ)
    env.update(env_extra or {})
    try:
        p = subprocess.run(
            argv, timeout=timeout_s, capture_output=True, text=True,
            env=env, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        print(f"[bench] child {argv[1]} timed out after {timeout_s:.0f}s",
              file=sys.stderr)
        return None
    sys.stderr.write(p.stderr[-2000:])
    for line in reversed(p.stdout.strip().splitlines()):
        try:
            obj = json.loads(line)
            if isinstance(obj, dict) and "metric" in obj:
                return obj
        except ValueError:
            continue
    print(f"[bench] child {argv[1]} exited rc={p.returncode} with no "
          f"JSON line", file=sys.stderr)
    return None


def _tpu_evidence():
    """Headline of the committed dated TPU artifact, if one exists."""
    path = os.path.join(REPO, "BENCH_TPU.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
        head = doc.get("headline", {})
        return {
            "file": "BENCH_TPU.json",
            "timestamp": doc.get("timestamp"),
            "metric": head.get("metric"),
            "value": head.get("value"),
        }
    except Exception:  # noqa: BLE001 — evidence pointer is best-effort
        return None


def _persist_tpu_artifact(headline, secondary):
    from datetime import datetime, timezone

    doc = {
        "timestamp": datetime.now(timezone.utc).isoformat(timespec="seconds"),
        "hardware": "TPU v5e-1 (tunneled)",
        "headline": headline,
        "secondary": secondary,
        "config": {
            "tinyllama": "B8xT1024 bf16 LoRA r8 q/v, flash, remat=dots",
            "llama2_7b": "B4xT1024 nf4-base QLoRA r8 q/v, flash, remat=full",
        },
    }
    with open(os.path.join(REPO, "BENCH_TPU.json"), "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")


def main():
    t_start = time.monotonic()

    def remaining():
        return DEADLINE_S - (time.monotonic() - t_start)

    # the probe runs even forced-CPU (it probes the CPU backend then):
    # every bench line carries per-phase pre-flight timing, and a
    # cpu_fallback line names the phase the TPU died in
    preflight = _preflight_probe()

    def emit_cpu_fallback():
        # CPU smoke: explicitly marked; can never read as a TPU result.
        line = _run_child(
            [sys.executable, os.path.join(REPO, "bench.py"), "--child"],
            timeout_s=max(remaining() - 10, 60),
            env_extra={"DTX_BENCH_FORCE_CPU": "1"},
        )
        if line is None:
            line = {"metric": "bench_error", "value": 0,
                    "unit": "cpu smoke failed", "vs_baseline": None}
        line["cpu_fallback"] = True
        line["vs_baseline"] = None
        line["preflight"] = preflight
        ev = _tpu_evidence()
        if ev is not None:
            line["tpu_evidence"] = ev
        print(json.dumps(line), flush=True)

    forced_cpu = bool(os.environ.get("DTX_BENCH_FORCE_CPU"))
    on_tpu = (not forced_cpu and preflight["ok"]
              and preflight.get("platform") == "tpu")

    if not on_tpu:
        return emit_cpu_fallback()

    # --- TPU path: tinyllama (continuity) then 7B QLoRA (the north star) ---
    tiny = _run_child(
        [sys.executable, os.path.join(REPO, "bench.py"), "--child"],
        timeout_s=min(max(remaining() * 0.45, 120), 300),
    )
    if tiny is not None and "debug" in tiny.get("metric", ""):
        # the child fell back to CPU after a clean (non-hang) device failure
        # post-preflight: a smoke line must never be persisted as TPU evidence
        print("[bench] tinyllama child degraded to CPU despite preflight — "
              "dropping its line from the TPU artifact", file=sys.stderr)
        tiny = None

    seven = None
    if remaining() > 150:
        seven = _run_child(
            [sys.executable, os.path.join(REPO, "scripts", "bench_7b.py"),
             "--steps", os.environ.get("DTX_BENCH_7B_STEPS", "10")],
            timeout_s=remaining() - 20,
        )
        if seven is not None:
            # vs_baseline for the artifact = speedup over round-2's recorded
            # 709 tok/s/chip (bench_7b.py itself reports MFU there)
            seven = dict(seven)
            seven["mfu"] = seven.get("vs_baseline")
            seven["vs_baseline"] = round(
                float(seven["value"]) / ROUND2_7B_TOKS, 3)
    else:
        print("[bench] skipping 7B line: insufficient budget left "
              f"({remaining():.0f}s)", file=sys.stderr)

    headline = seven or tiny
    if headline is None:
        # the device passed preflight but every measurement child failed or
        # degraded — fall back to the marked CPU smoke so the artifact still
        # carries an honest line
        print("[bench] no TPU measurement landed; emitting marked CPU "
              "fallback", file=sys.stderr)
        return emit_cpu_fallback()
    secondary = tiny if headline is seven else None
    _persist_tpu_artifact(headline, secondary)
    out = dict(headline)
    if secondary is not None:
        out["secondary"] = secondary
    out["preflight"] = preflight
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    if os.environ.get("DTX_BENCH_REPLAY"):
        # replay mode: loadgen harness against an in-process fleet, with
        # the same per-phase pre-flight diagnosis on its line
        child_replay(preflight=_preflight_probe())
    elif os.environ.get("DTX_BENCH_DISAGG"):
        # disaggregated-serving twin bench (uniform vs role-split fleet
        # at equal chips) with the same per-phase pre-flight diagnosis
        child_disagg(preflight=_preflight_probe())
    elif os.environ.get("DTX_BENCH_TENANT"):
        # multi-tenant QoS twin bench (tenancy plane on vs off over the
        # same two-tenant mix) with the same pre-flight diagnosis
        child_tenant(preflight=_preflight_probe())
    elif os.environ.get("DTX_BENCH_SERVE_CAPACITY"):
        # KV-overcommit capacity twin bench (eager reserve vs overcommit
        # over one block budget) with the same pre-flight diagnosis
        child_serve_capacity(preflight=_preflight_probe())
    elif os.environ.get("DTX_BENCH_SERVE_SPEC"):
        # speculative-decoding twin-engine serve bench (spec-on vs spec-off,
        # aligned + adversarial) with the same pre-flight diagnosis
        child_serve_spec(preflight=_preflight_probe())
    elif os.environ.get("DTX_BENCH_SERVE"):
        # serve mode is its own entry (no orchestrator): probe first so the
        # serve line carries the same per-phase pre-flight diagnosis
        child_serve(preflight=_preflight_probe())
    elif "--child" in sys.argv:
        child_tinyllama()
    else:
        main()
