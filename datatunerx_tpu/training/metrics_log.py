"""Training metrics: jsonl logs + Prometheus remote-write.

Replaces the reference LogCallback + exporter (reference cmd/tuning/callback.py,
cmd/tuning/prometheus/metrics.py). Wire format kept: snappy-compressed protobuf
WriteRequest POSTed to ``{addr}/api/v1/write`` with the run UID as a label
(reference metrics.py:21-39), and jsonl mirrors under ``watch/`` (reference
callback.py:144-155).

Fixed reference bug (SURVEY.md §7.5): the reference encodes metric *values as
labels* with constant sample value 1 (metrics.py:60-74), which breaks PromQL
math. Here each metric is a real timeseries ``dtx_train_<name>{uid=...} value``.

Dependency-free wire encoding: a minimal protobuf writer and a literal-only
snappy block encoding (the snappy format allows all-literal streams; any
compliant decompressor accepts it).
"""

from __future__ import annotations

import json
import math
import os
import re
import struct
import time
import urllib.request
from typing import Dict, Optional

from datatunerx_tpu.obs.metrics import (
    Registry,
    sample_percentile,
    set_build_info,
)

# ------------------------------------------------------------------ protobuf

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field(tag: int, wire: int) -> bytes:
    return _varint((tag << 3) | wire)


def _len_delim(tag: int, payload: bytes) -> bytes:
    return _field(tag, 2) + _varint(len(payload)) + payload


def _label(name: str, value: str) -> bytes:
    return _len_delim(1, name.encode()) + _len_delim(2, value.encode())


def _sample(value: float, ts_ms: int) -> bytes:
    out = _field(1, 1) + struct.pack("<d", value)
    # sint64? Prometheus Sample.timestamp is int64 (not zigzag)
    out += _field(2, 0) + _varint(ts_ms & 0xFFFFFFFFFFFFFFFF)
    return out


def encode_write_request(
    metrics: Dict[str, float], labels: Dict[str, str], ts_ms: Optional[int] = None
) -> bytes:
    """Prometheus WriteRequest: one TimeSeries per metric."""
    ts_ms = ts_ms if ts_ms is not None else int(time.time() * 1000)
    body = b""
    for name, value in metrics.items():
        if value is None or (isinstance(value, float) and math.isnan(value)):
            continue
        series = _len_delim(1, _label("__name__", name))
        for k, v in sorted(labels.items()):
            series += _len_delim(1, _label(k, str(v)))
        series += _len_delim(2, _sample(float(value), ts_ms))
        body += _len_delim(1, series)
    return body


def snappy_compress_literal(data: bytes) -> bytes:
    """Snappy block format with literal-only elements (spec-valid, uncompacted)."""
    out = bytearray(_varint(len(data)))
    i = 0
    while i < len(data):
        chunk = data[i : i + 60]  # literal length <= 60 fits the tag byte
        out.append((len(chunk) - 1) << 2)  # tag 00 = literal
        out += chunk
        i += len(chunk)
    return bytes(out)


def push_remote_write(
    address: str,
    metrics: Dict[str, float],
    labels: Dict[str, str],
    timeout: float = 5.0,
) -> bool:
    """POST to {address}/api/v1/write (headers per reference metrics.py:29-34)."""
    payload = snappy_compress_literal(encode_write_request(metrics, labels))
    req = urllib.request.Request(
        address.rstrip("/") + "/api/v1/write",
        data=payload,
        headers={
            "Content-Encoding": "snappy",
            "Content-Type": "application/x-protobuf",
            "X-Prometheus-Remote-Write-Version": "0.1.0",
            "User-Agent": "datatunerx-tpu/0.1",
        },
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return 200 <= resp.status < 300
    except Exception:
        return False  # metrics export must never kill training


# ------------------------------------------------------------------ callback

class MetricsLogger:
    """Per-step logging: stdout + watch/*.jsonl + optional remote-write.

    jsonl field names mirror the reference (callback.py:103-138): loss, lr,
    epoch, percentage, current_steps, total_steps, elapsed_time, eta;
    eval: eval_loss, perplexity (+ generative rouge/bleu when scored).
    """

    def __init__(
        self,
        output_dir: str,
        total_steps: int,
        metrics_export_address: Optional[str] = None,
        uid: Optional[str] = None,
        registry: Optional[Registry] = None,
        prefetch_depth: Optional[int] = None,
    ):
        self.output_dir = output_dir
        self.total_steps = max(total_steps, 1)
        self.address = metrics_export_address
        self.uid = uid
        self.start = time.time()
        self.watch_dir = os.path.join(output_dir, "watch")
        os.makedirs(self.watch_dir, exist_ok=True)
        # prefetch-depth advisory (ROADMAP "input-path stragglers", first
        # slice): watch the logged pipe_step_wait_ms signal and, once per
        # run, suggest a deeper --prefetch_depth when its p95 says the step
        # loop is waiting on the input pipeline
        self.prefetch_depth = prefetch_depth
        self.prefetch_advisory: Optional[dict] = None
        self._pipe_waits: list = []
        self._advise_after = int(
            os.environ.get("DTX_PREFETCH_ADVISE_RECORDS", "20"))
        self._advise_ms = float(
            os.environ.get("DTX_PREFETCH_ADVISE_MS", "5.0"))
        # in-run retuning (the ROADMAP's "remaining piece"): when the live
        # HostPrefetcher is attached, the advisory doesn't just print — it
        # RESIZES the running prefetcher's bounded queue to the suggested
        # depth (DTX_PREFETCH_RETUNE=0 reverts to advise-only)
        self._prefetcher = None
        self._retune = os.environ.get("DTX_PREFETCH_RETUNE", "1") != "0"
        # Shared-registry mirror of the training plane (obs/metrics.py, PR 7):
        # every logged record re-states dtx_train_*/dtx_eval_* gauges —
        # including the pipeline-health signals pipe_step_wait_ms and
        # pipe_queue_depth (prefetch occupancy), the autotuning input ROADMAP
        # wants — and the exposition is written to watch/metrics.prom for
        # node-exporter-textfile-style scraping. Purely additive: jsonl,
        # stdout, and remote-write behavior are unchanged.
        self.registry = registry if registry is not None else Registry()
        self._expo_path = os.path.join(self.watch_dir, "metrics.prom")

    def _mirror(self, prefix: str, step: int, metrics: Dict[str, float]):
        set_build_info(self.registry, "training")
        labels = {"uid": self.uid} if self.uid else None
        self.registry.gauge(
            f"{prefix}_step", "Steps completed at the last logged record."
        ).set(step, labels)
        for k, v in metrics.items():
            f = _f(v)
            if math.isnan(f):
                continue
            # jsonl keys like "rouge-1" are not valid metric-name chars
            name = re.sub(r"[^a-zA-Z0-9_]", "_", f"{prefix}_{k}")
            self.registry.gauge(name).set(f, labels)
        # atomic replace: a scraper never reads a half-written exposition
        tmp = self._expo_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(self.registry.expose())
            os.replace(tmp, self._expo_path)
        except OSError:
            pass  # metrics export must never kill training

    def _common(self, step: int) -> Dict:
        elapsed = time.time() - self.start
        rate = elapsed / max(step, 1)
        return {
            "current_steps": step,
            "total_steps": self.total_steps,
            "percentage": round(step / self.total_steps * 100, 2),
            "elapsed_time": round(elapsed, 3),
            "eta": round(rate * max(self.total_steps - step, 0), 3),
        }

    def _write(self, filename: str, record: Dict):
        with open(os.path.join(self.watch_dir, filename), "a") as f:
            f.write(json.dumps(record) + "\n")

    def attach_prefetcher(self, prefetcher) -> None:
        """Hand the logger the LIVE HostPrefetcher (anything with a
        ``resize(depth)``) so the advisory can act in-run instead of only
        suggesting a flag for next time. Re-attach per epoch — the trainer
        rebuilds its prefetcher at epoch boundaries; the current effective
        depth carries over via ``effective_prefetch_depth``."""
        self._prefetcher = prefetcher

    def effective_prefetch_depth(self) -> Optional[int]:
        """The depth the NEXT prefetcher should be built with: the retuned
        value once retuning acted, else the configured depth."""
        adv = self.prefetch_advisory
        if adv and adv.get("retuned"):
            return adv["suggested_prefetch_depth"]
        return self.prefetch_depth

    def _maybe_advise_prefetch(self, metrics: Dict[str, float]):
        """Once per run: when dtx_train_pipe_step_wait_ms p95 over the last
        DTX_PREFETCH_ADVISE_RECORDS logged records exceeds
        DTX_PREFETCH_ADVISE_MS, log a suggested --prefetch_depth (double
        the current depth; 2 when the pipeline ran at an unknown depth)."""
        if self.prefetch_advisory is not None:
            return
        wait = metrics.get("pipe_step_wait_ms")
        if wait is None:
            return
        w = _f(wait)
        if math.isnan(w):
            return
        self._pipe_waits.append(w)
        if len(self._pipe_waits) < self._advise_after:
            return
        window = self._pipe_waits[-self._advise_after:]
        p95 = sample_percentile(window, 0.95)
        if p95 <= self._advise_ms:
            self._pipe_waits = self._pipe_waits[-self._advise_after:]
            return
        depth = self.prefetch_depth
        suggested = depth * 2 if depth else 2
        self.prefetch_advisory = {
            "pipe_step_wait_ms_p95": round(p95, 3),
            "threshold_ms": self._advise_ms,
            "records": len(window),
            "prefetch_depth": depth,
            "suggested_prefetch_depth": suggested,
            "retuned": False,
        }
        # act, don't just advise: resize the live prefetcher's queue to the
        # suggested depth (this epoch benefits; effective_prefetch_depth
        # carries it into the next epoch's prefetcher)
        retuned = False
        if self._retune and self._prefetcher is not None:
            try:
                self._prefetcher.resize(suggested)
                retuned = True
            except Exception:  # noqa: BLE001 — advisory must never kill a run
                pass
        self.prefetch_advisory["retuned"] = retuned
        self.registry.gauge(
            "dtx_train_prefetch_depth_suggested",
            "Advisory: a deeper --prefetch_depth would likely hide input "
            "stalls (0 = no advisory fired).").set(
            suggested, {"uid": self.uid} if self.uid else None)
        acted = (f"; retuned the live prefetcher to depth {suggested}"
                 if retuned else
                 f"; try --prefetch_depth {suggested}")
        print(
            f"[advice] input pipeline stalls: pipe_step_wait_ms p95="
            f"{p95:.1f}ms over the last {len(window)} records exceeds "
            f"{self._advise_ms:g}ms — the step loop is waiting on the "
            f"input path{acted}"
            + (f" (configured {depth})" if depth else ""),
            flush=True)

    def log_train(self, step: int, metrics: Dict[str, float]):
        rec = {**self._common(step), **{k: _f(v) for k, v in metrics.items()}}
        self._write("trainer_log.jsonl", rec)
        self._mirror("dtx_train", step, metrics)
        self._maybe_advise_prefetch(metrics)
        print(f"[train] {json.dumps(rec)}", flush=True)
        if self.address:
            push_remote_write(
                self.address,
                {f"dtx_train_{k}": _f(v) for k, v in metrics.items()},
                {"uid": self.uid or "", "phase": "train"},
            )

    def log_eval(self, step: int, metrics: Dict[str, float]):
        rec = {**self._common(step), **{k: _f(v) for k, v in metrics.items()}}
        self._write("eval_log.jsonl", rec)
        self._mirror("dtx_eval", step, metrics)
        print(f"[eval] {json.dumps(rec)}", flush=True)
        if self.address:
            push_remote_write(
                self.address,
                {f"dtx_eval_{k}": _f(v) for k, v in metrics.items()},
                {"uid": self.uid or "", "phase": "eval"},
            )


def _f(v) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return float("nan")
