"""Optimizer / LR-schedule factories keyed by the Hyperparameter CR enums.

The reference plumbs `Parameters.Optimizer` and `Parameters.Scheduler` strings
from the Hyperparameter CRD through the trainer CLI into HF TrainingArguments
(reference internal/controller/finetune/finetune_controller.go:478-479,
cmd/tuning/parser.py → Seq2SeqTrainingArguments). We accept the same names
(HF `lr_scheduler_type` / `optim` vocabularies) and map to optax.
"""

from __future__ import annotations

import optax

SCHEDULERS = (
    "linear", "cosine", "cosine_with_restarts", "polynomial",
    "constant", "constant_with_warmup",
)


def make_schedule(
    name: str,
    learning_rate: float,
    total_steps: int,
    warmup_ratio: float = 0.0,
    warmup_steps: int | None = None,
):
    name = (name or "linear").lower()
    if warmup_steps is None:
        warmup_steps = int(total_steps * warmup_ratio)
    decay_steps = max(total_steps - warmup_steps, 1)

    if name == "constant" and warmup_steps == 0:
        return optax.constant_schedule(learning_rate)
    if name in ("constant", "constant_with_warmup"):
        body = optax.constant_schedule(learning_rate)
    elif name == "linear":
        body = optax.linear_schedule(learning_rate, 0.0, decay_steps)
    elif name == "cosine":
        body = optax.cosine_decay_schedule(learning_rate, decay_steps)
    elif name == "cosine_with_restarts":
        # HF uses num_cycles=1 by default — equivalent to plain cosine; keep a
        # 2-cycle sawtooth to honor the "restarts" intent.
        cycle = max(decay_steps // 2, 1)
        body = optax.join_schedules(
            [optax.cosine_decay_schedule(learning_rate, cycle),
             optax.cosine_decay_schedule(learning_rate, cycle)],
            [cycle],
        )
    elif name == "polynomial":
        body = optax.polynomial_schedule(learning_rate, 0.0, power=1.0,
                                         transition_steps=decay_steps)
    else:
        raise ValueError(f"unknown scheduler {name!r}; choices {SCHEDULERS}")

    if warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, learning_rate, warmup_steps)
        return optax.join_schedules([warmup, body], [warmup_steps])
    return body


OPTIMIZERS = ("adamw", "adamw_torch", "adamw_hf", "adam", "sgd", "adafactor", "lion")


def make_optimizer(
    name: str,
    schedule,
    weight_decay: float = 0.0,
    max_grad_norm: float = 1.0,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
):
    name = (name or "adamw").lower()
    if name in ("adamw", "adamw_torch", "adamw_hf"):
        core = optax.adamw(schedule, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay)
    elif name == "adam":
        core = optax.adam(schedule, b1=b1, b2=b2, eps=eps)
    elif name == "sgd":
        core = optax.sgd(schedule, momentum=0.9)
    elif name == "adafactor":
        core = optax.adafactor(schedule)
    elif name == "lion":
        core = optax.lion(schedule, weight_decay=weight_decay)
    else:
        raise ValueError(f"unknown optimizer {name!r}; choices {OPTIMIZERS}")
    chain = []
    if max_grad_norm and max_grad_norm > 0:
        chain.append(optax.clip_by_global_norm(max_grad_norm))
    chain.append(core)
    return _dtype_stable(optax.chain(*chain))


def _dtype_stable(inner):
    """Pin every optimizer-state leaf to its init dtype across updates.

    optax moment updates compute in the GRADS dtype (fp32), so bf16 moments
    (full-param bf16 training inits bf16 moments) silently promote to fp32
    after one step: state no longer matches the Orbax restore template from
    ``init_state``, and train-step buffer donation stops aliasing (output
    dtypes differ from the donated inputs) — found by AOT buffer-assignment
    analysis (scripts/aot_certify.py r5). The cast-back happens AFTER the
    fp32 update math, so update precision is unchanged; only storage dtype
    is held stable."""
    import jax

    def init(params):
        return inner.init(params)

    def update(updates, state, params=None):
        new_updates, new_state = inner.update(updates, state, params)
        new_state = jax.tree_util.tree_map(
            lambda new, old: (new.astype(old.dtype)
                              if hasattr(old, "dtype") and hasattr(new, "astype")
                              and new.dtype != old.dtype else new),
            new_state, state)
        return new_updates, new_state

    return optax.GradientTransformation(init, update)
