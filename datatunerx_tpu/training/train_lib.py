"""Explicit jitted train/eval loop — the TPU-native replacement for HF Trainer +
Ray Train (reference cmd/tuning/train.py:138-305, trainer.py).

One `Trainer` covers the reference's finetuning types (reference
cmd/tuning/parser.py:121-124):

  lora   — optimizer state over the adapter tree only; base params frozen
  freeze — last `num_layer_trainable` layers of a chosen module group train
           (reference parser.py:125-137), expressed as a per-layer gradient mask
           over the stacked [L, ...] leaves
  full   — everything trains (GSPMD/fsdp shards params + opt state)
  none   — eval only

Gradient accumulation is exact: per-microbatch grads of the *sum* NLL are
accumulated in a `lax.scan` and divided by the total valid-token count, so the
result is identical to one big batch regardless of padding imbalance.
"""

from __future__ import annotations

import collections
import dataclasses
import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import struct

from datatunerx_tpu.models.config import ModelConfig
from datatunerx_tpu.models.llama import forward
from datatunerx_tpu.models.lora import (
    DEFAULT_TARGETS,
    init_lora_params,
    lora_scaling,
)
from datatunerx_tpu.data.prefetch import PlacedBatch
from datatunerx_tpu.parallel.sharding import place_batch, shard_tree
from datatunerx_tpu.training.loss import IGNORE_INDEX, causal_lm_loss
from datatunerx_tpu.training.optimizer import make_optimizer, make_schedule

_ATTN_MODULES = ("q_proj", "k_proj", "v_proj", "o_proj")
_MLP_MODULES = ("gate_proj", "up_proj", "down_proj")


def _freeze_selected_modules(train_cfg) -> tuple:
    """The trainable module group for freeze tuning (reference
    ``--name_module_trainable``, cmd/tuning/parser.py:125-137). Single source
    of truth for BOTH the optimizer labels and the gradient mask."""
    return (_MLP_MODULES if train_cfg.name_module_trainable in ("mlp",)
            else _ATTN_MODULES)


def _in_freeze_group(path, modules) -> bool:
    names = [getattr(p, "key", p) for p in path]
    return "layers" in names and any(m in names for m in modules)


@dataclasses.dataclass
class TrainConfig:
    finetuning_type: str = "lora"  # lora | freeze | full | none
    # LoRA (reference cmd/tuning/parser.py:138-164)
    lora_rank: int = 8
    lora_alpha: float = 32.0
    lora_dropout: float = 0.1
    lora_targets: Sequence[str] = DEFAULT_TARGETS
    # freeze tuning (reference cmd/tuning/parser.py:125-137)
    num_layer_trainable: int = 3
    name_module_trainable: str = "mlp"
    # optimization (Hyperparameter CR fields, SURVEY.md §2.3)
    learning_rate: float = 2e-4
    scheduler: str = "cosine"
    optimizer: str = "adamw"
    warmup_ratio: float = 0.0
    weight_decay: float = 0.0
    max_grad_norm: float = 1.0
    total_steps: int = 1000
    grad_accum: int = 1
    neftune_alpha: float = 0.0
    compute_dtype: Any = jnp.bfloat16
    # stage: sft (default) | dpo | rm | ppo. DPO is LoRA-only by design: the
    # frozen reference policy is the BASE model with the adapter switched off —
    # one weight tree serves both policies, no second 7B copy in HBM (the
    # reference reserves --stage dpo but has no runtime for it). RM (reference
    # cmd/tuning/parser.py:117-120 stage list, reward_model arg :74-76) trains
    # base+LoRA with a scalar value head scored at the last response token,
    # pairwise ranking loss -log σ(r_chosen − r_rejected). PPO (training/
    # ppo.py) adds the same v_head to the POLICY adapter (actor-critic shared
    # trunk) and reuses the adapter-off base as both reference policy and
    # reward-model trunk.
    stage: str = "sft"
    dpo_beta: float = 0.1

    def __post_init__(self):
        assert self.finetuning_type in ("lora", "freeze", "full", "none")
        assert self.stage in ("sft", "dpo", "rm", "ppo")
        if self.stage in ("dpo", "rm", "ppo") and self.finetuning_type != "lora":
            raise ValueError(
                f"stage {self.stage} requires finetuning_type lora (the "
                "frozen base serves as the DPO reference policy / keeps the "
                "reward model a cheap adapter; full/freeze would need a "
                "second copy of the weights)"
            )


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    lora: Any  # None unless finetuning_type == "lora"
    opt_state: Any
    rng: jax.Array


class Trainer:
    def __init__(
        self,
        model_cfg: ModelConfig,
        train_cfg: TrainConfig,
        mesh=None,
    ):
        self.model_cfg = model_cfg
        self.cfg = train_cfg
        self.mesh = mesh
        if model_cfg.attention_impl == "ring":
            from datatunerx_tpu.ops.ring_attention import set_ring_context

            set_ring_context(mesh)
        # Mosaic kernels can't be GSPMD-auto-partitioned: the flash call must
        # run under shard_map on a multi-device mesh. ALWAYS (re)set the
        # process-global context — a non-flash or mesh-less trainer must
        # clear a previous trainer's mesh, or later single-device flash
        # calls would wrap over a stale (possibly abstract/dead) mesh
        from datatunerx_tpu.ops.flash_attention import set_flash_context

        set_flash_context(mesh if model_cfg.attention_impl == "flash"
                          else None)
        self.schedule = make_schedule(
            train_cfg.scheduler,
            train_cfg.learning_rate,
            train_cfg.total_steps,
            train_cfg.warmup_ratio,
        )
        self.optimizer = make_optimizer(
            train_cfg.optimizer,
            self.schedule,
            weight_decay=train_cfg.weight_decay,
            max_grad_norm=train_cfg.max_grad_norm,
        )
        if train_cfg.finetuning_type == "freeze":
            # No optimizer moments for fully-frozen leaves (embed/norms/lm_head
            # and the unselected module group) — the memory win freeze tuning
            # exists for. Layer-window freezing within the selected stacked
            # leaves is handled by the gradient mask in _train_step_impl.
            import optax

            modules = _freeze_selected_modules(train_cfg)

            def labels(params):
                def lab(path, x):
                    return ("train" if _in_freeze_group(path, modules)
                            else "frozen")

                return jax.tree_util.tree_map_with_path(lab, params)

            self.optimizer = optax.multi_transform(
                {"train": self.optimizer, "frozen": optax.set_to_zero()}, labels
            )
        self.scaling = lora_scaling(train_cfg.lora_alpha, train_cfg.lora_rank)
        # Process-wide step-program memo: two Trainers built from equal
        # (model_cfg, train_cfg, mesh) produce identical programs, so they
        # share one jitted callable — and with it jax's in-memory executable
        # cache. Spinning up N trainers in one process (scoring controller
        # sweeps, the test suite's dozens of e2e runs) compiles each distinct
        # step program once instead of once per Trainer. This matters doubly
        # on jax 0.4.x, where the persistent compilation cache is unusable
        # (XLA:CPU executable serialization corrupts the heap — see
        # tests/conftest.py).
        key = _step_memo_key(model_cfg, train_cfg, mesh, type(self))
        cached = None if key is None else _STEP_MEMO.get(key)
        if cached is None:
            self._train_step = jax.jit(self._train_step_impl, donate_argnums=(0,))
            self._eval_step = jax.jit(self._eval_step_impl)
            if key is not None:
                _STEP_MEMO[key] = (self._train_step, self._eval_step)
                while len(_STEP_MEMO) > _STEP_MEMO_MAX:
                    _STEP_MEMO.popitem(last=False)
        else:
            _STEP_MEMO.move_to_end(key)
            self._train_step, self._eval_step = cached

    # ---------------------------------------------------------------- state
    def init_state(self, params, rng: jax.Array) -> TrainState:
        lora = None
        if self.cfg.finetuning_type == "lora":
            lora = init_lora_params(
                self.model_cfg,
                jax.random.fold_in(rng, 0x10AA),  # distinct stream from step rngs
                rank=self.cfg.lora_rank,
                targets=tuple(self.cfg.lora_targets),
            )
            if self.cfg.stage in ("rm", "ppo"):
                # scalar value head over the final-norm hidden state; rides in
                # the trainable tree (replicated by the sharding rules)
                lora["v_head"] = (
                    jax.random.normal(jax.random.fold_in(rng, 0x4EAD),
                                      (self.model_cfg.hidden_size,),
                                      jnp.float32)
                    / math.sqrt(self.model_cfg.hidden_size)
                )
        if self.mesh is not None:
            params = shard_tree(params, self.mesh)
            if lora is not None:
                lora = shard_tree(lora, self.mesh)
        trainable = self._trainable(params, lora)
        if self.cfg.finetuning_type == "none":
            opt_state = ()
        else:
            with self.mesh or _nullcontext():
                opt_state = jax.jit(self.optimizer.init)(trainable)
        step = jnp.zeros((), jnp.int32)
        if self.mesh is not None:
            # replicate scalars/keys on the mesh so checkpoint-restore templates
            # carry complete shardings (place_state then exists only for
            # cross-topology restores)
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(self.mesh, P())
            step = jax.device_put(step, repl)
            rng = jax.device_put(rng, repl)
        return TrainState(
            step=step,
            params=params,
            lora=lora,
            opt_state=opt_state,
            rng=rng,
        )

    def _trainable(self, params, lora):
        return lora if self.cfg.finetuning_type == "lora" else params

    def place_state(self, state: TrainState) -> TrainState:
        """Re-place a (restored) state onto this trainer's mesh shardings."""
        if self.mesh is None:
            return state
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(self.mesh, P())
        put = lambda t: None if t is None else shard_tree(t, self.mesh)  # noqa: E731
        return TrainState(
            step=jax.device_put(state.step, repl),
            params=put(state.params),
            lora=put(state.lora),
            opt_state=put(state.opt_state),
            rng=jax.device_put(state.rng, repl),
        )

    def _freeze_mask(self, params):
        """Per-leaf multiplicative masks for freeze tuning."""
        L = self.model_cfg.num_layers
        n = self.cfg.num_layer_trainable
        modules = _freeze_selected_modules(self.cfg)
        layer_ok = (jnp.arange(L) >= L - n).astype(jnp.float32)

        def mask_for(path, x):
            if _in_freeze_group(path, modules):
                return layer_ok.reshape((L,) + (1,) * (x.ndim - 1))
            return jnp.zeros((), jnp.float32)

        return jax.tree_util.tree_map_with_path(mask_for, params)

    # ----------------------------------------------------------------- loss
    def _sequence_logps(self, params, lora, ids, labels, rng, train: bool):
        """Per-sequence sum of response-token log-probs ([B]); response
        positions are where the (shifted) label is not IGNORE_INDEX."""
        logits, _ = forward(
            params, ids, self.model_cfg,
            lora=(lora, self.scaling) if lora is not None else None,
            compute_dtype=self.cfg.compute_dtype,
            lora_dropout=self.cfg.lora_dropout if (train and lora is not None) else 0.0,
            dropout_rng=rng if (train and lora is not None) else None,
        )
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        tgt = ids[:, 1:]
        ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        mask = (labels[:, 1:] != IGNORE_INDEX).astype(jnp.float32)
        return jnp.sum(ll * mask, axis=-1)

    def _dpo_loss(self, trainable, state: TrainState, batch, rng, train: bool):
        """DPO (Rafailov et al. 2023): -log σ(β[(π_c − ref_c) − (π_r − ref_r)]).
        Policy = base + adapter; reference = same base, adapter OFF
        (stop-gradient) — both sides in the same program, chosen and rejected
        concatenated so each policy is ONE forward."""
        ids = jnp.concatenate([batch["chosen_ids"], batch["rejected_ids"]], 0)
        labels = jnp.concatenate([batch["chosen_labels"],
                                  batch["rejected_labels"]], 0)
        pol = self._sequence_logps(state.params, trainable, ids, labels, rng, train)
        ref = jax.lax.stop_gradient(
            self._sequence_logps(state.params, None, ids, labels, None, False)
        )
        B = batch["chosen_ids"].shape[0]
        margin = (pol[:B] - ref[:B]) - (pol[B:] - ref[B:])
        loss = -jax.nn.log_sigmoid(self.cfg.dpo_beta * margin)
        # padding pairs (all-IGNORE labels, from eval tail padding) would
        # each contribute ln2: mask them out of sum AND count
        valid = jnp.any(batch["chosen_labels"][:, 1:] != IGNORE_INDEX,
                        axis=-1).astype(jnp.float32)
        # (sum, count) contract shared with the token-NLL path: count = pairs
        return jnp.sum(loss * valid), jnp.sum(valid).astype(jnp.int32)

    def _rm_loss(self, trainable, state: TrainState, batch, rng, train: bool):
        """Pairwise reward-model loss: -log σ(r_chosen − r_rejected), reward =
        v_head · hidden at each sequence's LAST response token (where the
        label stops being IGNORE). Chosen/rejected share one forward."""
        ids = jnp.concatenate([batch["chosen_ids"], batch["rejected_ids"]], 0)
        labels = jnp.concatenate([batch["chosen_labels"],
                                  batch["rejected_labels"]], 0)
        _, _, hidden = forward(
            state.params, ids, self.model_cfg,
            lora=(trainable, self.scaling),
            compute_dtype=self.cfg.compute_dtype,
            lora_dropout=self.cfg.lora_dropout if train else 0.0,
            dropout_rng=rng if train else None,
            return_hidden=True,
            skip_logits=True,  # reward = v_head · hidden; no vocab projection
        )
        resp = labels != IGNORE_INDEX  # [2B, T]
        T = ids.shape[1]
        last = jnp.argmax(
            jnp.where(resp, jnp.arange(T, dtype=jnp.int32)[None, :], -1), axis=1
        )  # [2B] index of last response token (0 for all-pad rows)
        h_last = jnp.take_along_axis(
            hidden, last[:, None, None].astype(jnp.int32), axis=1
        )[:, 0].astype(jnp.float32)  # [2B, D]
        rewards = h_last @ trainable["v_head"].astype(jnp.float32)  # [2B]
        B = batch["chosen_ids"].shape[0]
        loss = -jax.nn.log_sigmoid(rewards[:B] - rewards[B:])
        valid = jnp.any(batch["chosen_labels"][:, 1:] != IGNORE_INDEX,
                        axis=-1).astype(jnp.float32)  # mask eval-tail pad pairs
        return jnp.sum(loss * valid), jnp.sum(valid).astype(jnp.int32)

    def _forward_loss(self, trainable, state: TrainState, batch, rng, train: bool):
        if self.cfg.stage == "dpo":
            return self._dpo_loss(trainable, state, batch, rng, train)
        if self.cfg.stage == "rm":
            return self._rm_loss(trainable, state, batch, rng, train)
        if self.cfg.finetuning_type == "lora":
            params, lora = state.params, trainable
        else:
            params, lora = trainable, None
        logits, _ = forward(
            params,
            batch["input_ids"],
            self.model_cfg,
            attention_mask=batch.get("attention_mask"),
            segment_ids=batch.get("segment_ids"),
            positions=batch.get("positions"),
            lora=(lora, self.scaling) if lora is not None else None,
            compute_dtype=self.cfg.compute_dtype,
            lora_dropout=self.cfg.lora_dropout if train else 0.0,
            dropout_rng=rng if train else None,
            neftune_alpha=self.cfg.neftune_alpha if train else 0.0,
        )
        return causal_lm_loss(logits, batch["labels"])

    # ------------------------------------------------------------ train step
    def _train_step_impl(self, state: TrainState, batch):
        """batch leaves: [A, mb, T] when grad_accum > 1 else [B, T]."""
        cfg = self.cfg
        rng = jax.random.fold_in(jax.random.fold_in(state.rng, 0x57E9), state.step)
        trainable = self._trainable(state.params, state.lora)

        def sum_nll(tr, mb, r):
            s, n = self._forward_loss(tr, state, mb, r, train=True)
            return s, n

        vgrad = jax.value_and_grad(sum_nll, has_aux=True)

        if cfg.grad_accum > 1:
            def micro(carry, xs):
                g_acc, s_acc, n_acc = carry
                mb, i = xs
                (s, n), g = vgrad(trainable, mb, jax.random.fold_in(rng, i))
                return (
                    jax.tree_util.tree_map(jnp.add, g_acc, g),
                    s_acc + s,
                    n_acc + n,
                ), None

            zeros = jax.tree_util.tree_map(jnp.zeros_like, trainable)
            A = jax.tree_util.tree_leaves(batch)[0].shape[0]
            (grads, total_nll, total_n), _ = jax.lax.scan(
                micro,
                (zeros, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
                (batch, jnp.arange(A)),
            )
        else:
            (total_nll, total_n), grads = vgrad(trainable, batch, rng)

        denom = jnp.maximum(total_n, 1).astype(jnp.float32)
        grads = jax.tree_util.tree_map(lambda g: g / denom, grads)

        if cfg.finetuning_type == "freeze":
            mask = self._freeze_mask(trainable)
            grads = jax.tree_util.tree_map(jnp.multiply, grads, mask)

        updates, opt_state = self.optimizer.update(grads, state.opt_state, trainable)
        if cfg.finetuning_type == "freeze":
            updates = jax.tree_util.tree_map(jnp.multiply, updates, mask)
        # apply in the update dtype, then cast back to the param dtype: a bare
        # jnp.add promotes bf16 params against fp32 updates, so one full-param
        # step silently doubled the whole state (and broke train-step buffer
        # donation, since output dtypes no longer matched the donated inputs)
        # — caught by AOT buffer-assignment analysis, scripts/aot_certify.py
        new_trainable = jax.tree_util.tree_map(
            lambda p, u: (p + u).astype(p.dtype), trainable, updates)

        grad_norm = optax_global_norm(grads)
        metrics = {
            "loss": total_nll / denom,
            "lr": self.schedule(state.step),
            "grad_norm": grad_norm,
            "tokens": total_n,
        }
        if cfg.finetuning_type == "lora":
            new_state = state.replace(
                step=state.step + 1, lora=new_trainable, opt_state=opt_state
            )
        else:
            new_state = state.replace(
                step=state.step + 1, params=new_trainable, opt_state=opt_state
            )
        return new_state, metrics

    def _eval_step_impl(self, state: TrainState, batch):
        trainable = self._trainable(state.params, state.lora)
        s, n = self._forward_loss(trainable, state, batch, None, train=False)
        return {"sum_nll": s, "tokens": n}

    # ------------------------------------------------------------- public API
    def train_step(self, state: TrainState, batch):
        """Accepts host batches (placed inline) or ``PlacedBatch`` objects a
        DevicePrefetcher already put on the mesh (data/prefetch.py)."""
        batch = self._put_batch(batch, accum=self.cfg.grad_accum > 1)
        return self._train_step(state, batch)

    def eval_step(self, state: TrainState, batch):
        batch = self._put_batch(batch)
        return self._eval_step(state, batch)

    def _put_batch(self, batch, accum: bool = False):
        if isinstance(batch, PlacedBatch):
            # already on the mesh via the pipelined path — placing again would
            # misread device arrays as process-local slices on multi-host
            return dict(batch)
        return place_batch(batch, self.mesh, accum=accum)

    def evaluate(self, state: TrainState, batches) -> dict:
        """Aggregate eval: mean loss + perplexity = exp(loss) (reference
        cmd/tuning/trainer.py:324-327)."""
        tot_s, tot_n = 0.0, 0
        for b in batches:
            m = self.eval_step(state, b)
            tot_s += float(m["sum_nll"])
            tot_n += int(m["tokens"])
        loss = tot_s / max(tot_n, 1)
        import math

        return {"eval_loss": loss, "perplexity": math.exp(min(loss, 80.0)), "eval_tokens": tot_n}


# Bounded LRU: each entry pins a Trainer closure + its compiled executables,
# so an unbounded dict would leak across a long-lived controller sweeping
# many distinct configs (each trial would add, never release). 16 covers any
# realistic set of concurrently-live configs; evicted entries free their
# executables once the owning Trainers are gone.
_STEP_MEMO: collections.OrderedDict = collections.OrderedDict()
_STEP_MEMO_MAX = 16


def _step_memo_key(model_cfg, train_cfg, mesh, cls):
    """Hashable identity of the compiled step program, or None when identity
    can't be established (unhashable/exotic field values → compile fresh).
    dataclass reprs cover every field deterministically; the mesh enters by
    axis layout + device ids (devices are process singletons in jax); the
    concrete Trainer class guards subclasses that override step impls."""
    try:
        mesh_key = None
        if mesh is not None:
            mesh_key = (
                tuple(mesh.shape.items()),
                tuple(d.id for d in mesh.devices.flat),
            )
        return (cls.__qualname__, repr(model_cfg), repr(train_cfg), mesh_key)
    except Exception:  # noqa: BLE001 — memoization is best-effort
        return None


def optax_global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros(())
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False
