from datatunerx_tpu.training.loss import causal_lm_loss, IGNORE_INDEX
from datatunerx_tpu.training.optimizer import make_optimizer, make_schedule
from datatunerx_tpu.training.train_lib import TrainState, Trainer, TrainConfig

__all__ = [
    "causal_lm_loss",
    "IGNORE_INDEX",
    "make_optimizer",
    "make_schedule",
    "TrainState",
    "Trainer",
    "TrainConfig",
]
