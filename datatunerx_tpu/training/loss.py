"""Causal-LM loss with prompt masking.

Matches HF Trainer semantics the reference relies on (reference
cmd/tuning/train.py:73-117 builds labels with IGNORE_INDEX over the prompt;
HF shifts internally): loss at position t predicts token t+1, ignoring -100.
Perplexity = exp(eval_loss) (reference cmd/tuning/trainer.py:324-327).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

IGNORE_INDEX = -100


def causal_lm_loss(
    logits: jnp.ndarray,  # [B, T, V] float32
    labels: jnp.ndarray,  # [B, T] int32 with IGNORE_INDEX at masked positions
):
    """Returns (sum_loss, n_valid_tokens). Mean = sum/n; callers combine across
    microbatches/devices by summing both (so gradient accumulation is exact)."""
    logits = logits[:, :-1].astype(jnp.float32)
    labels = labels[:, 1:]
    valid = labels != IGNORE_INDEX
    safe = jnp.where(valid, labels, 0)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    tok = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, logz - tok, 0.0)
    return nll.sum(), valid.sum()
