"""In-training greedy generation for generative eval.

Parity with the reference's generative eval path (reference
cmd/tuning/trainer.py:29-172 GenEvalSeq2SeqTrainer: generate on the eval set
with left-padding, strip the prompt, score rouge-1/2/l + bleu-4, and
``save_predictions`` → generated_predictions.jsonl). TPU-native: KV-cache
greedy decode with prompt lengths bucketed to limit recompilation; adapters
applied unmerged via the forward's lora hook.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp

from datatunerx_tpu.models.config import ModelConfig
from datatunerx_tpu.models.llama import forward, init_cache
from datatunerx_tpu.scoring.metrics import generation_scores


def greedy_generate(
    params,
    cfg: ModelConfig,
    tokenizer,
    prompt_ids: List[int],
    *,
    lora: Optional[tuple] = None,
    max_new_tokens: int = 64,
    stop_ids=None,
) -> List[int]:
    import numbers

    from datatunerx_tpu.utils.decoding import prepare_prompt

    stop_ids = {int(s) for s in (stop_ids or set())
                if isinstance(s, numbers.Integral)}
    stop_ids.add(tokenizer.eos_token_id)
    # left-pad (reference uses left padding for generation, trainer.py:76-97);
    # pads are attention-masked and real tokens keep rope positions
    # 0..len(prompt)-1 (cache slot != position handled by the cache's per-slot
    # position record, models/llama.py)
    ids, mask, positions, padded_len, n_prompt, max_new_tokens, buf = prepare_prompt(
        prompt_ids, tokenizer.eos_token_id, cfg.max_seq_len, max_new_tokens,
    )
    cache = init_cache(cfg, 1, padded_len + buf, dtype=jnp.bfloat16)
    logits, cache = forward(
        params, jnp.asarray([ids], jnp.int32), cfg,
        positions=jnp.asarray([positions], jnp.int32),
        attention_mask=jnp.asarray([mask], jnp.int32), cache=cache, lora=lora,
        compute_dtype=jnp.bfloat16,
    )
    out: List[int] = []
    nxt = int(jnp.argmax(logits[0, -1]))
    pos = n_prompt
    for _ in range(max_new_tokens):
        if nxt in stop_ids:
            break
        out.append(nxt)
        logits, cache = forward(
            params, jnp.asarray([[nxt]], jnp.int32), cfg,
            positions=jnp.asarray([[pos]], jnp.int32), cache=cache, lora=lora,
            compute_dtype=jnp.bfloat16,
        )
        nxt = int(jnp.argmax(logits[0, -1]))
        pos += 1
    return out


def generative_eval(
    params,
    cfg: ModelConfig,
    tokenizer,
    template,
    records: List[Dict],
    output_dir: str,
    *,
    lora: Optional[tuple] = None,
    max_new_tokens: int = 64,
    max_examples: int = 32,
    columns: Optional[Dict[str, str]] = None,
) -> Dict[str, float]:
    """Generate for up to `max_examples` eval records; write
    generated_predictions.jsonl (reference trainer.py:144-172 contract) and
    return averaged rouge/bleu (reference callback.py:103-138 field names)."""
    from datatunerx_tpu.data.preprocess import map_columns

    stop_ids = {tokenizer.convert_tokens_to_ids(w) for w in template.stop_words}
    totals = {"rouge-1": 0.0, "rouge-2": 0.0, "rouge-l": 0.0, "bleu-4": 0.0}
    rows = []
    n = 0
    for rec in records[:max_examples]:
        rec = map_columns(rec, columns)
        query, label = rec.get("instruction"), rec.get("response")
        if not (isinstance(query, str) and isinstance(label, str)
                and query and label):
            continue
        prompt_ids, _ = template.encode_oneturn(
            tokenizer, query, "", rec.get("history"), rec.get("system"))
        out_ids = greedy_generate(
            params, cfg, tokenizer, prompt_ids, lora=lora,
            max_new_tokens=max_new_tokens, stop_ids=stop_ids,
        )
        predict = tokenizer.decode(out_ids, skip_special_tokens=True)
        scores = generation_scores(predict, label)
        for k in totals:
            totals[k] += scores[k]
        n += 1
        rows.append({"prompt": query, "label": label, "predict": predict})

    os.makedirs(output_dir, exist_ok=True)
    with open(os.path.join(output_dir, "generated_predictions.jsonl"), "w") as f:
        for row in rows:
            f.write(json.dumps(row, ensure_ascii=False) + "\n")
    if n == 0:
        return {}
    return {k: v / n for k, v in totals.items()}
