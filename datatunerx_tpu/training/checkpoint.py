"""Checkpointing: periodic Orbax saves, resume, and the completion manifest.

The reference only captures a final checkpoint (save_strategy="no",
reference cmd/tuning/train.py:199,300-305) and plumbs its path back by writing
``/home/ray/checkpoint_path`` on the head pod, which the Go controller scrapes
via pod-exec ``cat`` (reference internal/controller/finetune/
finetune_controller.go:278-305). SURVEY.md §5.4 calls for better:

- periodic Orbax saves every N steps + resume-on-restart (elasticity the
  reference lacks),
- a **completion manifest** JSON written to a deterministic key under
  ``storage_path`` (checkpoint URI + final metrics) that the controller reads
  from object storage — no pod-exec,
- a local ``checkpoint_path`` file kept for drop-in compatibility with the
  reference's contract.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import jax

MANIFEST_NAME = "manifest.json"
LEGACY_PATH_FILE = "checkpoint_path"  # reference train.py:383-389 contract


class CheckpointManager:
    """Thin wrapper over orbax CheckpointManager for TrainState pytrees."""

    def __init__(
        self,
        directory: str,
        save_interval_steps: int = 0,
        max_to_keep: int = 3,
    ):
        import orbax.checkpoint as ocp

        from datatunerx_tpu.utils import storage

        if storage.is_uri(directory):
            # object-store checkpoint dir (gs://…): tensorstore handles the
            # scheme natively, no local mkdir (SURVEY.md §5.4 async-to-GCS)
            self.directory = directory
        else:
            self.directory = os.path.abspath(directory)
            os.makedirs(self.directory, exist_ok=True)
        self.save_interval_steps = save_interval_steps
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                # periodic saves overlap the step loop; barriers only on the
                # final save / restore / close (at 7B a synchronous save
                # stalls training for the full serialization time)
                enable_async_checkpointing=True,
            ),
        )

    def maybe_save(self, state, step: int, force: bool = False) -> bool:
        due = force or (
            self.save_interval_steps > 0 and step > 0
            and step % self.save_interval_steps == 0
        )
        if not due:
            return False
        import orbax.checkpoint as ocp

        self._mngr.save(step, args=ocp.args.StandardSave(state))
        if force:
            # the final save gates the completion manifest: anything reading
            # the manifest may immediately load the checkpoint
            self._mngr.wait_until_finished()
        return True

    def wait(self):
        self._mngr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self) -> list:
        """Every saved step, ascending — the continuous-scoring watcher
        (experiment/watcher.py) lists a job's periodic eval checkpoints
        through this."""
        return sorted(self._mngr.all_steps())

    def restore(self, state_template, step: Optional[int] = None):
        """Restore into the structure/shardings of `state_template`."""
        import orbax.checkpoint as ocp

        self._mngr.wait_until_finished()  # in-flight async saves must land
        step = step if step is not None else self.latest_step()
        if step is None:
            return None, None
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape")
            else x,
            state_template,
        )
        restored = self._mngr.restore(step, args=ocp.args.StandardRestore(abstract))
        return restored, step

    def close(self):
        self._mngr.close()


def restore_raw_state(mngr, step):
    """Restore a checkpoint WITHOUT a target template, across orbax versions.

    Newer orbax (≥0.5 composite-handler era) refuses a bare
    ``mngr.restore(step)`` for StandardSave checkpoints (KeyError asking for
    CheckpointArgs) — it needs an explicit ``StandardRestore()``; versions
    predating the args API don't have ``ocp.args`` at all. Serving loads
    adapter/full checkpoints without a state template (the tree shape IS the
    information being loaded), so both forms are tried."""
    import orbax.checkpoint as ocp

    args_cls = getattr(getattr(ocp, "args", None), "StandardRestore", None)
    if args_cls is not None:
        try:
            return mngr.restore(step, args=args_cls())
        except (TypeError, ValueError, KeyError):
            pass
    return mngr.restore(step)


def write_manifest(
    storage_path: str,
    run_name: str,
    checkpoint_uri: str,
    metrics: Optional[dict] = None,
    extra: Optional[dict] = None,
) -> str:
    """Write the completion manifest at the deterministic key
    ``<storage_path>/<run_name>/manifest.json`` and the legacy path file.
    ``storage_path`` may be a local path or an object-store URI — the
    controller reads the same key (no pod-exec, SURVEY.md §5.4)."""
    from datatunerx_tpu.utils import storage

    run_dir = storage.join(storage_path, run_name)
    storage.makedirs(run_dir)
    manifest = {
        "run": run_name,
        "checkpoint": checkpoint_uri,
        "finished_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "metrics": metrics or {},
    }
    if extra:
        manifest.update(extra)
    path = storage.join(run_dir, MANIFEST_NAME)
    storage.write_text(path, json.dumps(manifest, indent=1, sort_keys=True))
    storage.write_text(storage.join(run_dir, LEGACY_PATH_FILE), checkpoint_uri)
    return path


def read_manifest(storage_path: str, run_name: str) -> Optional[dict]:
    from datatunerx_tpu.utils import storage

    path = storage.join(storage_path, run_name, MANIFEST_NAME)
    if not storage.exists(path):
        return None
    return json.loads(storage.read_text(path))


def export_merged_model(params, cfg, export_dir: str, lora=None, scaling: float = 1.0) -> str:
    """Export (optionally LoRA-merged) weights as an HF-layout .npz plus config
    (reference ``--export_dir``, cmd/tuning/parser.py:88-91)."""
    import numpy as np

    from datatunerx_tpu.models.lora import merge_lora
    from datatunerx_tpu.utils.hf_convert import export_hf_state_dict

    if lora is not None:
        params = merge_lora(params, lora, scaling)
    os.makedirs(export_dir, exist_ok=True)
    sd = export_hf_state_dict(params, cfg)
    if lora is not None and "v_head" in lora:
        # reward models (stage rm) carry a scalar value head the HF layout
        # has no slot for; exported under its own key
        sd["v_head.weight"] = np.asarray(lora["v_head"])
    out = os.path.join(export_dir, "model.npz")
    np.savez(out, **sd)
    import dataclasses

    with open(os.path.join(export_dir, "config.json"), "w") as f:
        json.dump(dataclasses.asdict(cfg), f, indent=1, default=str)
    return out
