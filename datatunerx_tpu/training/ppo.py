"""PPO stage: RLHF policy optimization against a trained reward model.

The reference lists ``--stage ppo`` in its argument schema (reference
cmd/tuning/parser.py:117-120) with ppo knobs (:170-185) but, like dpo/rm,
ships no runtime for it — its train.py only ever builds an SFT trainer.
This module is new capability, designed TPU-first:

- **One frozen base, three roles.** Policy = base + trainable LoRA (+ value
  head on the final-norm hidden state); reference policy = the same base with
  the adapter switched OFF (the DPO trick, train_lib.py:256); reward model =
  the same base + the FROZEN adapter/v_head from an ``--stage rm`` run. One
  copy of the 7B weights in HBM serves all three — the torch equivalent keeps
  2-3 model replicas.
- **Whole rollout is ONE compiled program**: prefill → ``lax.scan`` sampling
  decode over the shared KV cache (old log-probs and values recorded inside
  the scan — the policy is never re-run for them) → reference log-probs →
  reward score → per-token KL-shaped rewards → GAE, all jitted together. No
  host round-trips inside a PPO step.
- **Token-level PPO** (the TRL/InstructGPT recipe): reward at the last
  response token from the rm value head, per-token penalty
  ``-kl_coef * (log π(a) - log π_ref(a))``, GAE(γ, λ) advantages, clipped
  surrogate + clipped value loss over ``ppo_epochs`` full-batch passes.
- Adaptive KL controller (``ppo_target`` > 0) runs on host between steps and
  feeds ``kl_coef`` back in as a scalar operand — no recompilation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from datatunerx_tpu.models.config import ModelConfig
from datatunerx_tpu.models.llama import forward, init_cache, lm_logits
from datatunerx_tpu.training.train_lib import TrainConfig, Trainer


@dataclasses.dataclass
class PPOConfig:
    gen_len: int = 64          # response tokens sampled per rollout
    temperature: float = 1.0   # 0 = greedy (degenerate but allowed for tests)
    top_k: int = 0             # 0 = sample the full softmax
    kl_coef: float = 0.1       # initial per-token KL penalty coefficient
    ppo_target: float = 0.0    # target |KL|; >0 enables the adaptive controller
    kl_horizon: float = 10.0   # adaptation speed (steps to close the error)
    ppo_epochs: int = 2        # optimization passes per rollout batch
    clip_ratio: float = 0.2
    vf_coef: float = 0.1
    vf_clip: float = 0.2
    gamma: float = 1.0
    gae_lambda: float = 0.95
    score_norm: bool = False   # whiten rm scores across the batch (--ppo_score_norm)
    whiten_advantages: bool = True

    def __post_init__(self):
        assert self.gen_len > 0
        assert self.ppo_epochs >= 1
        assert 0.0 < self.clip_ratio < 1.0


def compute_gae(rewards, values, mask, gamma: float, lam: float):
    """GAE over [B, G] response windows. ``mask`` is 1 on response tokens
    (a contiguous prefix of the window); the episode terminates at the last
    masked token — no bootstrap value beyond it."""
    rewards = rewards * mask
    values = values * mask

    def step(carry, xs):
        r, v, v_next, m, m_next = xs
        delta = r + gamma * v_next * m_next - v
        adv = delta + gamma * lam * carry
        adv = adv * m  # positions after the episode carry nothing
        return adv, adv

    v_next = jnp.concatenate([values[:, 1:], jnp.zeros_like(values[:, :1])], 1)
    m_next = jnp.concatenate([mask[:, 1:], jnp.zeros_like(mask[:, :1])], 1)
    xs = tuple(x.T for x in (rewards, values, v_next, mask, m_next))  # [G, B]
    _, adv = jax.lax.scan(step, jnp.zeros(rewards.shape[:1]), xs, reverse=True)
    adv = adv.T
    return adv, adv + values


def _masked_mean(x, m, eps=1e-8):
    return jnp.sum(x * m) / jnp.maximum(jnp.sum(m), eps)


def _whiten(x, m):
    mean = _masked_mean(x, m)
    var = _masked_mean(jnp.square(x - mean), m)
    return (x - mean) * jax.lax.rsqrt(var + 1e-8) * m


class PPOTrainer(Trainer):
    """Composes the base Trainer's state/optimizer/mesh machinery with
    rollout + PPO update steps. ``train_cfg.stage`` must be "ppo"
    (finetuning_type lora; the policy value head rides in the lora tree like
    the rm stage's, train_lib.py:169-177)."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        train_cfg: TrainConfig,
        ppo_cfg: PPOConfig,
        *,
        reward_lora: Any,
        reward_scaling: float,
        eos_id: int,
        pad_id: int = 0,
        mesh=None,
    ):
        assert train_cfg.stage == "ppo", "PPOTrainer requires stage='ppo'"
        if "v_head" not in reward_lora:
            raise ValueError(
                "reward_lora must come from an --stage rm run (no v_head found)"
            )
        super().__init__(model_cfg, train_cfg, mesh=mesh)
        self.ppo_cfg = ppo_cfg
        if eos_id is None:
            raise ValueError(
                "PPO requires a tokenizer with an EOS token "
                "(tokenizer.eos_token_id is None): rollouts could never "
                "terminate early without one"
            )
        self.eos_id = int(eos_id)
        self.pad_id = int(pad_id)
        self.kl_coef = float(ppo_cfg.kl_coef)  # host-side, adaptively tuned
        if mesh is not None:
            from datatunerx_tpu.parallel.sharding import shard_tree

            reward_lora = shard_tree(reward_lora, mesh)
        self.reward_lora = jax.tree_util.tree_map(jax.lax.stop_gradient,
                                                  reward_lora)
        self.reward_scaling = float(reward_scaling)
        self._rollout = jax.jit(self._rollout_impl)
        self._update = jax.jit(self._ppo_update_impl, donate_argnums=(0,))

    # ------------------------------------------------------------- rollout
    def _sample(self, logits, rng):
        p = self.ppo_cfg
        if p.temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / p.temperature
        if p.top_k > 0:
            kth = jax.lax.top_k(logits, p.top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        return jax.random.categorical(rng, logits).astype(jnp.int32)

    def _rollout_impl(self, state, batch, kl_coef):
        cfg, p = self.model_cfg, self.ppo_cfg
        cdt = self.cfg.compute_dtype
        prompt_ids = batch["prompt_ids"].astype(jnp.int32)
        pmask = batch["prompt_mask"].astype(jnp.int32)
        B, Tp = prompt_ids.shape
        G = p.gen_len
        lora = (state.lora, self.scaling)
        v_head = state.lora["v_head"].astype(jnp.float32)
        rng = jax.random.fold_in(jax.random.fold_in(state.rng, 0x990),
                                 state.step)

        # left-padded prompts: real tokens at the end, rope positions 0..n-1
        positions = jnp.maximum(jnp.cumsum(pmask, axis=1) - 1, 0).astype(jnp.int32)
        n_prompt = jnp.sum(pmask, axis=1).astype(jnp.int32)  # [B]
        cache = init_cache(cfg, B, Tp + G,
                           dtype=jnp.bfloat16 if cdt is not None else jnp.float32)
        logits, cache, hidden = forward(
            params := state.params, prompt_ids, cfg, positions=positions,
            attention_mask=pmask, cache=cache, lora=lora, compute_dtype=cdt,
            return_hidden=True,
        )

        def dec(carry, i):
            lg_prev, h_prev, cache, done, r = carry
            r, r_step = jax.random.split(r)
            lg_prev = lg_prev.astype(jnp.float32)
            a = self._sample(lg_prev, r_step)                       # [B]
            logp = jax.nn.log_softmax(lg_prev, axis=-1)
            lp_a = jnp.take_along_axis(logp, a[:, None], 1)[:, 0]
            value = h_prev.astype(jnp.float32) @ v_head             # V(s_t)
            m = (~done).astype(jnp.int32)   # token i is part of the response
            tok = jnp.where(done, self.pad_id, a)
            new_done = done | (a == self.eos_id)
            pos = (n_prompt + i)[:, None]
            lg, cache, h = forward(
                params, tok[:, None], cfg, positions=pos,
                attention_mask=m[:, None],  # post-eos slots → pos sentinel
                cache=cache, lora=lora, compute_dtype=cdt, return_hidden=True,
            )
            return (lg[:, -1], h[:, -1], cache, new_done, r), (tok, lp_a, value, m)

        carry0 = (logits[:, -1], hidden[:, -1], cache,
                  jnp.zeros((B,), bool), rng)
        _, (toks, old_logp, values, resp_mask) = jax.lax.scan(
            dec, carry0, jnp.arange(G))
        toks, old_logp = toks.T, old_logp.T                    # [B, G]
        values, resp_mask = values.T, resp_mask.T.astype(jnp.float32)

        # ---- full sequences for the reference/reward forwards ----------
        seq = jnp.concatenate([prompt_ids, toks], axis=1)      # [B, Tp+G]
        full_mask = jnp.concatenate(
            [pmask, resp_mask.astype(jnp.int32)], axis=1)
        full_pos = jnp.maximum(jnp.cumsum(full_mask, axis=1) - 1, 0).astype(jnp.int32)

        def gen_logps(lora_arg):
            # hidden-only forward + lm_head over just the G predicting
            # positions: the [Tp+G, V] softmax would be ~17× wasted work
            _, _, h = forward(params, seq, cfg, positions=full_pos,
                              attention_mask=full_mask, lora=lora_arg,
                              compute_dtype=cdt, return_hidden=True,
                              skip_logits=True)
            lg = lm_logits(params, h[:, Tp - 1:-1], cfg)        # [B, G, V]
            lp = jax.nn.log_softmax(lg, axis=-1)
            return jnp.take_along_axis(
                lp, seq[:, Tp:, None], axis=-1)[..., 0]         # [B, G]

        ref_logp = jax.lax.stop_gradient(gen_logps(None))

        _, _, rh = forward(params, seq, cfg, positions=full_pos,
                           attention_mask=full_mask,
                           lora=(self.reward_lora, self.reward_scaling),
                           compute_dtype=cdt, return_hidden=True,
                           skip_logits=True)
        n_resp = jnp.sum(resp_mask, axis=1).astype(jnp.int32)   # ≥ 1 always
        last_idx = Tp + n_resp - 1
        h_last = jnp.take_along_axis(
            rh, last_idx[:, None, None], axis=1)[:, 0].astype(jnp.float32)
        score = h_last @ self.reward_lora["v_head"].astype(jnp.float32)  # [B]
        raw_score = score
        if p.score_norm:
            score = (score - jnp.mean(score)) / (jnp.std(score) + 1e-6)

        kl = (old_logp - ref_logp) * resp_mask                  # [B, G]
        last_onehot = (jnp.arange(G)[None, :] == (n_resp - 1)[:, None])
        rewards = -kl_coef * kl + last_onehot * score[:, None]
        adv, rets = compute_gae(rewards, values, resp_mask,
                                p.gamma, p.gae_lambda)

        stats = {
            "reward_score": jnp.mean(raw_score),
            "kl": _masked_mean(kl, resp_mask),
            "response_len": jnp.mean(n_resp.astype(jnp.float32)),
        }
        ro = {
            "seq": seq, "full_mask": full_mask, "positions": full_pos,
            "resp_mask": resp_mask, "old_logp": old_logp, "values": values,
            "advantages": adv, "returns": rets,
        }
        return jax.lax.stop_gradient(ro), stats

    # -------------------------------------------------------------- update
    def _ppo_update_impl(self, state, ro):
        cfg, p = self.model_cfg, self.ppo_cfg
        cdt = self.cfg.compute_dtype
        G = ro["old_logp"].shape[1]
        Tp = ro["seq"].shape[1] - G
        m = ro["resp_mask"]
        adv = ro["advantages"]
        if p.whiten_advantages:
            adv = _whiten(adv, m)

        def loss_fn(lora_tr):
            _, _, hid = forward(
                state.params, ro["seq"], cfg, positions=ro["positions"],
                attention_mask=ro["full_mask"], lora=(lora_tr, self.scaling),
                compute_dtype=cdt, return_hidden=True, skip_logits=True,
                # no dropout in PPO: the surrogate ratio must compare the same
                # deterministic policy the rollout sampled from
            )
            h_pred = hid[:, Tp - 1:-1]                           # [B, G, D]
            lg = lm_logits(state.params, h_pred, cfg)            # [B, G, V]
            lp = jax.nn.log_softmax(lg, axis=-1)
            new_logp = jnp.take_along_axis(
                lp, ro["seq"][:, Tp:, None], axis=-1)[..., 0]
            new_v = h_pred.astype(jnp.float32) @ lora_tr["v_head"].astype(jnp.float32)

            # Clamp before exp: at masked (post-EOS) positions old_logp is the
            # sampled token's log-prob while new_logp indexes the pad token, so
            # the difference is meaningless — adv=0 cancels it, but an
            # unclamped exp can overflow to inf and inf*0 => NaN.
            ratio = jnp.exp(jnp.clip(new_logp - ro["old_logp"], -20.0, 20.0))
            clipped = jnp.clip(ratio, 1.0 - p.clip_ratio, 1.0 + p.clip_ratio)
            pg = -jnp.minimum(ratio * adv, clipped * adv)
            pg_loss = _masked_mean(pg, m)

            v_clip = ro["values"] + jnp.clip(
                new_v - ro["values"], -p.vf_clip, p.vf_clip)
            vf = 0.5 * jnp.maximum(jnp.square(new_v - ro["returns"]),
                                   jnp.square(v_clip - ro["returns"]))
            vf_loss = _masked_mean(vf, m)

            aux = {
                "pg_loss": pg_loss,
                "vf_loss": vf_loss,
                "approx_kl": _masked_mean(ro["old_logp"] - new_logp, m),
                "clipfrac": _masked_mean(
                    (jnp.abs(ratio - 1.0) > p.clip_ratio).astype(jnp.float32), m),
            }
            return pg_loss + p.vf_coef * vf_loss, aux

        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.lora)
        updates, opt_state = self.optimizer.update(
            grads, state.opt_state, state.lora)
        # cast back to the param dtype (bare add would promote against fp32
        # updates — see train_lib._train_step_impl)
        new_lora = jax.tree_util.tree_map(
            lambda p, u: (p + u).astype(p.dtype), state.lora, updates)
        metrics = dict(aux)
        metrics["loss"] = loss
        metrics["lr"] = self.schedule(state.step)
        from datatunerx_tpu.training.train_lib import optax_global_norm

        metrics["grad_norm"] = optax_global_norm(grads)
        return state.replace(step=state.step + 1, lora=new_lora,
                             opt_state=opt_state), metrics

    # ---------------------------------------------------------- public API
    def step(self, state, batch):
        """One PPO iteration: rollout, then ``ppo_epochs`` update passes.
        Returns (state, metrics); metrics mix rollout stats (reward_score, kl,
        response_len) with the last update pass's losses."""
        p = self.ppo_cfg
        batch = self._put_batch(batch)
        ro, stats = self._rollout(state, batch, jnp.float32(self.kl_coef))
        metrics = {}
        for _ in range(p.ppo_epochs):
            state, metrics = self._update(state, ro)
        metrics.update({k: v for k, v in stats.items()})
        metrics["kl_coef"] = self.kl_coef
        if p.ppo_target > 0.0:
            # proportional controller (TRL AdaptiveKLController): nudge the
            # coefficient so measured per-token KL tracks ppo_target
            err = float(jnp.clip(
                float(stats["kl"]) / p.ppo_target - 1.0, -0.2, 0.2))
            self.kl_coef = max(self.kl_coef * (1.0 + err / p.kl_horizon), 1e-4)
        return state, metrics

    # SFT-style train/eval steps don't apply to PPO
    def train_step(self, state, batch):  # pragma: no cover
        raise NotImplementedError("use PPOTrainer.step(state, prompt_batch)")

    def eval_step(self, state, batch):  # pragma: no cover
        raise NotImplementedError("use PPOTrainer.step(state, prompt_batch)")


CONTROLLER_STATE = "ppo_controller.json"


def save_controller_state(ckpt_dir: str, step: int, kl_coef: float) -> None:
    """Persist the host-side adaptive-KL controller next to the Orbax
    checkpoints: kl_coef is trainer state the TrainState pytree doesn't
    carry, and a resume that silently reset it to --init_kl_coef would
    discontinuously weaken the reward shaping."""
    import json

    from datatunerx_tpu.utils import storage

    storage.write_text(
        storage.join(ckpt_dir, CONTROLLER_STATE),
        json.dumps({"step": int(step), "kl_coef": float(kl_coef)}))


def load_controller_state(ckpt_dir: str) -> Optional[dict]:
    import json

    from datatunerx_tpu.utils import storage

    path = storage.join(ckpt_dir, CONTROLLER_STATE)
    if not storage.exists(path):
        return None
    return json.loads(storage.read_text(path))


def load_reward_model(model_cfg: ModelConfig, params, reward_dir: str,
                      mesh=None):
    """Load the frozen reward adapter from an ``--stage rm`` run directory
    (``<storage_path>/<run>`` containing manifest.json + checkpoints/).

    Reuses the run's manifest for rank/targets/scaling and restores the
    adapter + v_head through a throwaway rm-stage TrainState template over the
    SAME base params — the 7B base is never duplicated. Returns
    (reward_lora, reward_scaling)."""
    import json
    import os

    from datatunerx_tpu.models.lora import DEFAULT_TARGETS, lora_scaling
    from datatunerx_tpu.training.checkpoint import (
        MANIFEST_NAME,
        CheckpointManager,
    )
    from datatunerx_tpu.utils import storage

    mpath = storage.join(reward_dir, MANIFEST_NAME)
    if not storage.exists(mpath):
        raise FileNotFoundError(
            f"--reward_model {reward_dir!r}: no {MANIFEST_NAME} — point it at "
            "an --stage rm run directory (<storage_path>/<uid>)")
    manifest = json.loads(storage.read_text(mpath))
    rank = int(manifest.get("lora_rank") or 8)
    targets = tuple(manifest.get("lora_targets") or DEFAULT_TARGETS)
    scaling = float(manifest.get("lora_scaling")
                    or lora_scaling(float(manifest.get("lora_alpha") or 32.0),
                                    rank))
    ckpt_uri = manifest.get("checkpoint")
    if not ckpt_uri:
        raise ValueError(f"manifest {mpath} has no checkpoint URI")
    ckpt_dir = os.path.dirname(str(ckpt_uri).rstrip("/"))
    step = int(os.path.basename(str(ckpt_uri).rstrip("/")))

    rm_trainer = Trainer(
        model_cfg,
        TrainConfig(stage="rm", finetuning_type="lora", lora_rank=rank,
                    lora_targets=targets, compute_dtype=None,
                    # the template's opt_state tree must match the saved one;
                    # structure depends only on the optimizer family
                    optimizer=str(manifest.get("optimizer") or "adamw")),
        mesh=mesh,
    )
    template = rm_trainer.init_state(params, jax.random.PRNGKey(0))
    mngr = CheckpointManager(ckpt_dir)
    try:
        restored, _ = mngr.restore(template, step=step)
    finally:
        mngr.close()
    if restored is None:
        raise FileNotFoundError(f"no checkpoint at {ckpt_uri}")
    return restored.lora, scaling
