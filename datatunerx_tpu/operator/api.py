"""CR schemas: 8 kinds across 3 API groups (SURVEY.md §2.3; reconstructed from
the reference's field-by-field usage since its meta-server types module is not
vendored).

Groups:
  finetune.datatunerx.io/v1beta1:  Finetune, FinetuneJob, FinetuneExperiment
  core.datatunerx.io/v1beta1:      LLM, Hyperparameter, LLMCheckpoint
  extension.datatunerx.io/v1beta1: Dataset, Scoring

Everything is a plain dataclass serializable to/from dicts (to_dict/from_dict)
so stores can persist JSON and webhooks can validate structurally.
"""

from __future__ import annotations

import copy
import dataclasses
import time
import typing
from typing import Any, Dict, List, Optional

GROUP_FINETUNE = "finetune.datatunerx.io/v1beta1"
GROUP_CORE = "core.datatunerx.io/v1beta1"
GROUP_EXTENSION = "extension.datatunerx.io/v1beta1"

# shared finalizer (reference finetune_controller.go:98-113)
FINETUNE_GROUP_FINALIZER = "finetune.datatunerx.io/finalizer"


def _new_uid() -> str:
    import uuid

    return str(uuid.uuid4())


@dataclasses.dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = dataclasses.field(default_factory=_new_uid)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    finalizers: List[str] = dataclasses.field(default_factory=list)
    owner_references: List[Dict[str, str]] = dataclasses.field(default_factory=list)
    resource_version: int = 0
    generation: int = 1
    creation_timestamp: float = dataclasses.field(default_factory=time.time)
    deletion_timestamp: Optional[float] = None


@dataclasses.dataclass
class CustomResource:
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: Dict[str, Any] = dataclasses.field(default_factory=dict)
    status: Dict[str, Any] = dataclasses.field(default_factory=dict)

    # class attributes set by subclasses (ClassVar: not dataclass fields)
    api_version: typing.ClassVar[str] = ""
    kind: typing.ClassVar[str] = ""

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def deepcopy(self):
        return copy.deepcopy(self)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "metadata": dataclasses.asdict(self.metadata),
            "spec": copy.deepcopy(self.spec),
            "status": copy.deepcopy(self.status),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]):
        meta = ObjectMeta(**d.get("metadata", {}))
        return cls(metadata=meta, spec=copy.deepcopy(d.get("spec", {})),
                   status=copy.deepcopy(d.get("status", {})))


# --------------------------------------------------------- finetune group

class Finetune(CustomResource):
    """One training run (SURVEY.md §2.3 Finetune).

    spec: dataset, llm, hyperparameter{hyperparameterRef, overrides},
          image{name, path, imagePullPolicy}, node (worker count), resource,
          TPU addition: topology/mesh {dp, fsdp, tp, sp}
    status: state, jobInfo{podName, containerName}, llmCheckpoint{ref, checkpointPath}
    """

    api_version = GROUP_FINETUNE
    kind = "Finetune"

    STATE_INIT = "Init"
    STATE_PENDING = "Pending"
    STATE_RUNNING = "Running"
    STATE_SUCCESSFUL = "Successful"
    STATE_FAILED = "Failed"


class FinetuneJob(CustomResource):
    """Pipeline wrapper: train → checkpoint publish → serve → score
    (SURVEY.md §2.3 FinetuneJob).

    spec: finetune{name, finetuneSpec}, scoringPluginConfig{name, parameters},
          serveConfig{nodeSelector, tolerations}
    status: state, finetuneStatus (mirror), result{modelExportResult, image,
            serve, dashboard, score}, stats
    """

    api_version = GROUP_FINETUNE
    kind = "FinetuneJob"

    STATE_INIT = "Init"
    STATE_FINETUNE = "Finetune"
    STATE_BUILDIMAGE = "BuildImage"  # checkpoint-publish stage (no image bake on TPU)
    STATE_SERVE = "Serve"
    STATE_SUCCESSFUL = "Successful"
    STATE_FAILED = "Failed"


class FinetuneExperiment(CustomResource):
    """Batch of jobs with best-version selection (SURVEY.md §2.3).

    spec: finetuneJobs[{name, spec}], pending (pause switch)
    status: state, jobsStatus[{name, status}], bestVersion{score, image, llm,
            hyperparameter, dataset}, stats
    """

    api_version = GROUP_FINETUNE
    kind = "FinetuneExperiment"

    STATE_PENDING = "Pending"
    STATE_PROCESSING = "Processing"
    STATE_SUCCESS = "Success"
    STATE_FAILED = "Failed"


# ------------------------------------------------------------- core group

class LLM(CustomResource):
    """Model registry entry. status.referenceFinetuneName back-references."""

    api_version = GROUP_CORE
    kind = "LLM"


class Hyperparameter(CustomResource):
    """Reusable parameter group. spec.parameters fields (SURVEY.md §2.3):
    scheduler, optimizer, int4, int8, loRA_R, loRA_Alpha, loRA_Dropout,
    learningRate, epochs, blockSize, batchSize, warmupRatio, weightDecay,
    gradAccSteps, trainerType, PEFT, FP16 — numeric-ish fields are strings
    (reference quirk kept for API compat); TPU additions: topology, meshShape."""

    api_version = GROUP_CORE
    kind = "Hyperparameter"


class LLMCheckpoint(CustomResource):
    """Immutable provenance snapshot of a finished run: deep-copied LLM/
    Dataset/Hyperparameter specs + checkpoint URI (reference
    finetune_controller.go:621-653)."""

    api_version = GROUP_CORE
    kind = "LLMCheckpoint"


# -------------------------------------------------------- extension group

class Dataset(CustomResource):
    """spec.datasetMetadata.datasetInfo: subsets[].splits.{train,validate,test}
    .file URIs + features[{name: instruction|response, mapTo}]."""

    api_version = GROUP_EXTENSION
    kind = "Dataset"


class Scoring(CustomResource):
    """spec: inferenceService URL, plugin{loadPlugin, name, parameters};
    status.score (string, reference quirk kept)."""

    api_version = GROUP_EXTENSION
    kind = "Scoring"


ALL_KINDS = [
    Finetune, FinetuneJob, FinetuneExperiment,
    LLM, Hyperparameter, LLMCheckpoint,
    Dataset, Scoring,
]
KIND_BY_NAME = {k.kind: k for k in ALL_KINDS}
