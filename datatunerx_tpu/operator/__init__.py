"""Control plane: the CRD-driven orchestration layer.

Rebuilds the reference's Go operator (reference internal/controller/finetune/,
SURVEY.md §2.1 G1-G13) as a Python reconciler framework with the same
capability surface: 8 CR kinds in 3 API groups, three nested state-machine
controllers (Finetune → FinetuneJob → FinetuneExperiment), resource
generation, validation webhooks, finalizers, owner references, and
requeue-with-backoff error policy.

Mechanism replacement (SURVEY.md §7.1): KubeRay RayJob/RayService become a
pluggable ClusterBackend — LocalProcessBackend executes training/serving as
host processes (CI/e2e), ManifestBackend renders GKE JobSet/Deployment specs
for TPU node pools.
"""

from datatunerx_tpu.operator.api import (
    Dataset,
    Finetune,
    FinetuneExperiment,
    FinetuneJob,
    Hyperparameter,
    LLM,
    LLMCheckpoint,
    ObjectMeta,
    Scoring,
)
from datatunerx_tpu.operator.store import ObjectStore
from datatunerx_tpu.operator.reconciler import Manager, Result

__all__ = [
    "Dataset", "Finetune", "FinetuneExperiment", "FinetuneJob",
    "Hyperparameter", "LLM", "LLMCheckpoint", "ObjectMeta", "Scoring",
    "ObjectStore", "Manager", "Result",
]
