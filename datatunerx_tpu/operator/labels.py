"""Well-known labels (reference pkg/util/label/label.go:3-35)."""

LABEL_INSTANCE = "finetune.datatunerx.io/instance"
LABEL_COMPONENT = "finetune.datatunerx.io/component"
LABEL_PART_OF = "finetune.datatunerx.io/part-of"
LABEL_FINETUNE_BINDING = "finetune.datatunerx.io/finetunebinding"


def generate_instance_label(name: str) -> dict:
    return {LABEL_INSTANCE: name}


def generate_component_label(component: str) -> dict:
    return {LABEL_COMPONENT: component}
