"""Admission-time HBM capacity check for Finetune jobs (VERDICT r3 #4).

Bridges the Hyperparameter CR's string-typed parameters and the Finetune
spec to `parallel/memory.py::check_fits`, so the controller can reject a
job whose training state provably cannot fit the assigned slice's HBM —
at admission, with a byte breakdown in the status — instead of letting it
OOM minutes into on-slice compilation. (The reference has no equivalent:
its worker simply dies, reference internal/controller/finetune/
finetune_controller.go:596-603 just requests 1 GPU + 8 CPU.)

The model is resolved the same way the trainer will resolve it
(utils/model_loader.py): ``preset:<name>`` or a local directory with
``config.json``. Remote/unreadable model paths resolve to None and the
check ADMITS — an unresolvable model is not evidence of oversize, and the
trainer's own loader will surface real path errors.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Optional, Tuple

from datatunerx_tpu.operator.generate import _truthy, is_peft


def resolve_model_config(model_path: str, overrides: Optional[dict] = None):
    """ModelConfig the trainer will build, or None when unresolvable here."""
    from datatunerx_tpu.models.config import ModelConfig, get_config

    overrides = overrides or {}
    try:
        if model_path.startswith("preset:"):
            return get_config(model_path.split(":", 1)[1], **overrides)
        cfg_json = os.path.join(model_path, "config.json")
        if os.path.isdir(model_path) and os.path.exists(cfg_json):
            with open(cfg_json) as f:
                raw = json.load(f)
            field_names = {f.name for f in dataclasses.fields(ModelConfig)}
            raw = {k: v for k, v in raw.items() if k in field_names}
            for k in ("head_dim", "sliding_window"):
                if raw.get(k) in ("None", ""):
                    raw[k] = None
            raw.update(overrides)
            return ModelConfig(**raw)
    except Exception:  # noqa: BLE001 — malformed config: let the trainer err
        return None
    return None


def _mesh_shape_from(parameters: dict, n_chips: int) -> Dict[str, int]:
    """EXACTLY the mesh the SPMD driver will build (tuning/train.py:147-158):
    same dims parsing, same None-axis absorption via ``mesh_shape_for``.
    Raises ValueError when the shape cannot tile ``n_chips`` — the same
    error the trainer would hit on-slice."""
    from datatunerx_tpu.parallel.mesh import mesh_shape_for

    ms = parameters.get("meshShape")
    dims: Dict[str, int] = {}
    if isinstance(ms, dict):
        dims = {k: int(v) for k, v in ms.items()}
    elif isinstance(ms, str) and ms:
        for part in ms.split(","):
            k, _, v = part.partition("=")
            dims[k.strip()] = int(v)
    dims.pop("dcn", None)
    shape = mesh_shape_for(
        n_chips,
        dp=dims.get("dp"),
        fsdp=dims.get("fsdp", 1 if "dp" in dims else None),
        tp=dims.get("tp", 1),
        sp=dims.get("sp", 1),
    )
    return dict(zip(("dp", "fsdp", "tp", "sp"), shape))


def check_admission(
    model_path: str,
    parameters: dict,
    *,
    n_chips: int,
    generation: str = "v5e",
) -> Optional[Tuple[str, dict]]:
    """→ None to admit, or (reason, footprint_gb) to reject.

    ``parameters`` is the merged Hyperparameter map (string-typed values,
    reference quirk). Only rejects when the model config is resolvable AND
    the exact+analytic estimate exceeds the per-chip budget.
    """
    import jax.numpy as jnp

    overrides: dict = {}
    if _truthy(parameters.get("int8")):
        overrides["quantization"] = "int8"
    elif _truthy(parameters.get("int4")):
        overrides["quantization"] = "int4"
    if parameters.get("attention"):
        overrides["attention_impl"] = str(parameters["attention"])
    cfg = resolve_model_config(model_path, overrides)
    if cfg is None:
        return None

    from datatunerx_tpu.parallel.memory import check_fits
    from datatunerx_tpu.training.train_lib import TrainConfig

    try:
        train_cfg = TrainConfig(
            finetuning_type="lora" if is_peft(parameters) else "full",
            lora_rank=int(float(parameters.get("loRA_R", 8))),
            lora_targets=tuple(
                str(parameters.get("loRATarget", "q_proj,v_proj")).split(",")),
            optimizer=str(parameters.get("optimizer", "adamw")).lower(),
            grad_accum=int(float(parameters.get("gradAccSteps", 1))),
            compute_dtype=jnp.bfloat16,
        )
        per_device_batch = int(float(parameters.get("batchSize", 8)))
        seq = int(float(parameters.get("blockSize", 1024)))
    except (TypeError, ValueError):
        # garbled numerics are the webhooks' problem, not admission's
        return None

    try:
        mesh_shape = _mesh_shape_from(parameters, n_chips)
    except ValueError as e:
        # the trainer's mesh_shape_for would raise the same on-slice —
        # surface it at admission instead
        return (f"meshShape cannot tile the assigned {n_chips} chips: {e}",
                {})
    # batchSize is PER-DEVICE (--per_device_train_batch_size, generate.py);
    # the trainer's global batch is per_device * data_par * grad_accum
    # (tuning/train.py:168). estimate_footprint takes the GLOBAL batch and
    # divides back by the same factors, so the per-device microbatch it
    # models equals batchSize exactly.
    data_par = mesh_shape.get("dp", 1) * mesh_shape.get("fsdp", 1)
    batch = per_device_batch * data_par * train_cfg.grad_accum

    try:
        fits, fp, budget = check_fits(
            cfg, train_cfg, batch=batch, seq=seq,
            mesh_shape=mesh_shape, generation=generation)
    except Exception:  # noqa: BLE001 — estimator bug must never block jobs
        return None
    if fits:
        return None
    return (
        f"estimated HBM footprint {fp.total / 1e9:.1f} GB/chip exceeds the "
        f"{generation} budget {budget / 1e9:.1f} GB at "
        f"batch={batch} seq={seq} mesh={mesh_shape} "
        f"(breakdown GB: {fp.gb()}); shard further (meshShape), lower "
        f"batchSize/blockSize, or quantize (int4)", fp.gb())


def serving_replicas_for(
    hint: dict,
    *,
    min_replicas: int = 1,
    max_replicas: int = 8,
    free_slices: Optional[int] = None,
) -> int:
    """Turn the gateway's autoscale hint (gateway/autoscale.py, polled from
    GET /autoscale) into the replica count the controller should apply.

    The gateway only observes (queue depth, shed count, p95); capacity
    policy lives HERE: the spec's min/max bounds and — when a TPU slice
    pool exists — the free-slice inventory cap scale-up, so the controller
    never asks for replicas the hardware can't place (the same inventory
    `placement.SlicePool` gates training jobs with)."""
    current = max(1, int(hint.get("replicas", 1)))
    desired = int(hint.get("desiredReplicas", current))
    desired = max(min_replicas, min(max_replicas, desired))
    if free_slices is not None and desired > current:
        desired = min(desired, current + max(0, int(free_slices)))
    return desired
