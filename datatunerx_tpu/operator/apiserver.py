"""REST API server: the kubectl-shaped front door to the object store.

The reference's user surface is the k8s API server + 8 CRDs (SURVEY.md §1 L6,
kubectl/Helm/dtx-ctl/web UI). Without a cluster, this server provides the same
verbs over the in-process store so external tools (the dtx CLI, a UI, curl)
can drive the pipeline:

  GET    /apis                                    — discovery
  GET    /apis/{group}/{version}/{kind}           — list (``?labelSelector=k=v``)
  POST   /apis/{group}/{version}/{kind}           — create (admission applies)
  GET    /apis/{group}/{version}/{kind}/{ns}/{name}
  PUT    /apis/{group}/{version}/{kind}/{ns}/{name}
  DELETE /apis/{group}/{version}/{kind}/{ns}/{name}
  GET    /healthz | /readyz | /metrics

Admission (operator/webhooks.py) runs on create/update — the webhook-server
equivalent (reference controller_manager.go:114-134).
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from datatunerx_tpu.operator.api import ALL_KINDS, CustomResource, KIND_BY_NAME
from datatunerx_tpu.operator.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
)
from datatunerx_tpu.operator.webhooks import AdmissionError

_GROUPS = {
    "finetune.datatunerx.io": ["Finetune", "FinetuneJob", "FinetuneExperiment"],
    "core.datatunerx.io": ["LLM", "Hyperparameter", "LLMCheckpoint"],
    "extension.datatunerx.io": ["Dataset", "Scoring"],
}
_KIND_LOWER = {k.kind.lower(): k.kind for k in ALL_KINDS}
# also accept plural-ish forms (kubectl habit)
for k in ALL_KINDS:
    _KIND_LOWER[k.kind.lower() + "s"] = k.kind

_PATH = re.compile(
    r"^/apis/(?P<group>[^/]+)/(?P<version>[^/]+)/(?P<kind>[^/]+)"
    r"(?:/(?P<ns>[^/]+)(?:/(?P<name>[^/]+))?)?$"
)


def _resolve_kind(raw: str) -> Optional[str]:
    return _KIND_LOWER.get(raw.lower())


class ApiHandler(BaseHTTPRequestHandler):
    store: ObjectStore = None
    manager = None
    token: Optional[str] = None  # DTX_API_TOKEN bearer auth when set

    def _authorized(self) -> bool:
        if not self.token:
            return True
        return self.headers.get("Authorization") == f"Bearer {self.token}"

    # ------------------------------------------------------------ plumbing
    def _send(self, code: int, payload):
        body = json.dumps(payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length) or b"{}")

    def log_message(self, *a):
        pass

    # --------------------------------------------------------------- verbs
    def do_GET(self):
        url = urlparse(self.path)
        if url.path in ("/healthz", "/readyz"):
            return self._send(200, {"status": "ok"})
        if not self._authorized():
            return self._send(401, {"error": "unauthorized"})
        if url.path in ("/", "/ui", "/ui/"):
            # single-file web UI (reference datatunerx-ui equivalent)
            import os

            try:
                with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                       "ui.html"), "rb") as f:
                    body = f.read()
            except OSError:
                return self._send(404, {"error": "ui.html not bundled"})
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if url.path.startswith("/trainermetrics/"):
            # /trainermetrics/{ns}/{name}: trainer/eval jsonl curves for the UI
            parts = [p for p in url.path.split("/")[2:] if p]
            if len(parts) != 2:
                return self._send(400, {"error": "use /trainermetrics/{namespace}/{name}"})
            ns, name = parts
            if not re.fullmatch(r"[A-Za-z0-9][A-Za-z0-9._-]*", name):
                return self._send(400, {"error": "invalid job name"})
            if self.store.try_get("Finetune", name, ns) is None:
                return self._send(404, {"error": f"Finetune {ns}/{name} not found"})
            backend = getattr(self.manager, "training_backend", None) if self.manager else None
            series = getattr(backend, "metrics_series", None)
            if series is None:
                return self._send(
                    501, {"error": "metrics series not supported by this backend"})
            return self._send(200, {"name": name, **series(name)})
        if url.path == "/metrics":
            n_err = len(self.manager.errors) if self.manager else 0
            lines = [
                "# TYPE dtx_operator_reconcile_errors_total counter",
                f"dtx_operator_reconcile_errors_total {n_err}",
                "# TYPE dtx_operator_reconciles_total counter",
            ]
            counts = dict(  # snapshot: the manager thread inserts keys live
                getattr(self.manager, "reconcile_counts", {}) if self.manager else {}
            )
            for kind, n in sorted(counts.items()):
                lines.append(
                    f'dtx_operator_reconciles_total{{kind="{kind}"}} {n}')
            probe = getattr(self.manager, "health_probe", None) if self.manager else None
            if probe is not None:
                lines.append("# TYPE dtx_device_healthy gauge")
                lines.append(f"dtx_device_healthy {int(bool(probe.healthy))}")
            pool = getattr(self.manager, "slice_pool", None) if self.manager else None
            if pool is not None:
                lines.append("# TYPE dtx_slices_free gauge")
                lines.append(f"dtx_slices_free {pool.free_count()}")
                lines.append("# TYPE dtx_slices_total gauge")
                lines.append(f"dtx_slices_total {len(pool.slices())}")
            body = ("\n".join(lines) + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.end_headers()
            self.wfile.write(body)
            return
        if url.path == "/apis":
            return self._send(200, {"groups": _GROUPS})
        if url.path.startswith("/logs/"):
            # /logs/{ns}/{name}: trainer log tail for a Finetune (local backend)
            parts = [p for p in url.path.split("/")[2:] if p]
            if len(parts) != 2:
                return self._send(400, {"error": "use /logs/{namespace}/{name}"})
            ns, name = parts
            if not re.fullmatch(r"[A-Za-z0-9][A-Za-z0-9._-]*", name):
                return self._send(400, {"error": "invalid job name"})
            if self.store.try_get("Finetune", name, ns) is None:
                return self._send(404, {"error": f"Finetune {ns}/{name} not found"})
            backend = getattr(self.manager, "training_backend", None) if self.manager else None
            tail = getattr(backend, "log_tail", None)
            if tail is None:
                return self._send(501, {"error": "log tail not supported by this backend"})
            return self._send(200, {"name": name, "log": tail(name, 100)})

        m = _PATH.match(url.path)
        if not m:
            return self._send(404, {"error": "not found"})
        kind = _resolve_kind(m["kind"])
        if kind is None:
            return self._send(404, {"error": f"unknown kind {m['kind']}"})

        if m["name"]:
            try:
                obj = self.store.get(kind, m["name"], m["ns"] or "default")
            except NotFound as e:
                return self._send(404, {"error": str(e)})
            return self._send(200, obj.to_dict())

        qs = parse_qs(url.query)
        labels = None
        if "labelSelector" in qs:
            try:
                labels = dict(
                    pair.split("=", 1)
                    for pair in qs["labelSelector"][0].split(",")
                )
            except ValueError:
                return self._send(
                    400, {"error": "labelSelector must be k=v[,k=v...]"}
                )
        ns = m["ns"] or qs.get("namespace", ["default"])[0]
        items = self.store.list(kind, namespace=None if ns == "-" else ns,
                                labels=labels)
        return self._send(200, {"kind": f"{kind}List",
                                "items": [o.to_dict() for o in items]})

    def do_POST(self):
        if not self._authorized():
            return self._send(401, {"error": "unauthorized"})
        m = _PATH.match(urlparse(self.path).path)
        if not m:
            return self._send(404, {"error": "not found"})
        kind = _resolve_kind(m["kind"])
        if kind is None:
            return self._send(404, {"error": f"unknown kind {m['kind']}"})
        try:
            payload = self._body()
            obj = KIND_BY_NAME[kind].from_dict(payload)
            if not obj.metadata.name:
                return self._send(400, {"error": "metadata.name is required"})
            created = self.store.create(obj)
            return self._send(201, created.to_dict())
        except AdmissionError as e:
            return self._send(422, {"error": f"admission denied: {e}"})
        except AlreadyExists as e:
            return self._send(409, {"error": str(e)})
        except (ValueError, KeyError, TypeError) as e:
            return self._send(400, {"error": str(e)})

    def do_PUT(self):
        if not self._authorized():
            return self._send(401, {"error": "unauthorized"})
        m = _PATH.match(urlparse(self.path).path)
        if not m or not m["name"]:
            return self._send(404, {"error": "not found"})
        kind = _resolve_kind(m["kind"])
        if kind is None:
            return self._send(404, {"error": f"unknown kind {m['kind']}"})
        try:
            obj = KIND_BY_NAME[kind].from_dict(self._body())
            if (obj.metadata.name != m["name"]
                    or obj.metadata.namespace != (m["ns"] or "default")):
                return self._send(400, {
                    "error": "metadata.name/namespace must match the URL path"})
            # kube semantics: a main-resource PUT cannot write .status (that
            # is the /status subresource, which this server doesn't expose) —
            # keep the stored status so a UI/CLI spec edit can't wipe
            # controller bookkeeping (scores, checkpoint refs)
            try:
                obj.status = self.store.get(
                    kind, m["name"], m["ns"] or "default").status
            except NotFound:
                pass
            updated = self.store.update(obj)
            return self._send(200, updated.to_dict())
        except AdmissionError as e:
            return self._send(422, {"error": f"admission denied: {e}"})
        except Conflict as e:
            return self._send(409, {"error": str(e)})
        except NotFound as e:
            return self._send(404, {"error": str(e)})
        except (ValueError, KeyError, TypeError) as e:
            return self._send(400, {"error": str(e)})

    def do_DELETE(self):
        if not self._authorized():
            return self._send(401, {"error": "unauthorized"})
        m = _PATH.match(urlparse(self.path).path)
        if not m or not m["name"]:
            return self._send(404, {"error": "not found"})
        kind = _resolve_kind(m["kind"])
        if kind is None:
            return self._send(404, {"error": f"unknown kind {m['kind']}"})
        try:
            self.store.delete(kind, m["name"], m["ns"] or "default")
            return self._send(200, {"status": "deleted"})
        except NotFound as e:
            return self._send(404, {"error": str(e)})


def serve_api(store, manager=None, port: int = 8080, host: str = "127.0.0.1",
              token: Optional[str] = None):
    """Start the API server on a background thread; returns (server, port).

    Binds loopback by default — this API is full-CRUD and can launch local
    processes via the backends; expose it beyond localhost only behind a
    bearer token (``token`` / DTX_API_TOKEN) or a real ingress."""
    import os

    token = token if token is not None else os.environ.get("DTX_API_TOKEN")
    handler = type("BoundApiHandler", (ApiHandler,), {"store": store,
                                                      "manager": manager,
                                                      "token": token or None})
    srv = ThreadingHTTPServer((host, port), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_port
