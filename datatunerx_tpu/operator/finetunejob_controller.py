"""FinetuneJob controller: the central pipeline state machine (reference
internal/controller/finetune/finetunejob_controller.go:71-560).

  Init → precondition (deps exist, back-reference bookkeeping :213-257)
       → create Finetune (:259-283, first pass → ErrRecalibrate 10s requeue)
       → mirror Finetune status (:285-355)
       → Finetune Successful → checkpoint-publish stage (replaces the
         privileged image-bake Job, :310-344 — TPU serving mounts the
         checkpoint URI directly, SURVEY.md §7.1; state name kept: BuildImage)
       → deploy serving, health-gate (:357-466)
       → create Scoring (built-in or plugin, :438-463)
       → score set → Successful + serving teardown (:468-511)
"""

from __future__ import annotations

import os

import time
from typing import Optional

from datatunerx_tpu.operator.api import (
    Dataset,
    Finetune,
    FINETUNE_GROUP_FINALIZER,
    FinetuneJob,
    Hyperparameter,
    LLM,
    LLMCheckpoint,
    Scoring,
)
from datatunerx_tpu.operator.errors import ErrRecalibrate
from datatunerx_tpu.operator.generate import (
    generate_builtin_scoring,
    generate_finetune,
    generate_plugin_scoring,
    generate_serving_spec,
)
from datatunerx_tpu.operator.reconciler import Result
from datatunerx_tpu.operator.store import AlreadyExists, NotFound, ObjectStore

SERVE_POLL_S = float(os.environ.get("DTX_SERVE_POLL_S", "5.0"))


class FinetuneJobController:
    kind = FinetuneJob

    def __init__(self, serving_backend, slice_pool=None):
        self.serving = serving_backend
        # optional SlicePool: caps gateway scale-up at free slice inventory
        # (capacity.serving_replicas_for), same pool FinetuneController uses
        self.slice_pool = slice_pool

    # re-enter when owned Finetune / Scoring change (reference Watches wiring,
    # finetunejob_controller.go:162-206). Owner references already cover this
    # via the manager; serving state changes are polled.

    def reconcile(self, store: ObjectStore, job: FinetuneJob) -> Optional[Result]:
        meta = job.metadata

        if meta.deletion_timestamp:
            return self._cleanup(store, job)

        if FINETUNE_GROUP_FINALIZER not in meta.finalizers:
            meta.finalizers.append(FINETUNE_GROUP_FINALIZER)
            store.update(job)
            return Result(requeue_after=0)

        state = job.status.get("state", "")
        if state in (FinetuneJob.STATE_SUCCESSFUL, FinetuneJob.STATE_FAILED):
            return None

        if state == "":
            job.status["state"] = FinetuneJob.STATE_INIT
            store.update(job)
            return Result(requeue_after=0)

        self._reconcile_precondition(store, job)

        ft = self._reconcile_finetune_send(store, job)

        result = self._reconcile_by_finetune_status(store, job, ft)
        if result is not None:
            return result

        result = self._reconcile_serving(store, job)
        if result is not None:
            return result

        return self._reconcile_by_scoring_status(store, job)

    # ------------------------------------------------------- preconditions
    def _reconcile_precondition(self, store: ObjectStore, job: FinetuneJob):
        """Verify LLM/Hyperparameter/Dataset exist; append this job to their
        status.referenceFinetuneName (reference :213-257)."""
        ft_spec = job.spec.get("finetune", {}).get("finetuneSpec", {})
        refs = [
            (LLM, ft_spec.get("llm")),
            (Hyperparameter,
             (ft_spec.get("hyperparameter") or {}).get("hyperparameterRef")),
            (Dataset, ft_spec.get("dataset")),
        ]
        missing = []
        for kind, name in refs:
            if not name:
                missing.append(kind.kind)
                continue
            obj = store.try_get(kind, name, job.metadata.namespace)
            if obj is None:
                missing.append(f"{kind.kind}/{name}")
                continue
            back = obj.status.setdefault("referenceFinetuneName", [])
            if job.metadata.name not in back:
                back.append(job.metadata.name)
                store.update(obj)
        if missing:
            raise ErrRecalibrate(
                f"{job.metadata.namespace}/{job.metadata.name}: missing {missing}"
            )

    def _reconcile_finetune_send(self, store: ObjectStore, job: FinetuneJob) -> Finetune:
        """Create the Finetune child on first pass (reference :259-283)."""
        ft = generate_finetune(job)
        existing = store.try_get(Finetune, ft.metadata.name, ft.metadata.namespace)
        if existing is None:
            store.create(ft)
            raise ErrRecalibrate("finetune created; waiting for status")
        return existing

    # ----------------------------------------------------- finetune status
    def _reconcile_by_finetune_status(
        self, store: ObjectStore, job: FinetuneJob, ft: Finetune
    ) -> Optional[Result]:
        ft_state = ft.status.get("state", "")
        job.status["finetuneStatus"] = dict(ft.status)

        if ft_state in ("", Finetune.STATE_INIT, Finetune.STATE_PENDING,
                        Finetune.STATE_RUNNING):
            if job.status.get("state") != FinetuneJob.STATE_FINETUNE:
                job.status["state"] = FinetuneJob.STATE_FINETUNE
            store.update(job)
            return Result(requeue_after=SERVE_POLL_S)

        if ft_state == Finetune.STATE_FAILED:
            job.status["state"] = FinetuneJob.STATE_FAILED
            store.update(job)
            return None

        # Successful → checkpoint-publish stage (reference BuildImage, :296-344)
        if job.status.get("state") == FinetuneJob.STATE_FINETUNE:
            ckpt_info = ft.status.get("llmCheckpoint") or {}
            ref = ckpt_info.get("llmCheckpointRef")
            ckpt = store.try_get(LLMCheckpoint, ref, job.metadata.namespace) if ref else None
            if ckpt is None:
                return Result(requeue_after=SERVE_POLL_S)
            # record the serving artifact pointers (reference fills
            # CheckpointImage{Name, CheckPointPath, LLMPath}, :328-336)
            ckpt.spec["checkpointImage"] = {
                "name": f"ckpt-{job.metadata.name}-{time.strftime('%Y%m%d')}",
                "checkPointPath": ckpt.spec.get("checkpoint"),
                "llmPath": (ckpt.spec.get("image") or {}).get("path"),
            }
            store.update(ckpt)
            job.status["state"] = FinetuneJob.STATE_BUILDIMAGE
            job.status.setdefault("result", {})["modelExportResult"] = True
            job.status["result"]["image"] = ckpt.spec["checkpointImage"]["name"]
            job.status["result"]["checkpointPath"] = ckpt.spec.get("checkpoint")
            store.update(job)
            return Result(requeue_after=0)
        return None

    # -------------------------------------------------------------- serving
    def _reconcile_serving(self, store: ObjectStore, job: FinetuneJob) -> Optional[Result]:
        if job.status.get("state") not in (FinetuneJob.STATE_BUILDIMAGE,
                                           FinetuneJob.STATE_SERVE):
            return None

        name = job.metadata.name
        serve_status = self.serving.status(name)
        if serve_status == "NotFound":
            ckpt_ref = (job.status.get("finetuneStatus", {})
                        .get("llmCheckpoint", {}) or {}).get("llmCheckpointRef")
            ckpt = store.try_get(LLMCheckpoint, ckpt_ref, job.metadata.namespace)
            info = {
                "llmPath": (ckpt.spec.get("checkpointImage") or {}).get("llmPath")
                if ckpt else None,
                "checkpointPath": ckpt.spec.get("checkpoint") if ckpt else None,
            }
            self.serving.deploy(name, generate_serving_spec(job, {
                "llmPath": info["llmPath"],
                "checkpointPath": info["checkpointPath"],
            }))
            job.status["state"] = FinetuneJob.STATE_SERVE
            store.update(job)
            return Result(requeue_after=SERVE_POLL_S)

        if serve_status != "HEALTHY":
            if serve_status == "FAILED":
                job.status["state"] = FinetuneJob.STATE_FAILED
                store.update(job)
                return None
            return Result(requeue_after=SERVE_POLL_S)

        # HEALTHY (reference gate :423-424) → record endpoints + create Scoring
        endpoint = self.serving.endpoint(name) or f"http://{name}.{job.metadata.namespace}.svc:8000"
        result = job.status.setdefault("result", {})
        changed = result.get("serve") != endpoint
        result["serve"] = endpoint
        result["dashboard"] = endpoint.replace(":8000", ":8080")
        inference_url = endpoint.rstrip("/") + "/chat/completions"  # reference :433

        changed = self._reconcile_autoscale(job) or changed

        if store.try_get(Scoring, name, job.metadata.namespace) is None:
            if job.spec.get("scoringPluginConfig") and job.spec["scoringPluginConfig"].get("name"):
                scoring = generate_plugin_scoring(job, inference_url)
            else:
                scoring = generate_builtin_scoring(job, inference_url)
            try:
                store.create(scoring)
            except AlreadyExists:
                pass
            changed = True
        if changed:
            store.update(job)
        return None  # scoring watch / requeue drives the rest

    def _reconcile_autoscale(self, job: FinetuneJob) -> bool:
        """Poll the gateway's autoscale hint and apply the capacity-clamped
        replica count (gateway/autoscale.py → capacity.serving_replicas_for).
        No-op for single-replica/no-gateway deployments and backends that
        don't expose scale_hint/scale. Returns True when job.status changed."""
        serve_cfg = job.spec.get("serveConfig", {}) or {}
        gatewayed = (bool(serve_cfg.get("gateway"))
                     or int(serve_cfg.get("replicas") or 1) > 1)
        hint_fn = getattr(self.serving, "scale_hint", None)
        scale_fn = getattr(self.serving, "scale", None)
        if not gatewayed or hint_fn is None or scale_fn is None:
            return False
        hint = hint_fn(job.metadata.name)
        if hint is None:
            return False

        from datatunerx_tpu.operator.capacity import serving_replicas_for

        desired = serving_replicas_for(
            hint,
            min_replicas=int(serve_cfg.get("minReplicas") or 1),
            max_replicas=int(serve_cfg.get("maxReplicas")
                             or serve_cfg.get("replicas") or 1),
            free_slices=(self.slice_pool.free_count()
                         if self.slice_pool is not None else None),
        )
        result = job.status.setdefault("result", {})
        summary = {
            "replicas": hint["replicas"],
            "desiredReplicas": desired,
            "queueDepth": hint["queueDepth"],
            "shedCount": hint["shedCount"],
            "p95LatencySeconds": hint["p95LatencySeconds"],
            "reason": hint["reason"],
        }
        changed = result.get("serving") != summary
        result["serving"] = summary
        if desired != hint["replicas"]:
            try:
                scale_fn(job.metadata.name, desired)
            except Exception:  # noqa: BLE001 — next poll retries; don't
                pass           # fail the reconcile over a scale hiccup
        return changed

    # -------------------------------------------------------------- scoring
    def _reconcile_by_scoring_status(self, store: ObjectStore, job: FinetuneJob) -> Optional[Result]:
        if job.status.get("state") != FinetuneJob.STATE_SERVE:
            return None
        scoring = store.try_get(Scoring, job.metadata.name, job.metadata.namespace)
        if scoring is None:
            return Result(requeue_after=SERVE_POLL_S)
        if scoring.status.get("error"):
            # permanent scoring failure (invalid spec) — fail the job and tear
            # down serving rather than polling SERVE forever
            job.status["state"] = FinetuneJob.STATE_FAILED
            job.status.setdefault("result", {})["scoringError"] = scoring.status["error"]
            store.update(job)
            self.serving.delete(job.metadata.name)
            return None
        if scoring.status.get("score") is None:
            return Result(requeue_after=SERVE_POLL_S)
        # score set → Successful; tear down serving (reference :485-508)
        job.status["state"] = FinetuneJob.STATE_SUCCESSFUL
        job.status.setdefault("result", {})["score"] = scoring.status["score"]
        job.status["stats"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        store.update(job)
        self.serving.delete(job.metadata.name)
        return None

    # -------------------------------------------------------------- cleanup
    def _cleanup(self, store: ObjectStore, job: FinetuneJob) -> Optional[Result]:
        """Reference reconcileCleaner (:513-560): delete children, clear
        back-references, drop finalizer."""
        name, ns = job.metadata.name, job.metadata.namespace
        self.serving.delete(name)
        for kind, child in ((Scoring, name), (Finetune, f"{name}-finetune")):
            try:
                store.delete(kind, child, ns)
            except NotFound:
                pass
        ft_name = job.spec.get("finetune", {}).get("name")
        if ft_name:
            try:
                store.delete(Finetune, ft_name, ns)
            except NotFound:
                pass
        ft_spec = job.spec.get("finetune", {}).get("finetuneSpec", {})
        for kind, ref in (
            (LLM, ft_spec.get("llm")),
            (Hyperparameter, (ft_spec.get("hyperparameter") or {}).get("hyperparameterRef")),
            (Dataset, ft_spec.get("dataset")),
        ):
            if not ref:
                continue
            obj = store.try_get(kind, ref, ns)
            if obj and name in obj.status.get("referenceFinetuneName", []):
                obj.status["referenceFinetuneName"].remove(name)
                store.update(obj)
        if FINETUNE_GROUP_FINALIZER in job.metadata.finalizers:
            job.metadata.finalizers.remove(FINETUNE_GROUP_FINALIZER)
            store.update(job)
        return None
