"""Kubernetes-native admission: HTTPS webhook server + TLS cert rotation.

The reference boots a webhook server on :9443 behind cert-rotator-provisioned
TLS and registers 5 validating/mutating webhooks (reference
cmd/controller-manager/app/controller_manager.go:83-135; the webhook bodies
live in the unvendored meta-server module). Round 2 only enforced these rules
in-process (webhooks.AdmittingStore), so a ``kubectl apply`` in ``--backend
kube`` mode bypassed validation entirely (VERDICT r2 missing #1). This module
closes that gap the Kubernetes-native way:

- ``CertManager`` — self-signed CA + server certificate generation and
  time-based rotation (cert-rotator equivalent, in-process): certs are
  regenerated when less than ``refresh_margin`` of validity remains, and the
  fresh CA bundle is re-patched into the webhook configurations.
- ``AdmissionWebhookServer`` — TLS HTTP server answering AdmissionReview v1
  on ``/validate`` (VALIDATORS) and ``/mutate`` (DEFAULTERS as a JSONPatch).
- ``webhook_configurations()`` — renders the ValidatingWebhookConfiguration /
  MutatingWebhookConfiguration objects (failurePolicy: Fail, like the
  reference's meta-server webhooks) with the caBundle inline.
- ``install_webhooks()`` — creates/updates those configurations through a
  KubeClient (the cert-rotator's "write the caBundle into the config" step).

The fake apiserver (tests/fake_apiserver.py) honors stored webhook
configurations on create/update, so the admission path is exercised over real
HTTPS + AdmissionReview wire format in tests.
"""

from __future__ import annotations

import base64
import copy
import datetime
import json
import os
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from datatunerx_tpu.operator.api import KIND_BY_NAME, ObjectMeta
from datatunerx_tpu.operator.webhooks import (
    DEFAULTERS,
    VALIDATORS,
    AdmissionError,
)

# The 5 kinds the reference registers webhooks for
# (controller_manager.go:114-134).
WEBHOOK_KINDS = ("FinetuneJob", "FinetuneExperiment", "LLM", "Hyperparameter",
                 "Dataset")


# ------------------------------------------------------------ certificates

def _generate_ca_and_cert(
    dns_names: List[str], validity_days: int
) -> Tuple[bytes, bytes, bytes]:
    """→ (ca_pem, server_cert_pem, server_key_pem): a fresh self-signed CA
    and a CA-signed server leaf for ``dns_names`` (cert-rotator's shape)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    now = datetime.datetime.now(datetime.timezone.utc)
    not_after = now + datetime.timedelta(days=validity_days)

    ca_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    ca_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "dtx-webhook-ca")])
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name)
        .issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .sign(ca_key, hashes.SHA256())
    )

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    leaf_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, dns_names[0])])
    sans = []
    for n in dns_names:
        try:
            import ipaddress

            sans.append(x509.IPAddress(ipaddress.ip_address(n)))
        except ValueError:
            sans.append(x509.DNSName(n))
    cert = (
        x509.CertificateBuilder()
        .subject_name(leaf_name)
        .issuer_name(ca_name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(not_after)
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .sign(ca_key, hashes.SHA256())
    )

    ca_pem = ca_cert.public_bytes(serialization.Encoding.PEM)
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption(),
    )
    return ca_pem, cert_pem, key_pem


def _pem_expiry(cert_pem: bytes) -> Optional[datetime.datetime]:
    try:
        from cryptography import x509

        return x509.load_pem_x509_certificate(cert_pem).not_valid_after_utc
    except Exception:  # noqa: BLE001 — absent/garbled = treat as expired
        return None


def _pem_sans(cert_pem: bytes) -> Optional[set]:
    """DNS + IP SANs of a PEM cert (None if absent/garbled)."""
    try:
        from cryptography import x509
        from cryptography.x509.oid import ExtensionOID

        cert = x509.load_pem_x509_certificate(cert_pem)
        sans = cert.extensions.get_extension_for_oid(
            ExtensionOID.SUBJECT_ALTERNATIVE_NAME).value
        return {str(v) for v in sans.get_values_for_type(x509.DNSName)} | {
            str(v) for v in sans.get_values_for_type(x509.IPAddress)}
    except Exception:  # noqa: BLE001 — absent/unsupported = regenerate
        return None


class CertManager:
    """Provision + rotate the webhook serving cert (cert-rotator equivalent,
    reference controller_manager.go:83-111). Certs live under ``cert_dir`` as
    tls.crt / tls.key / ca.crt — the same layout cert-rotator writes into the
    mounted secret."""

    def __init__(self, cert_dir: str, dns_names: Optional[List[str]] = None,
                 validity_days: int = 365, refresh_margin_days: int = 30):
        self.cert_dir = cert_dir
        self.dns_names = list(dns_names or ["localhost", "127.0.0.1"])
        self.validity_days = validity_days
        self.refresh_margin = datetime.timedelta(days=refresh_margin_days)
        self._lock = threading.Lock()

    @property
    def cert_path(self) -> str:
        return os.path.join(self.cert_dir, "tls.crt")

    @property
    def key_path(self) -> str:
        return os.path.join(self.cert_dir, "tls.key")

    @property
    def ca_path(self) -> str:
        return os.path.join(self.cert_dir, "ca.crt")

    def _cert_pem(self) -> Optional[bytes]:
        try:
            with open(self.cert_path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def _expiry(self) -> Optional[datetime.datetime]:
        pem = self._cert_pem()
        return _pem_expiry(pem) if pem else None

    def _cert_names(self) -> Optional[set]:
        """DNS + IP SANs of the cert on disk (None if absent/garbled)."""
        pem = self._cert_pem()
        return _pem_sans(pem) if pem else None

    def _pem_stale(self, cert_pem: Optional[bytes]) -> bool:
        """Rotation test on raw PEM (shared with the Secret-backed variant):
        absent, inside the refresh margin, or SAN drift — a cert from an
        older deploy (e.g. pre-service-SAN localhost-only) must regenerate
        even with months of validity left, or apiserver TLS verification of
        service-style routing keeps failing cluster-wide."""
        if not cert_pem:
            return True
        exp = _pem_expiry(cert_pem)
        if exp is None:
            return True
        now = datetime.datetime.now(datetime.timezone.utc)
        if exp - now < self.refresh_margin:
            return True
        names = _pem_sans(cert_pem)
        return names is None or not set(self.dns_names) <= names

    def needs_rotation(self) -> bool:
        return self._pem_stale(self._cert_pem())

    def _write_local(self, ca: bytes, cert: bytes, key: bytes):
        os.makedirs(self.cert_dir, exist_ok=True)
        for path, data in ((self.ca_path, ca), (self.cert_path, cert),
                           (self.key_path, key)):
            with open(path, "wb") as f:
                f.write(data)

    def ensure(self, as_leader: bool = True) -> bool:
        """Generate certs if absent or within the refresh margin.
        Returns True when new certs were written (callers must then re-patch
        the caBundle into the webhook configurations and reload TLS).

        ``as_leader`` is accepted for interface parity with the HA
        Secret-backed variant; a local cert dir has exactly one writer
        (replicas=1 by construction), so it is ignored here."""
        del as_leader
        with self._lock:
            if not self.needs_rotation():
                return False
            ca, cert, key = _generate_ca_and_cert(
                self.dns_names, self.validity_days)
            self._write_local(ca, cert, key)
            return True

    def ca_bundle_b64(self) -> str:
        with open(self.ca_path, "rb") as f:
            return base64.b64encode(f.read()).decode()


class SecretBackedCertManager(CertManager):
    """HA cert manager (VERDICT r3 #6): the CA + serving cert live in one
    Kubernetes Secret, so every replica serves TLS from the SAME chain — the
    reference's cert-rotator keeps its certs in a Secret shared by replicas
    for exactly this reason (reference controller_manager.go:83-111).

    Protocol:
    - ``ensure(as_leader=True)`` (boot, or the elected leader's rotation
      loop): if the Secret is absent/stale, generate fresh certs and
      create-or-CAS-replace the Secret. A lost write race (409) converges on
      the winner's certs — at most one generation survives, so a fresh HA
      install booting N replicas still ends with ONE CA.
    - ``ensure(as_leader=False)`` (standby rotation loop): NEVER generates;
      pulls whatever the Secret currently holds, returning True when the
      local materialization changed so the caller hot-reloads its TLS
      context. Rotation is thereby gated on the election leader.

    ``cert_dir`` is a local materialization of the Secret (ssl needs file
    paths); it is not shared between replicas and needs no volume."""

    SECRET_KEYS = ("ca.crt", "tls.crt", "tls.key")

    def __init__(self, client, namespace: str, secret_name: str,
                 cert_dir: str, dns_names: Optional[List[str]] = None,
                 validity_days: int = 365, refresh_margin_days: int = 30):
        super().__init__(cert_dir, dns_names=dns_names,
                         validity_days=validity_days,
                         refresh_margin_days=refresh_margin_days)
        self.client = client
        self.namespace = namespace
        self.secret_name = secret_name

    # ------------------------------------------------------------ secret io
    def _read_secret(self) -> Optional[dict]:
        from datatunerx_tpu.operator.kubeclient import ApiError

        try:
            return self.client.get("", "v1", "secrets", self.namespace,
                                   self.secret_name)
        except ApiError as e:
            if e.status == 404:
                return None
            raise

    @staticmethod
    def _decode(data: dict) -> Dict[str, bytes]:
        out = {}
        for k, v in (data or {}).items():
            try:
                out[k] = base64.b64decode(v)
            except Exception:  # noqa: BLE001 — garbled entry = stale
                out[k] = b""
        return out

    def _materialize(self, data: dict) -> bool:
        """Write the Secret payload into cert_dir; True when changed."""
        decoded = self._decode(data)
        if not all(decoded.get(k) for k in self.SECRET_KEYS):
            return False
        changed = False
        os.makedirs(self.cert_dir, exist_ok=True)
        for k in self.SECRET_KEYS:
            path = os.path.join(self.cert_dir, k)
            try:
                with open(path, "rb") as f:
                    cur = f.read()
            except FileNotFoundError:
                cur = None
            if cur != decoded[k]:
                with open(path, "wb") as f:
                    f.write(decoded[k])
                changed = True
        return changed

    # ------------------------------------------------------------- rotation
    def needs_rotation(self) -> bool:
        sec = self._read_secret()
        data = self._decode((sec or {}).get("data") or {})
        return self._pem_stale(data.get("tls.crt"))

    def ensure(self, as_leader: bool = True) -> bool:
        from datatunerx_tpu.operator.kubeclient import ApiError

        with self._lock:
            sec = self._read_secret()
            data = (sec or {}).get("data") or {}
            stale = self._pem_stale(self._decode(data).get("tls.crt"))
            if not stale or not as_leader:
                # healthy Secret (or standby waiting on the leader): converge
                # the local materialization on whatever the cluster holds
                return self._materialize(data)

            ca, cert, key = _generate_ca_and_cert(
                self.dns_names, self.validity_days)
            body = {
                "apiVersion": "v1", "kind": "Secret",
                "metadata": {"name": self.secret_name,
                             "namespace": self.namespace},
                "type": "kubernetes.io/tls",
                "data": {
                    "ca.crt": base64.b64encode(ca).decode(),
                    "tls.crt": base64.b64encode(cert).decode(),
                    "tls.key": base64.b64encode(key).decode(),
                },
            }
            try:
                if sec is None:
                    self.client.create("", "v1", "secrets", self.namespace,
                                       body)
                else:
                    body["metadata"]["resourceVersion"] = (
                        sec.get("metadata") or {}).get("resourceVersion")
                    self.client.replace("", "v1", "secrets", self.namespace,
                                        self.secret_name, body)
            except ApiError as e:
                if e.status != 409:
                    raise
                # lost the generation race: exactly one writer wins; adopt
                # the winner's certs instead of fighting over the CA
                sec = self._read_secret()
                return self._materialize((sec or {}).get("data") or {})
            return self._materialize(body["data"])


# --------------------------------------------------------- admission logic

def _shim(kind: str, raw: dict):
    """Wrap a raw admission object into the CustomResource the validators
    expect (only .kind/.metadata.name/.spec are consumed)."""
    cls = KIND_BY_NAME[kind]
    meta = raw.get("metadata") or {}
    return cls(
        metadata=ObjectMeta(name=meta.get("name", ""),
                            namespace=meta.get("namespace", "default")),
        spec=raw.get("spec") or {},
    )


def _json_patch(before: dict, after: dict, path: str = "") -> List[dict]:
    """Minimal RFC-6902 patch for defaulting diffs (adds/replaces only —
    defaulters never delete fields)."""
    ops: List[dict] = []
    for k in after:
        esc = str(k).replace("~", "~0").replace("/", "~1")
        p = f"{path}/{esc}"
        if k not in before:
            ops.append({"op": "add", "path": p, "value": after[k]})
        elif isinstance(before[k], dict) and isinstance(after[k], dict):
            ops.extend(_json_patch(before[k], after[k], p))
        elif before[k] != after[k]:
            ops.append({"op": "replace", "path": p, "value": after[k]})
    return ops


def review_validate(request: dict) -> dict:
    """AdmissionReview request → response dict (validating)."""
    uid = request.get("uid", "")
    obj = request.get("object") or {}
    kind = (request.get("kind") or {}).get("kind") or obj.get("kind", "")
    validator = VALIDATORS.get(kind)
    if validator is None:
        return {"uid": uid, "allowed": True}
    try:
        validator(_shim(kind, obj))
    except AdmissionError as e:
        return {
            "uid": uid,
            "allowed": False,
            "status": {"code": 422, "message": str(e)},
        }
    except Exception as e:  # noqa: BLE001 — malformed spec shape
        return {
            "uid": uid,
            "allowed": False,
            "status": {"code": 422, "message": f"malformed spec: {e}"},
        }
    return {"uid": uid, "allowed": True}


def review_mutate(request: dict) -> dict:
    """AdmissionReview request → response dict (defaulting, JSONPatch)."""
    uid = request.get("uid", "")
    obj = request.get("object") or {}
    kind = (request.get("kind") or {}).get("kind") or obj.get("kind", "")
    defaulter = DEFAULTERS.get(kind)
    if defaulter is None:
        return {"uid": uid, "allowed": True}
    shim = _shim(kind, copy.deepcopy(obj))
    try:
        defaulter(shim)
    except Exception as e:  # noqa: BLE001
        return {
            "uid": uid,
            "allowed": False,
            "status": {"code": 422, "message": f"defaulting failed: {e}"},
        }
    if not isinstance(obj.get("spec"), dict):
        # RFC 6902: 'add /spec/foo' fails when /spec is absent OR null
        # (`spec:` with no value in YAML) — a real apiserver would reject
        # the patch (and failurePolicy Fail would then deny the create).
        # Add/replace the whole spec in one op.
        op = "replace" if "spec" in obj else "add"
        ops = [{"op": op, "path": "/spec", "value": shim.spec}] \
            if shim.spec else []
    else:
        ops = _json_patch(obj["spec"], shim.spec, path="/spec")
    resp = {"uid": uid, "allowed": True}
    if ops:
        resp["patchType"] = "JSONPatch"
        resp["patch"] = base64.b64encode(json.dumps(ops).encode()).decode()
    return resp


# ----------------------------------------------------------------- server

class AdmissionWebhookServer:
    """TLS server answering admission.k8s.io/v1 AdmissionReview on
    /validate and /mutate (reference webhook server :9443,
    controller_manager.go:70)."""

    def __init__(self, cert_manager: CertManager, host: str = "0.0.0.0",
                 port: int = 9443):
        self.certs = cert_manager
        rotated = self.certs.ensure()
        del rotated

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    review = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError:
                    self.send_response(400)
                    self.end_headers()
                    return
                request = review.get("request") or {}
                if self.path.startswith("/validate"):
                    response = review_validate(request)
                elif self.path.startswith("/mutate"):
                    response = review_mutate(request)
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                body = json.dumps({
                    "apiVersion": "admission.k8s.io/v1",
                    "kind": "AdmissionReview",
                    "response": response,
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.server.daemon_threads = True
        self._wrap_tls()
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._rotator: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _wrap_tls(self):
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.certs.cert_path, self.certs.key_path)
        self._ssl_ctx = ctx
        self.server.socket = ctx.wrap_socket(self.server.socket,
                                             server_side=True)

    @property
    def port(self) -> int:
        return self.server.server_port

    def start(self, rotation_check_s: float = 0.0,
              on_rotate=None, is_leader=None) -> "AdmissionWebhookServer":
        """``rotation_check_s`` > 0 starts a background expiry check: when
        the cert enters the refresh margin it is regenerated, the TLS context
        reloaded in place, and ``on_rotate(ca_bundle_b64)`` invoked so the
        caller can re-patch the webhook configurations.

        ``is_leader`` (HA): a zero-arg callable consulted each check. Only
        the leader generates new certs; a standby whose Secret-backed cert
        manager observes a rotation still hot-reloads its own TLS context
        (so it keeps serving the shared chain) but leaves the caBundle
        re-patch to the leader that performed the rotation."""
        self._thread.start()
        if rotation_check_s > 0:
            def loop():
                while not self._stop.wait(rotation_check_s):
                    try:
                        leader = True if is_leader is None \
                            else bool(is_leader())
                        if self.certs.ensure(as_leader=leader):
                            # live reload: new handshakes get the new chain
                            self._ssl_ctx.load_cert_chain(
                                self.certs.cert_path, self.certs.key_path)
                            if on_rotate is not None and leader:
                                on_rotate(self.certs.ca_bundle_b64())
                    except Exception as e:  # noqa: BLE001 — transient
                        # apiserver errors must not kill the rotator thread:
                        # a dead rotator means certs silently expire later
                        print(f"[webhook-server] rotation check failed: {e}",
                              flush=True)

            self._rotator = threading.Thread(target=loop, daemon=True)
            self._rotator.start()
        return self

    def stop(self):
        self._stop.set()
        self.server.shutdown()
        self.server.server_close()


# ------------------------------------------------------- configurations

def webhook_configurations(ca_bundle_b64: str, base_url: str) -> List[dict]:
    """Render the Validating/MutatingWebhookConfiguration objects for the 5
    webhook kinds (reference controller_manager.go:114-134). ``base_url``
    points at this operator's webhook server (url-style clientConfig; the
    in-cluster service-style variant is a deploy-time substitution)."""
    def rules(kinds):
        by_group: Dict[str, List[str]] = {}
        for kind in kinds:
            cls = KIND_BY_NAME[kind]
            group = cls.api_version.partition("/")[0]
            by_group.setdefault(group, []).append(cls.kind.lower() + "s")
        return [
            {
                "apiGroups": [g],
                "apiVersions": ["v1beta1"],
                "operations": ["CREATE", "UPDATE"],
                "resources": sorted(plurals),
            }
            for g, plurals in sorted(by_group.items())
        ]

    validating = {
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "ValidatingWebhookConfiguration",
        "metadata": {"name": "datatunerx-validating-webhook"},
        "webhooks": [{
            "name": "validate.datatunerx.io",
            "admissionReviewVersions": ["v1"],
            "sideEffects": "None",
            "failurePolicy": "Fail",
            "clientConfig": {
                "url": f"{base_url}/validate",
                "caBundle": ca_bundle_b64,
            },
            "rules": rules(WEBHOOK_KINDS),
        }],
    }
    mutating = {
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "MutatingWebhookConfiguration",
        "metadata": {"name": "datatunerx-mutating-webhook"},
        "webhooks": [{
            "name": "mutate.datatunerx.io",
            "admissionReviewVersions": ["v1"],
            "sideEffects": "None",
            "failurePolicy": "Fail",
            "clientConfig": {
                "url": f"{base_url}/mutate",
                "caBundle": ca_bundle_b64,
            },
            "rules": rules([k for k in WEBHOOK_KINDS if k in DEFAULTERS]),
        }],
    }
    return [mutating, validating]  # mutate before validate (apiserver order)


def install_webhooks(client, ca_bundle_b64: str, base_url: str):
    """Ensure the webhook configurations exist and carry this CA bundle —
    the cert-rotator's caBundle-injection step.

    When a configuration already exists (e.g. the deploy-time
    ``deploy/webhooks.yaml`` with a service-style clientConfig), the
    caBundle is injected and failurePolicy restored to the rendered
    fail-closed value (a degraded no-cryptography boot flips it to Ignore —
    see manager._neutralize_webhook_configs — and a later healthy start
    must undo that, or one degraded run permanently converts admission to
    fail-open). Routing: a service-style clientConfig (in-cluster DNS, the
    apiserver resolves it to whatever pod currently backs the Service) is
    the cluster operator's choice and survives restarts untouched; a
    url-style clientConfig names ONE process's address, so the caller that
    is now serving admission must re-point it at its own ``base_url`` — on
    HA failover the promoted standby re-installs, and leaving the URL at
    the dead leader would keep fail-closed admission returning Connection
    refused cluster-wide. Fresh configurations (dev / fake-apiserver runs)
    are created url-style against ``base_url``."""
    for cfg in webhook_configurations(ca_bundle_b64, base_url):
        plural = cfg["kind"].lower() + "s"
        path = (f"/apis/admissionregistration.k8s.io/v1/{plural}/"
                f"{cfg['metadata']['name']}")
        try:
            cur = client.request("GET", path)
        except Exception:  # noqa: BLE001 — not found: create url-style
            client.request(
                "POST", f"/apis/admissionregistration.k8s.io/v1/{plural}",
                body=cfg)
            continue
        cur = copy.deepcopy(cur)
        rendered_policy = {wh["name"]: wh.get("failurePolicy", "Fail")
                           for wh in cfg["webhooks"]}
        rendered_url = {wh["name"]: wh["clientConfig"]["url"]
                        for wh in cfg["webhooks"]}
        for wh in cur.get("webhooks") or []:
            cc = wh.setdefault("clientConfig", {})
            cc["caBundle"] = ca_bundle_b64
            if "url" in cc and wh.get("name") in rendered_url:
                cc["url"] = rendered_url[wh["name"]]
            if wh.get("name") in rendered_policy:
                wh["failurePolicy"] = rendered_policy[wh["name"]]
        client.request("PUT", path, body=cur)
