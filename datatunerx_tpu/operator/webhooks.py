"""Admission webhooks: validation + defaulting for the 5 webhook-registered
kinds (reference cmd/controller-manager/app/controller_manager.go:114-134
registers FinetuneJob, FinetuneExperiment, LLM, Hyperparameter, Dataset; the
validate/default bodies live in the unvendored meta-server module, so rules
here are re-derived from field semantics, SURVEY.md §2.3 + parser asserts,
cmd/tuning/parser.py:211-221).
"""

from __future__ import annotations

from typing import Callable, Dict, List

from datatunerx_tpu.operator.api import (
    CustomResource,
    Dataset,
    FinetuneExperiment,
    FinetuneJob,
    Hyperparameter,
    LLM,
    Scoring,
)

SCHEDULERS = ("cosine", "linear", "constant", "constant_with_warmup",
              "cosine_with_restarts", "polynomial")
OPTIMIZERS = ("adamw", "adam", "sgd", "adafactor", "lion")
LORA_TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj",
                "gate_proj", "up_proj", "down_proj")


class AdmissionError(Exception):
    pass


def _require(cond: bool, msg: str):
    if not cond:
        raise AdmissionError(msg)


# ------------------------------------------------------------- validators

def validate_hyperparameter(obj: CustomResource):
    p = obj.spec.get("parameters", {})
    _require(isinstance(p, dict), "spec.parameters must be an object")
    if p.get("scheduler"):
        _require(str(p["scheduler"]).lower() in SCHEDULERS,
                 f"scheduler must be one of {SCHEDULERS}")
    if p.get("optimizer"):
        _require(str(p["optimizer"]).lower() in OPTIMIZERS,
                 f"optimizer must be one of {OPTIMIZERS}")
    _require(not (_truthy(p.get("int4")) and _truthy(p.get("int8"))),
             "int4 and int8 are mutually exclusive")
    for key, lo, hi in (("loRA_Dropout", 0.0, 1.0), ("warmupRatio", 0.0, 1.0)):
        if p.get(key) is not None:
            v = _num(p[key], key)
            _require(lo <= v <= hi, f"{key} must be in [{lo}, {hi}]")
    for key in ("loRA_R", "epochs", "blockSize", "batchSize", "gradAccSteps"):
        if p.get(key) is not None:
            v = _num(p[key], key)
            _require(v > 0, f"{key} must be positive")
    if p.get("learningRate") is not None:
        _require(_num(p["learningRate"], "learningRate") > 0,
                 "learningRate must be positive")
    if p.get("loRATarget"):
        for t in str(p["loRATarget"]).split(","):
            _require(t.strip() in LORA_TARGETS,
                     f"invalid lora target {t.strip()!r}")
    if p.get("trainerType"):
        tt = str(p["trainerType"]).lower()
        _require(tt in ("sft", "dpo", "rm", "ppo"),
                 "trainerType must be sft, dpo, rm, or ppo")
        if tt == "ppo":
            _require(bool(p.get("rewardModel")),
                     "trainerType ppo requires parameters.rewardModel (an "
                     "rm-stage run directory under the storage path)")
        if tt in ("dpo", "rm", "ppo"):
            # catch the unrunnable combo at admission, not after the JobSet
            # burned its retries: DPO needs the LoRA policy/reference trick,
            # RM keeps the reward model a frozen-base adapter + value head.
            from datatunerx_tpu.operator.generate import is_peft

            _require(is_peft(p),
                     f"trainerType {tt} requires PEFT (LoRA) — the frozen "
                     "base serves as DPO reference policy / RM backbone")


def validate_dataset(obj: CustomResource):
    info = obj.spec.get("datasetMetadata", {}).get("datasetInfo", {})
    subsets = info.get("subsets")
    _require(bool(subsets), "datasetInfo.subsets must not be empty")
    train = subsets[0].get("splits", {}).get("train", {})
    _require(bool(train.get("file")), "subsets[0].splits.train.file is required")
    for f in info.get("features", []) or []:
        _require(f.get("name") in ("instruction", "response",
                                   "chosen", "rejected"),
                 "feature name must be one of instruction/response (SFT) "
                 "or chosen/rejected (DPO preference datasets)")
        _require(bool(f.get("mapTo")), "feature mapTo is required")


def validate_llm(obj: CustomResource):
    _require(bool(obj.metadata.name), "llm name required")


def validate_finetunejob(obj: CustomResource):
    ft = obj.spec.get("finetune", {})
    _require(isinstance(ft, dict) and bool(ft.get("finetuneSpec")),
             "spec.finetune.finetuneSpec is required")
    spec = ft["finetuneSpec"]
    for key in ("llm", "dataset"):
        _require(bool(spec.get(key)), f"finetuneSpec.{key} is required")
    _require(bool((spec.get("hyperparameter") or {}).get("hyperparameterRef")),
             "finetuneSpec.hyperparameter.hyperparameterRef is required")
    node = spec.get("node", 1)
    _require(int(node) >= 1, "finetuneSpec.node must be >= 1")
    plugin = obj.spec.get("scoringPluginConfig")
    if plugin and plugin.get("name") is not None:
        _require(bool(str(plugin["name"]).strip()),
                 "scoringPluginConfig.name must be non-empty when set")
    _validate_probes(obj.spec.get("scoringProbes"))
    _validate_serve_config(obj.spec.get("serveConfig") or {})


def _validate_serve_config(cfg: dict):
    _require(isinstance(cfg, dict), "serveConfig must be an object")
    for key in ("replicas", "minReplicas", "maxReplicas", "slots",
                "adapterPool", "adapterRankMax"):
        if cfg.get(key) is not None:
            v = _num(cfg[key], f"serveConfig.{key}")
            _require(v >= 1 and float(v).is_integer(),
                     f"serveConfig.{key} must be a positive integer")
    if cfg.get("adapterRankMax") is not None:
        _require(cfg.get("adapterPool") is not None,
                 "serveConfig.adapterRankMax requires adapterPool (the "
                 "rank ceiling only shapes a dynamic pool)")
    lo = int(float(cfg.get("minReplicas", 1) or 1))
    hi = cfg.get("maxReplicas")
    if hi is not None:
        _require(int(float(hi)) >= lo,
                 "serveConfig.maxReplicas must be >= minReplicas")
    if cfg.get("policy") is not None:
        _require(str(cfg["policy"]) in ("least_busy", "round_robin"),
                 "serveConfig.policy must be least_busy or round_robin")
    if cfg.get("kvOvercommit") not in (None, ""):
        _require(str(cfg["kvOvercommit"]) in ("off", "on"),
                 "serveConfig.kvOvercommit must be off or on")
    if cfg.get("specMode") not in (None, ""):
        _require(str(cfg["specMode"]) in ("auto", "on", "off"),
                 "serveConfig.specMode must be auto, on, or off")
    if cfg.get("samplingEpilogue") not in (None, ""):
        _require(str(cfg["samplingEpilogue"]) in ("auto", "on", "off"),
                 "serveConfig.samplingEpilogue must be auto, on, or off")
    if cfg.get("specTree") not in (None, ""):
        # validated here (not just at engine start) so a bad tree spec is
        # refused at admission instead of crash-looping replicas. Format
        # mirrors serving.speculative.parse_spec_tree — kept dependency-
        # free because the webhook must not import jax.
        _require(cfg.get("specDraft") not in (None, ""),
                 "serveConfig.specTree requires specDraft (tree drafts "
                 "are proposed by the draft model)")
        parts = str(cfg["specTree"]).lower().split("x")
        ok = (len(parts) == 2 and parts[0].strip().isdigit()
              and parts[1].strip().isdigit())
        _require(ok, "serveConfig.specTree must be 'WxD' (branch width x "
                     "draft depth, e.g. '4x3')")
        w, d = int(parts[0]), int(parts[1])
        _require(1 <= w <= 64 and 1 <= d <= 16,
                 "serveConfig.specTree width must be 1..64 and depth "
                 "1..16")
    for key in ("specK", "prefillThreshold"):
        if cfg.get(key) is not None:
            v = _num(cfg[key], f"serveConfig.{key}")
            _require(v >= 1 and float(v).is_integer(),
                     f"serveConfig.{key} must be a positive integer")
    if cfg.get("fleetPrefixMb") is not None:
        _require(_num(cfg["fleetPrefixMb"],
                      "serveConfig.fleetPrefixMb") > 0,
                 "serveConfig.fleetPrefixMb must be > 0")
    if cfg.get("role") not in (None, ""):
        roles = [r.strip() for r in str(cfg["role"]).split(",") if r.strip()]
        _require(bool(roles), "serveConfig.role must name at least one role")
        for r in roles:
            _require(r in ("prefill", "decode", "mixed"),
                     "serveConfig.role entries must be prefill, decode, "
                     "or mixed")
        gateway = bool(cfg.get("gateway")) or \
            int(float(cfg.get("replicas") or 1)) > 1
        _require(len(roles) == 1 or gateway,
                 "serveConfig.role cycles need the gateway (replicas > 1 "
                 "or gateway=true) to distribute them")
    tenants = cfg.get("tenants")
    if tenants is not None:
        from datatunerx_tpu.tenancy import (
            tenant_entry_from_crd,
            validate_tenant_entry,
        )

        _require(isinstance(tenants, dict) and bool(tenants),
                 "serveConfig.tenants must be a non-empty object mapping "
                 "tenant name to its policy")
        _require(cfg.get("tenantsConfig") in (None, ""),
                 "serveConfig.tenants and tenantsConfig are mutually "
                 "exclusive (inline map or mounted file, not both)")
        for name, entry in tenants.items():
            entry = (tenant_entry_from_crd(entry)
                     if isinstance(entry, dict) else entry)
            try:
                validate_tenant_entry(str(name), entry)
            except ValueError as e:
                _require(False, f"serveConfig.tenants: {e}")
    if cfg.get("hostAdapterCacheMb") is not None:
        _require(_num(cfg["hostAdapterCacheMb"],
                      "serveConfig.hostAdapterCacheMb") >= 0,
                 "serveConfig.hostAdapterCacheMb must be >= 0")


def validate_finetuneexperiment(obj: CustomResource):
    jobs = obj.spec.get("finetuneJobs")
    _require(bool(jobs), "spec.finetuneJobs must not be empty")
    names = [j.get("name") for j in jobs]
    _require(all(names), "every finetuneJobs entry needs a name")
    _require(len(set(names)) == len(names), "finetuneJobs names must be unique")
    for j in jobs:
        shim = FinetuneJob(metadata=obj.metadata, spec=j.get("spec", {}))
        validate_finetunejob(shim)


# -------------------------------------------------------------- defaulters

def default_finetunejob(obj: CustomResource):
    spec = obj.spec.setdefault("finetune", {}).setdefault("finetuneSpec", {})
    spec.setdefault("node", 1)
    serve = obj.spec.setdefault("serveConfig", {})
    # gateway-tier defaults: single replica unless asked; asking for
    # replicas > 1 implies the gateway fronts them
    serve.setdefault("replicas", 1)
    if int(float(serve.get("replicas") or 1)) > 1:
        serve.setdefault("gateway", True)
    if serve.get("gateway"):
        serve.setdefault("policy", "least_busy")
        serve.setdefault("minReplicas", 1)
        serve.setdefault("maxReplicas",
                         max(int(float(serve.get("replicas") or 1)), 1))


def default_hyperparameter(obj: CustomResource):
    p = obj.spec.setdefault("parameters", {})
    p.setdefault("scheduler", "cosine")
    p.setdefault("optimizer", "adamw")
    p.setdefault("loRA_R", "8")
    p.setdefault("loRA_Alpha", "32")
    p.setdefault("loRA_Dropout", "0.1")
    p.setdefault("learningRate", "2e-4")
    p.setdefault("epochs", "1")
    p.setdefault("blockSize", "1024")
    p.setdefault("batchSize", "4")
    p.setdefault("gradAccSteps", "1")
    p.setdefault("PEFT", "true")


def _validate_probes(probes):
    if probes is None:
        return
    _require(isinstance(probes, list) and probes,
             "scoring probes must be a non-empty list")
    for pr in probes:
        _require(isinstance(pr, dict)
                 and isinstance(pr.get("prompt"), str) and pr["prompt"]
                 and isinstance(pr.get("reference"), str) and pr["reference"],
                 "each scoring probe needs non-empty 'prompt' and 'reference'")


def validate_scoring(obj: CustomResource):
    _require(bool(obj.spec.get("inferenceService")),
             "spec.inferenceService is required")
    _validate_probes(obj.spec.get("probes"))


VALIDATORS: Dict[str, Callable] = {
    Hyperparameter.kind: validate_hyperparameter,
    Dataset.kind: validate_dataset,
    LLM.kind: validate_llm,
    FinetuneJob.kind: validate_finetunejob,
    FinetuneExperiment.kind: validate_finetuneexperiment,
    Scoring.kind: validate_scoring,
}
DEFAULTERS: Dict[str, Callable] = {
    FinetuneJob.kind: default_finetunejob,
    Hyperparameter.kind: default_hyperparameter,
}


def admit(obj: CustomResource) -> CustomResource:
    """Defaulting then validation — raises AdmissionError on rejection."""
    defaulter = DEFAULTERS.get(obj.kind)
    if defaulter:
        defaulter(obj)
    validator = VALIDATORS.get(obj.kind)
    if validator:
        validator(obj)
    return obj


class AdmittingStore:
    """Store wrapper applying admission on create/update (webhook-equivalent
    choke point, since there is no API server in front)."""

    def __init__(self, store):
        self._store = store

    def __getattr__(self, name):
        return getattr(self._store, name)

    def create(self, obj):
        return self._store.create(admit(obj))

    def update(self, obj):
        admit(obj)
        return self._store.update(obj)


def _truthy(v) -> bool:
    return str(v).lower() in ("true", "1", "yes")


def _num(v, key: str) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        raise AdmissionError(f"{key} must be numeric, got {v!r}")
