"""Cluster backends: where training and serving workloads actually run.

The reference delegates to KubeRay (RayJob for training,
finetune_controller.go:518-619; RayService for serving, generate.go:160-329).
Controllers here talk to two small interfaces instead, so the same state
machines drive:

- LocalProcessBackend — host subprocesses running the trainer CLI / serving
  server (CI, e2e tests, single-host dev);
- ManifestBackend — renders GKE JobSet/Deployment manifests targeting TPU node
  pools (``google.com/tpu`` resources + topology selectors, SURVEY.md §5.8);
  submission is `kubectl apply` territory outside this sandbox;
- FakeBackend — scripted transitions for controller unit tests (envtest-style,
  SURVEY.md §4.1).

Status vocabulary mirrors RayJob's deployment states the reference polls
(finetune_controller.go:169-199): Pending | Running | Succeeded | Failed.
"""

from __future__ import annotations

import json
import os
import shlex
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Protocol


class TrainingBackend(Protocol):
    def submit(self, name: str, spec: dict) -> None: ...

    def status(self, name: str) -> str: ...

    def delete(self, name: str) -> None: ...


class ServingBackend(Protocol):
    def deploy(self, name: str, spec: dict) -> None: ...

    def status(self, name: str) -> str: ...  # HEALTHY | PENDING | FAILED

    def endpoint(self, name: str) -> Optional[str]: ...

    def delete(self, name: str) -> None: ...


def _pkg_root() -> str:
    """Directory containing the datatunerx_tpu package (for subprocess PYTHONPATH)."""
    import datatunerx_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(datatunerx_tpu.__file__)))


# ----------------------------------------------------------------- fakes

class FakeTrainingBackend:
    """Scripted backend: tests drive transitions explicitly."""

    def __init__(self):
        self.jobs: Dict[str, dict] = {}
        self.states: Dict[str, str] = {}
        self.deleted: List[str] = []

    def submit(self, name, spec):
        self.jobs[name] = spec
        self.states.setdefault(name, "Pending")

    def status(self, name):
        return self.states.get(name, "NotFound")

    def delete(self, name):
        self.deleted.append(name)
        self.states.pop(name, None)
        self.jobs.pop(name, None)

    # test helpers
    def set_state(self, name, state):
        self.states[name] = state


class FakeServingBackend:
    def __init__(self):
        self.apps: Dict[str, dict] = {}
        self.states: Dict[str, str] = {}
        self.deleted: List[str] = []

    def deploy(self, name, spec):
        self.apps[name] = spec
        self.states.setdefault(name, "PENDING")

    def status(self, name):
        return self.states.get(name, "NotFound")

    def endpoint(self, name):
        if self.states.get(name) == "HEALTHY":
            return f"http://{name}.default.svc:8000"
        return None

    def delete(self, name):
        self.deleted.append(name)
        self.states.pop(name, None)
        self.apps.pop(name, None)

    def set_state(self, name, state):
        self.states[name] = state


# ---------------------------------------------------------- local process

class _PendingGroup:
    """Placeholder for a multi-host process group queued behind the spawn
    gate; unique per submission (identity-compared) so stale spawn threads
    can never act on a resubmission under the same job name."""

    __slots__ = ("failed",)

    def __init__(self):
        self.failed = False


class LocalProcessBackend:
    """Runs the trainer CLI as subprocess(es) per job; completion detected via
    process exit + the completion manifest (training/checkpoint.py).

    ``spec["num_hosts"] > 1`` spawns that many processes wired together with
    the same DTX_* env contract the JobSet manifests set (DTX_COORDINATOR_
    ADDRESS/NUM_PROCESSES/PROCESS_ID, parallel/distributed.py) — the local
    backend is then a faithful multi-host simulator: one process per "host",
    jax.distributed bootstrap, cross-process collectives over local gRPC."""

    # Multi-host spawn stagger (seconds between JOBS' process-group spawns,
    # process-wide): gloo's cross-process rendezvous has a hard 30 s connect
    # timeout baked into XLA, and N jobs × H hosts of simultaneous jax
    # startups on shared cores skew past it — the late processes then fail
    # collectives init even though nothing is wrong (observed: the 4-job e2e
    # on a 1-core machine, where r4's fast-poll controllers un-staggered the
    # submissions that used to spread out naturally). Real clusters (kube
    # backend) are unaffected.
    _spawn_gate = threading.Lock()
    _last_group_spawn = [0.0]

    def __init__(self, workdir: str, extra_env: Optional[dict] = None):
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.extra_env = extra_env or {}
        self._procs: Dict[str, list] = {}  # job -> [Popen per host]
        self._lock = threading.Lock()

    @staticmethod
    def _free_port() -> int:
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def submit(self, name: str, spec: dict) -> None:
        with self._lock:
            if name in self._procs:
                return
            jobdir = os.path.join(self.workdir, name)
            os.makedirs(jobdir, exist_ok=True)
            argv = [sys.executable, "-m", "datatunerx_tpu.tuning.train"] + [
                str(a) for a in spec["args"]
            ]
            with open(os.path.join(jobdir, "cmd.txt"), "w") as f:
                f.write(shlex.join(argv))
            env = dict(os.environ)
            env["PYTHONPATH"] = _pkg_root() + os.pathsep + env.get("PYTHONPATH", "")
            env.update(self.extra_env)
            env.update(spec.get("env", {}))

            hosts = max(1, int(spec.get("num_hosts", 1) or 1))
            if hosts == 1:
                log = open(os.path.join(jobdir, "log.txt"), "w")
                self._procs[name] = [subprocess.Popen(
                    argv, cwd=jobdir, stdout=log, stderr=subprocess.STDOUT,
                    env=env,
                )]
                return
            # multi-host: placeholder now (status() -> Pending), spawn the
            # process group off-thread behind the stagger gate. The token is
            # unique per submission so a queued thread from a deleted job can
            # never act on a later resubmission under the same name.
            token = _PendingGroup()
            self._procs[name] = token

        def _spawn_group():
            import time as _t

            stagger = float(os.environ.get("DTX_SIM_SUBMIT_STAGGER_S", "5"))
            ready_timeout = float(
                os.environ.get("DTX_SIM_SPAWN_READY_TIMEOUT_S", "300"))
            procs = []
            try:
                with LocalProcessBackend._spawn_gate:
                    wait = stagger - (
                        _t.monotonic()
                        - LocalProcessBackend._last_group_spawn[0])
                    if wait > 0:
                        _t.sleep(wait)
                    with self._lock:
                        if self._procs.get(name) is not token:
                            return  # deleted/replaced while queued
                    coord = f"127.0.0.1:{self._free_port()}"
                    for pid in range(hosts):
                        henv = dict(env)
                        henv.update({
                            "DTX_COORDINATOR_ADDRESS": coord,
                            "DTX_NUM_PROCESSES": str(hosts),
                            "DTX_PROCESS_ID": str(pid),
                        })
                        # simulated hosts share cores: a starved process must
                        # not be declared dead (its peer would fatally abort
                        # AFTER completing all work — parallel/distributed.py)
                        henv.setdefault("DTX_DIST_HEARTBEAT_S", "600")
                        henv.setdefault("DTX_DIST_SHUTDOWN_S", "600")
                        # pod-0 writes checkpoints/manifest; rest log beside
                        log_name = "log.txt" if pid == 0 else f"log.{pid}.txt"
                        log = open(os.path.join(jobdir, log_name), "w")
                        procs.append(subprocess.Popen(
                            argv, cwd=jobdir, stdout=log,
                            stderr=subprocess.STDOUT, env=henv,
                        ))
                    with self._lock:
                        if self._procs.get(name) is token:
                            self._procs[name] = procs
                        else:  # deleted during spawn: tear the group down
                            for p in procs:
                                p.terminate()
                            return
                    # hold the gate until this group survives startup: the
                    # first "[train]" line means jax.distributed + gloo
                    # rendezvous succeeded and the step loop runs. Only then
                    # may the next group pile onto the cores — startups
                    # serialize, TRAINING still overlaps fully.
                    log0 = os.path.join(jobdir, "log.txt")
                    deadline = _t.monotonic() + ready_timeout
                    while _t.monotonic() < deadline:
                        if any(p.poll() is not None for p in procs):
                            break  # died in startup; status() reports it
                        try:
                            with open(log0, errors="replace") as f:
                                if "[train]" in f.read():
                                    break
                        except OSError:
                            pass
                        _t.sleep(1.0)
                    LocalProcessBackend._last_group_spawn[0] = _t.monotonic()
            except BaseException:  # noqa: BLE001 — stuck-Pending is worse
                for p in procs:  # no orphans: reap anything already spawned
                    try:
                        p.terminate()
                    except OSError:
                        pass
                with self._lock:
                    if self._procs.get(name) is token:
                        token.failed = True  # status() -> Failed, retryable
                raise

        # The spawn worker is token-guarded (a delete or resubmission makes
        # it a no-op), bounded by ready_timeout, and the process group it
        # creates is reaped by delete().
        threading.Thread(target=_spawn_group, daemon=True).start()  # dtxlint: disable=DTX012 — fire-and-forget by design, see above

    def status(self, name: str) -> str:
        with self._lock:
            procs = self._procs.get(name)
        if procs is None:
            return "NotFound"
        if isinstance(procs, _PendingGroup):
            # multi-host group queued behind the spawn gate (or its spawn
            # thread died — surfaced as a normal, retryable job failure)
            return "Failed" if procs.failed else "Pending"
        rcs = [p.poll() for p in procs]
        if any(rc not in (None, 0) for rc in rcs):
            return "Failed"  # JobSet failure semantics: any host failing fails the job
        if any(rc is None for rc in rcs):
            return "Running"
        return "Succeeded"

    def delete(self, name: str) -> None:
        with self._lock:
            procs = self._procs.pop(name, None)
        for proc in procs or []:
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()

    def has_active_jobs(self) -> bool:
        """True while any trainer subprocess is live (the device health probe
        must not contend with a running job for the single-client TPU)."""
        with self._lock:
            return any(p.poll() is None
                       for procs in self._procs.values() for p in procs)

    def metrics_series(self, name: str, max_points: int = 2000) -> dict:
        """Parsed trainer/eval jsonl curves for the UI (the data the reference
        surfaces via Prometheus + its web frontend, SURVEY.md §3.5)."""
        out = {"train": [], "eval": []}
        for key, fname in (("train", "trainer_log.jsonl"),
                           ("eval", "eval_log.jsonl")):
            path = os.path.join(self.workdir, name, "result", "watch", fname)
            try:
                with open(path) as f:
                    rows = [json.loads(line) for line in f if line.strip()]
                out[key] = rows[-max_points:]
            except (OSError, ValueError):
                pass
        return out

    def log_tail(self, name: str, n: int = 40, max_bytes: int = 256 * 1024) -> str:
        path = os.path.join(self.workdir, name, "log.txt")
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(size - max_bytes, 0))
                data = f.read().decode(errors="replace")
            return "".join(data.splitlines(keepends=True)[-n:])
        except OSError:
            return ""


# -------------------------------------------------------------- manifests

def jobset_state(status: dict) -> str:
    """JobSet status → backend state vocabulary (the feedback loop the
    reference runs on RayJob JobDeploymentStatus,
    finetune_controller.go:169-199). A 'Completed'=True condition is terminal
    success, 'Failed'=True terminal failure; any active/ready replicated job
    counts as Running; otherwise Pending."""
    for cond in status.get("conditions") or []:
        if str(cond.get("status")) != "True":
            continue
        t = cond.get("type", "")
        if t == "Completed":
            return "Succeeded"
        if t in ("Failed", "FailurePolicyComplete"):
            return "Failed"
    for rj in status.get("replicatedJobsStatus") or []:
        if (rj.get("active", 0) or 0) > 0 or (rj.get("ready", 0) or 0) > 0:
            return "Running"
    return "Pending"


def deployment_state(status: dict) -> str:
    for cond in status.get("conditions") or []:
        if (cond.get("type") == "ReplicaFailure"
                and str(cond.get("status")) == "True"):
            return "FAILED"
        # crash-looping pods never set ReplicaFailure; the deployment's
        # progress deadline (default 600s) is the terminal signal for them
        if (cond.get("type") == "Progressing"
                and str(cond.get("status")) == "False"
                and cond.get("reason") == "ProgressDeadlineExceeded"):
            return "FAILED"
    if (status.get("availableReplicas") or 0) >= 1:
        return "HEALTHY"
    return "PENDING"


class ManifestBackend:
    """Renders k8s manifests for GKE TPU node pools instead of submitting them.

    Training → JobSet-style Job per TPU host group (replacing the reference's
    RayCluster worker group with nvidia.com/gpu,
    finetune_controller.go:576-609); Serving → Deployment + Service.
    """

    def __init__(self, out_dir: str, accelerator: str = "tpu-v5-lite-podslice",
                 topology: str = "2x4"):
        self.out_dir = out_dir
        self.accelerator = accelerator
        self.topology = topology
        os.makedirs(out_dir, exist_ok=True)
        self._submitted: Dict[str, dict] = {}

    def render_training(self, name: str, spec: dict) -> dict:
        hosts = int(spec.get("num_hosts", 1))
        image = spec.get("image", "datatunerx-tpu/trainer:latest")
        args = [str(a) for a in spec["args"]]
        # per-job placement overrides (operator/placement.py SlicePool):
        # concurrent jobs land on disjoint sub-slices/node pools
        topology = spec.get("topology") or self.topology
        node_selector = {
            "cloud.google.com/gke-tpu-accelerator": self.accelerator,
            "cloud.google.com/gke-tpu-topology": topology,
            **(spec.get("node_selector") or {}),
        }
        return {
            "apiVersion": "jobset.x-k8s.io/v1alpha2",
            "kind": "JobSet",
            "metadata": {"name": name, "labels": spec.get("labels", {})},
            "spec": {
                "replicatedJobs": [{
                    "name": "tpu-hosts",
                    "replicas": 1,
                    "template": {
                        "spec": {
                            "parallelism": hosts,
                            "completions": hosts,
                            "backoffLimit": 0,
                            "template": {
                                "metadata": {"labels": spec.get("labels", {})},
                                "spec": {
                                    "restartPolicy": "Never",
                                    "nodeSelector": node_selector,
                                    "containers": [{
                                        "name": "trainer",
                                        "image": image,
                                        "command": ["python", "-m", "datatunerx_tpu.tuning.train"],
                                        "args": args,
                                        "env": [
                                            {"name": "DTX_COORDINATOR_ADDRESS",
                                             "value": f"{name}-tpu-hosts-0-0.{name}:8476"},
                                            {"name": "DTX_NUM_PROCESSES", "value": str(hosts)},
                                            {"name": "DTX_PROCESS_ID",
                                             "valueFrom": {"fieldRef": {"fieldPath": (
                                                 "metadata.annotations['batch.kubernetes.io/job-completion-index']")}}},
                                        ] + [
                                            {"name": k, "value": str(v)}
                                            for k, v in spec.get("env", {}).items()
                                        ],
                                        "resources": {"limits": {"google.com/tpu": "4"}},
                                    }],
                                },
                            },
                        },
                    },
                }],
            },
        }

    def render_serving(self, name: str, spec: dict) -> list:
        labels = {"app": name, **spec.get("labels", {})}
        deployment = {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": name, "labels": labels},
            "spec": {
                # horizontal serving scale (gateway tier): the Service
                # spreads requests; in-cluster gateway deployment with
                # per-pod discovery is a ROADMAP open item
                "replicas": int(spec.get("replicas") or 1),
                "selector": {"matchLabels": {"app": name}},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": {
                        "nodeSelector": {
                            "cloud.google.com/gke-tpu-accelerator": self.accelerator,
                            **spec.get("node_selector", {}),
                        },
                        "tolerations": spec.get("tolerations", []),
                        "containers": [{
                            "name": "server",
                            "image": spec.get("image", "datatunerx-tpu/serving:latest"),
                            "command": ["python", "-m", "datatunerx_tpu.serving.server"],
                            "args": [
                                "--model_path", spec["model_path"],
                                "--checkpoint_path", spec.get("checkpoint_path", ""),
                                "--port", "8000",
                                "--quantization", spec.get("quantization", ""),
                                *(["--slots", str(spec["slots"])]
                                  if spec.get("slots") is not None else []),
                            ],
                            "ports": [{"containerPort": 8000}],
                            "readinessProbe": {
                                "httpGet": {"path": "/healthz", "port": 8000},
                                "periodSeconds": 5,
                            },
                            "resources": {"limits": {"google.com/tpu": "4"}},
                        }],
                    },
                },
            },
        }
        service = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": name, "labels": labels},
            "spec": {
                "selector": {"app": name},
                "ports": [{"port": 8000, "targetPort": 8000}],
            },
        }
        return [deployment, service]

    def submit(self, name, spec):
        manifest = self.render_training(name, spec)
        self._submitted[name] = manifest
        with open(os.path.join(self.out_dir, f"{name}-jobset.json"), "w") as f:
            json.dump(manifest, f, indent=1)

    def status(self, name):
        """Render-only mode has no apiserver to poll; the feedback loop is a
        status file (`<name>-status.json`) dropped next to the manifest by
        whatever applied it — either `{"state": "Running"}` directly or a raw
        JobSet status object (mapped via jobset_state). Absent file = Pending.
        For a live apiserver loop use KubeTrainingBackend (kubebackends.py).
        """
        if name not in self._submitted:
            return "NotFound"
        path = os.path.join(self.out_dir, f"{name}-status.json")
        try:
            with open(path) as f:
                status = json.load(f)
        except (OSError, ValueError):
            return "Pending"
        if isinstance(status, dict) and isinstance(status.get("state"), str):
            return status["state"]
        return jobset_state(status if isinstance(status, dict) else {})

    def delete(self, name):
        self._submitted.pop(name, None)
        for suffix in ("-jobset.json", "-status.json"):
            try:
                os.remove(os.path.join(self.out_dir, f"{name}{suffix}"))
            except OSError:
                pass
