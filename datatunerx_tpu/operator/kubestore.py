"""KubeObjectStore: the ObjectStore verbs against a real Kubernetes apiserver.

The in-memory ``ObjectStore`` gives reconcilers the API-server contract
(optimistic concurrency, finalizer-gated deletion, watches); this adapter
implements the SAME five verbs + watch over the CRD endpoints (deploy/crds/),
so the controllers run unchanged in-cluster — the arrangement the reference
gets from controller-runtime (reference cmd/controller-manager/app/
controller_manager.go:44-51 scheme registration; every controller Create/
Status().Update crosses into the apiserver, SURVEY.md §3).

Spec/metadata and status are separate update surfaces in k8s (status
subresource); ``update()`` writes both, preserving the single-call contract
controllers expect from ObjectStore.
"""

from __future__ import annotations

import calendar
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple, Type

from datatunerx_tpu.operator.api import ALL_KINDS, CustomResource, KIND_BY_NAME, ObjectMeta
from datatunerx_tpu.operator.kubeclient import ApiError, KubeClient
from datatunerx_tpu.operator.store import AlreadyExists, Conflict, Event, NotFound


def plural_of(kind: str) -> str:
    return kind.lower() + "s"


def gvp(cls: Type[CustomResource]) -> Tuple[str, str, str]:
    group, _, version = cls.api_version.partition("/")
    return group, version, plural_of(cls.kind)


def _epoch_to_rfc3339(t: Optional[float]) -> Optional[str]:
    if t is None:
        return None
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t))


def _rfc3339_to_epoch(s) -> Optional[float]:
    if not s:
        return None
    if isinstance(s, (int, float)):
        return float(s)
    try:
        return float(calendar.timegm(time.strptime(s, "%Y-%m-%dT%H:%M:%SZ")))
    except ValueError:
        return None


def to_k8s(obj: CustomResource) -> dict:
    m = obj.metadata
    meta: dict = {"name": m.name, "namespace": m.namespace}
    if m.uid:
        meta["uid"] = m.uid
    if m.labels:
        meta["labels"] = dict(m.labels)
    if m.annotations:
        meta["annotations"] = dict(m.annotations)
    if m.finalizers:
        meta["finalizers"] = list(m.finalizers)
    if m.owner_references:
        meta["ownerReferences"] = [
            {
                "apiVersion": KIND_BY_NAME[r["kind"]].api_version
                if r.get("kind") in KIND_BY_NAME else r.get("apiVersion", ""),
                "kind": r.get("kind"),
                "name": r.get("name"),
                "uid": r.get("uid"),
            }
            for r in m.owner_references
        ]
    if m.resource_version:
        meta["resourceVersion"] = str(m.resource_version)
    return {
        "apiVersion": obj.api_version,
        "kind": obj.kind,
        "metadata": meta,
        "spec": obj.spec,
        "status": obj.status,
    }


def from_k8s(d: dict) -> CustomResource:
    cls = KIND_BY_NAME[d["kind"]]
    km = d.get("metadata", {})
    rv_raw = km.get("resourceVersion", 0)
    meta = ObjectMeta(
        name=km.get("name", ""),
        namespace=km.get("namespace", "default"),
        uid=km.get("uid", ""),
        labels=dict(km.get("labels") or {}),
        annotations=dict(km.get("annotations") or {}),
        finalizers=list(km.get("finalizers") or []),
        owner_references=[
            {"kind": r.get("kind"), "name": r.get("name"), "uid": r.get("uid")}
            for r in (km.get("ownerReferences") or [])
        ],
        resource_version=int(rv_raw) if str(rv_raw).isdigit() else 0,
        generation=int(km.get("generation", 1) or 1),
        creation_timestamp=_rfc3339_to_epoch(km.get("creationTimestamp"))
        or time.time(),
        deletion_timestamp=_rfc3339_to_epoch(km.get("deletionTimestamp")),
    )
    return cls(metadata=meta, spec=d.get("spec") or {}, status=d.get("status") or {})


class KubeObjectStore:
    def __init__(self, client: KubeClient,
                 kinds: Optional[List[Type[CustomResource]]] = None):
        self.client = client
        self.kinds = list(kinds or ALL_KINDS)
        self._watchers: List[Callable[[Event], None]] = []
        self._watch_threads: List[threading.Thread] = []
        self._stop = threading.Event()

    # --------------------------------------------------------------- verbs
    def create(self, obj: CustomResource) -> CustomResource:
        cls = type(obj)
        group, version, plural = gvp(cls)
        body = to_k8s(obj)
        body["metadata"].pop("resourceVersion", None)
        status = body.pop("status", None)
        try:
            created = self.client.create(
                group, version, plural, obj.metadata.namespace, body
            )
        except ApiError as e:
            if e.status == 409:
                raise AlreadyExists(f"{obj.kind} {obj.key}") from e
            raise
        if status:
            created["status"] = status
            created = self._put_status(group, version, plural, obj.metadata.namespace,
                                       obj.metadata.name, created)
        return from_k8s(created)

    def get(self, kind, name: str, namespace: str = "default") -> CustomResource:
        cls = KIND_BY_NAME[kind] if isinstance(kind, str) else kind
        group, version, plural = gvp(cls)
        try:
            return from_k8s(self.client.get(group, version, plural, namespace, name))
        except ApiError as e:
            if e.status == 404:
                raise NotFound(f"{cls.kind} {namespace}/{name}") from e
            raise

    def try_get(self, kind, name, namespace="default"):
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def update(self, obj: CustomResource) -> CustomResource:
        cls = type(obj)
        group, version, plural = gvp(cls)
        ns, name = obj.metadata.namespace, obj.metadata.name
        body = to_k8s(obj)
        try:
            updated = self.client.replace(group, version, plural, ns, name, body)
        except ApiError as e:
            if e.status == 409:
                raise Conflict(f"{obj.kind} {obj.key}") from e
            if e.status == 404:
                raise NotFound(f"{obj.kind} {obj.key}") from e
            raise
        if updated.get("status") == obj.status:
            # status unchanged by this reconcile: skip the second PUT (halves
            # apiserver write load and watch-event churn)
            return from_k8s(updated)
        # status subresource write rides the rv the main write just returned
        updated["status"] = obj.status
        try:
            updated = self._put_status(group, version, plural, ns, name, updated)
        except NotFound:
            # removing the last finalizer completed a pending deletion during
            # the main write — the object is legitimately gone
            if updated["metadata"].get("deletionTimestamp"):
                return from_k8s(updated)
            raise
        return from_k8s(updated)

    def _put_status(self, group, version, plural, ns, name, body) -> dict:
        try:
            return self.client.replace(
                group, version, plural, ns, name, body, subresource="status"
            )
        except ApiError as e:
            if e.status == 409:
                raise Conflict(f"{body.get('kind')} {ns}/{name} (status)") from e
            if e.status == 404:
                raise NotFound(f"{body.get('kind')} {ns}/{name}") from e
            raise

    def delete(self, kind, name, namespace="default"):
        cls = KIND_BY_NAME[kind] if isinstance(kind, str) else kind
        group, version, plural = gvp(cls)
        try:
            self.client.delete(group, version, plural, namespace, name)
        except ApiError as e:
            if e.status == 404:
                raise NotFound(f"{cls.kind} {namespace}/{name}") from e
            raise

    def list(self, kind, namespace: Optional[str] = "default",
             labels: Optional[Dict[str, str]] = None) -> List[CustomResource]:
        cls = KIND_BY_NAME[kind] if isinstance(kind, str) else kind
        group, version, plural = gvp(cls)
        selector = ",".join(f"{k}={v}" for k, v in (labels or {}).items()) or None
        resp = self.client.list(group, version, plural, namespace,
                                label_selector=selector)
        out = [from_k8s(item) for item in resp.get("items", [])]
        return sorted(out, key=lambda o: o.metadata.name)

    # --------------------------------------------------------------- watch
    def watch(self, fn: Callable[[Event], None]):
        self._watchers.append(fn)
        if not self._watch_threads:
            self._start_watches()

    def _start_watches(self):
        for cls in self.kinds:
            group, version, plural = gvp(cls)
            t = threading.Thread(
                target=self.client.watch,
                args=(group, version, plural, None, self._dispatch, self._stop),
                daemon=True,
                name=f"watch-{plural}",
            )
            t.start()
            self._watch_threads.append(t)

    def _dispatch(self, ev_type: str, obj_dict: dict):
        if obj_dict.get("kind") not in KIND_BY_NAME:
            return
        obj = from_k8s(obj_dict)
        for w in list(self._watchers):
            try:
                w((ev_type, obj))
            except Exception:
                pass

    def stop(self):
        self._stop.set()
