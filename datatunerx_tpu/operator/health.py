"""Device health probe: don't queue training onto a wedged accelerator.

The tunneled-TPU failure mode is a HANG, not an error — a job submitted to a
wedged device burns its whole backoff budget producing nothing. The probe runs
a tiny device matmul in a SUBPROCESS (a hung probe must not poison the
operator) on an interval; while it fails, the Finetune controller holds new
submissions in Pending instead of handing them to the backend
(finetune_controller.py). The reference has no analogue — Ray would simply
run the job into the broken GPU.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
from typing import Optional

PROBE_CODE = (
    "import jax, jax.numpy as jnp;"
    "x = jnp.ones((256, 256), jnp.float32);"
    "print(float((x @ x)[0, 0]))"
)


def probe_device_once(timeout_s: float = 90.0) -> Optional[str]:
    """Run one subprocess probe; returns None when healthy, else the failure
    description."""
    try:
        p = subprocess.run([sys.executable, "-c", PROBE_CODE],
                           timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return f"device probe hung (> {timeout_s:.0f}s)"
    if p.returncode != 0:
        return f"device probe exited {p.returncode}: {p.stderr[-200:]}"
    if "256.0" not in p.stdout:
        return f"device probe wrong result: {p.stdout[-100:]!r}"
    return None


class DeviceHealthProbe:
    """Background prober with a sticky last-known state.

    Starts optimistic (healthy) so the first reconcile isn't blocked behind a
    cold probe; flips unhealthy as soon as a probe fails.
    """

    def __init__(self, interval_s: float = 300.0, timeout_s: float = 90.0,
                 idle_check=None):
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        # idle_check() -> bool: probe ONLY while no training job is running —
        # the accelerator is single-client (a probe against a busy device
        # reads as a false failure, and on the tunneled relay a second client
        # can wedge the device out from under the live job)
        self.idle_check = idle_check
        self.healthy = True
        self.last_error: Optional[str] = None
        self.last_checked: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def check_now(self) -> bool:
        err = probe_device_once(self.timeout_s)
        self.last_error = err
        self.healthy = err is None
        self.last_checked = time.time()
        return self.healthy

    def start(self):
        if self._thread is not None:
            return self

        def loop():
            while not self._stop.is_set():
                if self.idle_check is None or self.idle_check():
                    self.check_now()
                if self._stop.wait(self.interval_s):
                    return

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="device-health-probe")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
