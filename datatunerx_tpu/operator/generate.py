"""Child-resource generation (reference pkg/util/generate/generate.go).

GenerateFinetune          → Finetune CR from a FinetuneJob spec (generate.go:27-53)
build_trainer_args        → the CLI flag list (replaces getRayJobEntrypoint,
                            finetune_controller.go:451-516; fixes the
                            hardcoded lora_target and trailing-space flag bugs,
                            SURVEY.md §7.5)
generate_training_spec    → backend-agnostic training workload spec
generate_serving_spec     → serving workload (replaces GenerateRayService,
                            generate.go:160-329; no image bake — serving mounts
                            the checkpoint URI directly, SURVEY.md §7.1)
generate_builtin_scoring  → Scoring CR, built-in plugin (generate.go:331-341)
generate_plugin_scoring   → Scoring CR with user plugin (generate.go:343-358)
"""

from __future__ import annotations

import json
import random
import string
from typing import List, Optional

from datatunerx_tpu.operator import config
from datatunerx_tpu.operator.api import (
    Finetune,
    FinetuneJob,
    ObjectMeta,
    Scoring,
)
from datatunerx_tpu.operator.labels import (
    LABEL_FINETUNE_BINDING,
    generate_instance_label,
)
from datatunerx_tpu.operator.store import set_owner

# Hyperparameter CR parameter keys (SURVEY.md §2.3; merge at
# finetune_controller.go:682-758). Values arrive as strings (reference quirk).
PARAMETER_KEYS = (
    "scheduler", "optimizer", "int4", "int8", "loRA_R", "loRA_Alpha",
    "loRA_Dropout", "learningRate", "epochs", "blockSize", "batchSize",
    "warmupRatio", "weightDecay", "gradAccSteps", "trainerType", "PEFT",
    "FP16",
    # TPU additions
    "meshShape", "loRATarget", "packSequences", "attention",
    "rewardModel",  # --stage ppo: rm-stage run dir under the storage path
    "quantImpl",  # pallas (fused kernels, default) | xla (dequant+dot)
)


def rand_suffix(n: int = 5) -> str:
    return "".join(random.choices(string.ascii_lowercase + string.digits, k=n))


def generate_finetune(job: FinetuneJob) -> Finetune:
    """Reference generate.go:27-53: embed job.spec.finetune.finetuneSpec,
    defaulting image/path from env config."""
    ft_spec = dict(job.spec.get("finetune", {}).get("finetuneSpec", {}))
    image = dict(ft_spec.get("image", {}))
    if not image.get("name"):
        image["name"] = config.get_base_image()
    if not image.get("path"):
        image["path"] = config.get_default_model_path()
    ft_spec["image"] = image
    ft_spec.setdefault("node", 1)
    name = job.spec.get("finetune", {}).get("name") or f"{job.metadata.name}-finetune"
    ft = Finetune(
        metadata=ObjectMeta(
            name=name,
            namespace=job.metadata.namespace,
            labels={**generate_instance_label(job.metadata.name),
                    LABEL_FINETUNE_BINDING: job.metadata.name},
        ),
        spec=ft_spec,
    )
    set_owner(ft, job)
    return ft


def merge_hyperparameters(base: dict, overrides: Optional[dict]) -> dict:
    """Field-by-field override merge (reference updateHyperparameters,
    finetune_controller.go:682-758): only explicitly-set override fields win."""
    merged = {k: base.get(k) for k in PARAMETER_KEYS if base.get(k) is not None}
    for k, v in (overrides or {}).items():
        if v is not None:
            merged[k] = v
    return merged


def build_trainer_args(
    finetune: Finetune,
    dataset_spec: dict,
    parameters: dict,
    uid: Optional[str] = None,
    num_workers: Optional[int] = None,  # slice placement overrides spec.node
) -> List[str]:
    """The trainer CLI flag list (replaces getRayJobEntrypoint,
    finetune_controller.go:457-514). Same contract, three reference bugs fixed:
    canonical --lora_rank spelling (alias still accepted), lora_target comes
    from parameters instead of being hardcoded, no trailing-space flag."""
    info = dataset_spec.get("datasetMetadata", {}).get("datasetInfo", {})
    subsets = info.get("subsets", [{}])
    splits = subsets[0].get("splits", {}) if subsets else {}

    model_path = finetune.spec.get("image", {}).get("path")
    if not model_path:
        raise ValueError(
            f"{finetune.metadata.namespace}/{finetune.metadata.name}: "
            "finetune.spec.image.path is required"
        )
    args: List[str] = ["--model_name_or_path", model_path]
    train_file = splits.get("train", {}).get("file")
    if not train_file:
        raise ValueError("dataset has no train split file")
    args += ["--train_path", train_file]
    if splits.get("validate", {}).get("file"):
        args += ["--evaluation_path", splits["validate"]["file"]]

    features = info.get("features") or []
    columns = {
        f["mapTo"]: f["name"]
        for f in features
        if f.get("mapTo") and f.get("name") in ("instruction", "response",
                                                "chosen", "rejected")
    }
    if columns:
        import json as _json

        args += ["--columns", _json.dumps(columns)]

    args += ["--output_dir", "result"]
    args += ["--lora_target", parameters.get("loRATarget", "q_proj,v_proj")]
    if parameters.get("scheduler"):
        args += ["--lr_scheduler_type", str(parameters["scheduler"])]
    if parameters.get("optimizer"):
        args += ["--optim", str(parameters["optimizer"]).lower()]

    if _truthy(parameters.get("int8")):
        args += ["--quantization", "int8"]
    elif _truthy(parameters.get("int4")):
        args += ["--quantization", "int4"]

    # trainerType selects the training stage (Hyperparameter CR field the
    # reference carries but never consumes): sft (default) | dpo | rm | ppo
    tt = str(parameters.get("trainerType", "")).lower()
    if tt in ("dpo", "rm", "ppo"):
        args += ["--stage", tt]
    if tt == "ppo" and parameters.get("rewardModel"):
        # an --stage rm run directory (<storage_path>/<uid>)
        args += ["--reward_model", str(parameters["rewardModel"])]

    args += ["--finetuning_type", "lora" if is_peft(parameters) else "full"]
    for flag, key in (
        ("--lora_rank", "loRA_R"),
        ("--lora_alpha", "loRA_Alpha"),
        ("--lora_dropout", "loRA_Dropout"),
        ("--learning_rate", "learningRate"),
        ("--num_train_epochs", "epochs"),
        ("--block_size", "blockSize"),
        ("--per_device_train_batch_size", "batchSize"),
        ("--warmup_ratio", "warmupRatio"),
        ("--weight_decay", "weightDecay"),
        ("--gradient_accumulation_steps", "gradAccSteps"),
    ):
        if parameters.get(key) is not None:
            args += [flag, str(parameters[key])]
    if parameters.get("FP16") is not None:
        args += ["--fp16", str(_truthy(parameters["FP16"])).lower()]
    if parameters.get("meshShape"):
        ms = parameters["meshShape"]
        if isinstance(ms, dict):  # CRD object form {dcn, dp, fsdp, tp, sp}
            ms = ",".join(f"{k}={v}" for k, v in ms.items())
        args += ["--mesh", str(ms)]
    if parameters.get("attention"):
        args += ["--attention", str(parameters["attention"])]
    if parameters.get("quantImpl"):
        args += ["--quant_impl", str(parameters["quantImpl"])]
    if _truthy(parameters.get("packSequences")):
        args += ["--pack_sequences", "true"]

    node = int(finetune.spec.get("node", 1) or 1)
    args += ["--num_workers", str(num_workers or max(node, 1))]
    args += ["--storage_path", config.get_storage_path()]
    if config.get_metrics_export_address():
        args += ["--metrics_export_address", config.get_metrics_export_address()]
    args += ["--uid", uid or finetune.metadata.uid]
    return args


def _truthy(v) -> bool:
    return str(v).lower() in ("true", "1", "yes")


def is_peft(parameters: dict) -> bool:
    """The PEFT truthiness contract (default true, empty string counts as
    set-true — reference quirk). THE single definition: webhooks.py and
    capacity.py admission must model exactly the job this module renders."""
    return str(parameters.get("PEFT", "true")).lower() in ("true", "1", "")


def generate_training_spec(finetune: Finetune, args: List[str],
                           num_hosts: Optional[int] = None) -> dict:
    node = int(finetune.spec.get("node", 1) or 1)
    return {
        "args": args,
        # with slice placement, host count must match the ASSIGNED slice —
        # a multi-host podslice expects exactly its host count of workers
        "num_hosts": num_hosts or max(node, 1),
        "image": finetune.spec.get("image", {}).get("name"),
        "labels": generate_instance_label(finetune.metadata.name),
        "env": {},
    }


def generate_serving_spec(job: FinetuneJob, checkpoint: dict) -> dict:
    """Replaces GenerateRayService (generate.go:160-329). No baked image: the
    server gets the base model path + checkpoint URI directly."""
    serve_cfg = job.spec.get("serveConfig", {}) or {}
    return {
        "model_path": checkpoint.get("llmPath")
        or checkpoint.get("image", {}).get("path")
        or config.get_default_model_path(),
        "checkpoint_path": checkpoint.get("checkpointPath", ""),
        "labels": generate_instance_label(job.metadata.name),
        "node_selector": serve_cfg.get("nodeSelector", {}),
        "tolerations": serve_cfg.get("tolerations", []),
        # serve-time base quantization (serving/engine.py): fit big models on
        # one chip's HBM; TPU addition to ServeConfig
        "quantization": serve_cfg.get("quantization", ""),
        # continuous-batching slot count (serving/server.py --slots; 1 =
        # single-request engine); TPU addition to ServeConfig
        "slots": serve_cfg.get("slots"),
        # dynamic multi-adapter pool (serving --adapter_pool /
        # --adapter_rank_max + /admin/adapters): adapters as runtime data
        "adapter_pool": serve_cfg.get("adapterPool"),
        "adapter_rank_max": serve_cfg.get("adapterRankMax"),
        # multi-replica serving behind the inference gateway
        # (gateway/server.py, replaces the reference's Ray Serve tier):
        # replicas > 1 or gateway=true puts the gateway in front
        "replicas": int(serve_cfg.get("replicas") or 1),
        "gateway": bool(serve_cfg.get("gateway")),
        "policy": serve_cfg.get("policy", "least_busy"),
        "min_replicas": int(serve_cfg.get("minReplicas") or 1),
        "max_replicas": int(serve_cfg.get("maxReplicas")
                            or serve_cfg.get("replicas") or 1),
        # paged-KV overcommit + speculative decoding (serving/server.py
        # --kv_overcommit / --spec_draft_config / --spec_k / --spec_mode)
        "kv_overcommit": serve_cfg.get("kvOvercommit") or "",
        "spec_draft_config": serve_cfg.get("specDraft") or "",
        "spec_k": serve_cfg.get("specK"),
        "spec_mode": serve_cfg.get("specMode") or "",
        "spec_tree": serve_cfg.get("specTree") or "",
        "sampling_epilogue": serve_cfg.get("samplingEpilogue") or "",
        # disaggregated fleet plane (gateway/server.py --role /
        # --prefill_threshold / --fleet_*): replica roles, the shared
        # prefix tier, prefill→decode handoff, peer KV spill
        "role": serve_cfg.get("role") or "",
        "prefill_threshold": serve_cfg.get("prefillThreshold"),
        "fleet_prefix_mb": serve_cfg.get("fleetPrefixMb"),
        "fleet_handoff": bool(serve_cfg.get("fleetHandoff")),
        "fleet_spill": bool(serve_cfg.get("fleetSpill")),
        # multi-tenant QoS plane (datatunerx_tpu/tenancy/): the inline map
        # renders to one --tenants_config JSON argument (camelCase keys
        # mapped onto the directory schema); tenantsConfig is a mounted
        # file path passed through verbatim
        "tenants_config": _tenants_config_from(serve_cfg),
        "host_adapter_cache_mb": serve_cfg.get("hostAdapterCacheMb"),
    }


def _tenants_config_from(serve_cfg: dict) -> str:
    """serveConfig.tenants (inline map) or .tenantsConfig (file path) →
    the one --tenants_config string both servers load."""
    inline = serve_cfg.get("tenants")
    if isinstance(inline, dict) and inline:
        from datatunerx_tpu.tenancy import tenant_entry_from_crd

        return json.dumps({str(n): tenant_entry_from_crd(e)
                           if isinstance(e, dict) else e
                           for n, e in inline.items()},
                          sort_keys=True)
    return serve_cfg.get("tenantsConfig") or ""


def generate_builtin_scoring(job: FinetuneJob, inference_url: str) -> Scoring:
    """Reference generate.go:331-341: plugin-less Scoring CR. Probes may be
    customized per job via spec.scoringProbes [{prompt, reference}]."""
    spec = {
        "inferenceService": inference_url,
        "plugin": {"loadPlugin": False},
    }
    if job.spec.get("scoringProbes"):
        spec["probes"] = job.spec["scoringProbes"]
    # dataset-driven scoring: evaluate over the Dataset CR's test/validate
    # split instead of probes ("auto" = the job's own training dataset)
    ds_ref = job.spec.get("scoringDatasetRef")
    if ds_ref:
        if ds_ref == "auto":
            ds_ref = (job.spec.get("finetune", {})
                      .get("finetuneSpec", {}).get("dataset"))
        spec["datasetRef"] = ds_ref
        if job.spec.get("scoringMetric"):
            spec["metric"] = job.spec["scoringMetric"]
        if job.spec.get("scoringMaxExamples"):
            spec["maxExamples"] = job.spec["scoringMaxExamples"]
    sc = Scoring(
        metadata=ObjectMeta(
            name=job.metadata.name,
            namespace=job.metadata.namespace,
            labels=generate_instance_label(job.metadata.name),
        ),
        spec=spec,
    )
    set_owner(sc, job)
    return sc


def generate_plugin_scoring(job: FinetuneJob, inference_url: str) -> Scoring:
    """Reference generate.go:343-358: user-plugin Scoring CR."""
    cfg = job.spec.get("scoringPluginConfig", {}) or {}
    sc = Scoring(
        metadata=ObjectMeta(
            name=job.metadata.name,
            namespace=job.metadata.namespace,
            labels=generate_instance_label(job.metadata.name),
        ),
        spec={
            "inferenceService": inference_url,
            "plugin": {
                "loadPlugin": True,
                "name": cfg.get("name"),
                "parameters": cfg.get("parameters"),
            },
        },
    )
    set_owner(sc, job)
    return sc
