"""FinetuneExperiment controller: batch fan-out + aggregation + best-version
selection (reference internal/controller/finetune/
finetuneexperiment_controller.go:54-227).

- spec.pending=True pauses the experiment: child jobs deleted, state=Pending
  (reference :86-114); flipping back resumes.
- fan-out: one FinetuneJob per spec.finetuneJobs entry, owner-referenced
  (reference :123-152).
- aggregation: child statuses mirrored BY NAME into status.jobsStatus —
  fixing the fragile index-based pairing (reference :168-190, SURVEY.md §7.5).
- all Successful → bestVersion = highest score (numeric parse, not the
  reference's atoi-or-0, util.go:24-30); any mix of terminal states with at
  least one success still selects; all failed → Failed (reference :199-220).
"""

from __future__ import annotations

import os

import time
from typing import Optional

from datatunerx_tpu.operator.api import (
    FINETUNE_GROUP_FINALIZER,
    FinetuneExperiment,
    FinetuneJob,
    LLMCheckpoint,
    ObjectMeta,
)
from datatunerx_tpu.operator.reconciler import Result
from datatunerx_tpu.operator.store import AlreadyExists, NotFound, ObjectStore, set_owner

DEFAULT_POLL_S = 5.0


def parse_score(s) -> float:
    """Numeric score parse; unparseable → -inf so it never wins (the reference
    silently maps any non-integer to 0, util.go:24-30 — a bug we don't keep)."""
    try:
        return float(s)
    except (TypeError, ValueError):
        return float("-inf")


class FinetuneExperimentController:
    kind = FinetuneExperiment

    def __init__(self, poll_s: Optional[float] = None):
        # resolved at CONSTRUCTION, not import: tests and operators can set
        # DTX_EXPERIMENT_POLL_S (or pass poll_s) without reloading the
        # module — the old module-level read froze the env value for the
        # process lifetime
        self.poll_s = (float(os.environ.get("DTX_EXPERIMENT_POLL_S", "")
                             or DEFAULT_POLL_S)
                       if poll_s is None else float(poll_s))

    def reconcile(self, store: ObjectStore, exp: FinetuneExperiment) -> Optional[Result]:
        meta = exp.metadata

        if meta.deletion_timestamp:
            for entry in exp.spec.get("finetuneJobs", []):
                try:
                    store.delete(FinetuneJob, entry["name"], meta.namespace)
                except NotFound:
                    pass
            if FINETUNE_GROUP_FINALIZER in meta.finalizers:
                meta.finalizers.remove(FINETUNE_GROUP_FINALIZER)
                store.update(exp)
            return None

        if FINETUNE_GROUP_FINALIZER not in meta.finalizers:
            meta.finalizers.append(FINETUNE_GROUP_FINALIZER)
            store.update(exp)
            return Result(requeue_after=0)

        # pause switch (reference :86-114)
        if exp.spec.get("pending"):
            changed = False
            for entry in exp.spec.get("finetuneJobs", []):
                if store.try_get(FinetuneJob, entry["name"], meta.namespace):
                    try:
                        store.delete(FinetuneJob, entry["name"], meta.namespace)
                        changed = True
                    except NotFound:
                        pass
            if exp.status.get("state") != FinetuneExperiment.STATE_PENDING:
                exp.status["state"] = FinetuneExperiment.STATE_PENDING
                changed = True
            if changed:
                store.update(exp)
            return None

        if exp.status.get("state") in ("", FinetuneExperiment.STATE_PENDING, None):
            exp.status["state"] = FinetuneExperiment.STATE_PROCESSING
            store.update(exp)
            return Result(requeue_after=0)

        # fan-out (reference :123-152)
        created = False
        for entry in exp.spec.get("finetuneJobs", []):
            if store.try_get(FinetuneJob, entry["name"], meta.namespace) is None:
                job = FinetuneJob(
                    metadata=ObjectMeta(name=entry["name"], namespace=meta.namespace),
                    spec=entry.get("spec", {}),
                )
                set_owner(job, exp)
                try:
                    store.create(job)
                    created = True
                except AlreadyExists:
                    pass
        if created:
            return Result(requeue_after=self.poll_s)

        # aggregation by name (reference :154-197)
        jobs = []
        jobs_status = []
        for entry in exp.spec.get("finetuneJobs", []):
            job = store.try_get(FinetuneJob, entry["name"], meta.namespace)
            if job is not None:
                jobs.append(job)
                jobs_status.append({"name": entry["name"], "status": dict(job.status)})
        exp.status["jobsStatus"] = jobs_status

        states = [j.status.get("state") for j in jobs]
        n = len(exp.spec.get("finetuneJobs", []))
        all_terminal = len(jobs) == n and all(
            s in (FinetuneJob.STATE_SUCCESSFUL, FinetuneJob.STATE_FAILED) for s in states
        )
        if not all_terminal:
            store.update(exp)
            return Result(requeue_after=self.poll_s)

        successes = [j for j in jobs if j.status.get("state") == FinetuneJob.STATE_SUCCESSFUL]
        if not successes:
            exp.status["state"] = FinetuneExperiment.STATE_FAILED
            exp.status["stats"] = _now()
            store.update(exp)
            return None

        best = max(
            successes, key=lambda j: parse_score(j.status.get("result", {}).get("score"))
        )
        exp.status["bestVersion"] = self._best_version(store, best)
        exp.status["state"] = FinetuneExperiment.STATE_SUCCESS
        exp.status["stats"] = _now()
        store.update(exp)
        return None

    def _best_version(self, store: ObjectStore, job: FinetuneJob) -> dict:
        """Reference BestVersion{Score, Image, LLM, Hyperparameter, Dataset}
        (:209-215)."""
        ft_spec = job.spec.get("finetune", {}).get("finetuneSpec", {})
        return {
            "score": job.status.get("result", {}).get("score"),
            "image": job.status.get("result", {}).get("image"),
            "checkpointPath": job.status.get("result", {}).get("checkpointPath"),
            "llm": ft_spec.get("llm"),
            "hyperparameter": (ft_spec.get("hyperparameter") or {}).get("hyperparameterRef"),
            "dataset": ft_spec.get("dataset"),
        }


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
