"""Object store: the k8s-API-server-shaped state layer controllers talk to.

Gives the reconcilers the same contract controller-runtime gets from the API
server (SURVEY.md §3: every `Create`/`Status().Update` crosses into the API
server): optimistic concurrency via resourceVersion, finalizer-gated deletion,
owner-reference cascade, label selection, and watch events feeding the work
queue. In-memory with optional JSON-dir persistence; a real-cluster adapter can
implement the same five verbs against the k8s API without touching controller
code.
"""

from __future__ import annotations

import copy
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple, Type

from datatunerx_tpu.operator.api import CustomResource, KIND_BY_NAME


class Conflict(Exception):
    """resourceVersion mismatch (concurrent update)."""


class NotFound(Exception):
    pass


class AlreadyExists(Exception):
    pass


Event = Tuple[str, CustomResource]  # ("ADDED"|"MODIFIED"|"DELETED", obj)


class ObjectStore:
    def __init__(self, persist_dir: Optional[str] = None):
        self._lock = threading.RLock()
        self._objects: Dict[Tuple[str, str], CustomResource] = {}  # (kind, ns/name)
        self._watchers: List[Callable[[Event], None]] = []
        self._rv = 0
        self.persist_dir = persist_dir
        if persist_dir:
            os.makedirs(persist_dir, exist_ok=True)
            self._load()

    # ------------------------------------------------------------- helpers
    def _key(self, kind: str, namespace: str, name: str) -> Tuple[str, str]:
        return (kind, f"{namespace}/{name}")

    def _notify(self, event: Event):
        for w in list(self._watchers):
            try:
                w(event)
            except Exception:
                pass

    def watch(self, fn: Callable[[Event], None]):
        self._watchers.append(fn)

    # --------------------------------------------------------------- verbs
    def create(self, obj: CustomResource) -> CustomResource:
        with self._lock:
            k = self._key(obj.kind, obj.metadata.namespace, obj.metadata.name)
            if k in self._objects:
                raise AlreadyExists(f"{obj.kind} {k[1]}")
            self._rv += 1
            obj = obj.deepcopy()
            obj.metadata.resource_version = self._rv
            self._objects[k] = obj
            self._persist(obj)
            self._notify(("ADDED", obj.deepcopy()))
            return obj.deepcopy()

    def get(self, kind: Type[CustomResource] | str, name: str,
            namespace: str = "default") -> CustomResource:
        kind_name = kind if isinstance(kind, str) else kind.kind
        with self._lock:
            k = self._key(kind_name, namespace, name)
            if k not in self._objects:
                raise NotFound(f"{kind_name} {namespace}/{name}")
            return self._objects[k].deepcopy()

    def try_get(self, kind, name, namespace="default"):
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def update(self, obj: CustomResource) -> CustomResource:
        """Optimistic-concurrency update (spec+metadata+status)."""
        with self._lock:
            k = self._key(obj.kind, obj.metadata.namespace, obj.metadata.name)
            if k not in self._objects:
                raise NotFound(f"{obj.kind} {k[1]}")
            current = self._objects[k]
            if obj.metadata.resource_version != current.metadata.resource_version:
                raise Conflict(
                    f"{obj.kind} {k[1]}: rv {obj.metadata.resource_version} != "
                    f"{current.metadata.resource_version}"
                )
            if obj.to_dict() == current.to_dict():
                # no-op update: no rv bump, no event (otherwise every
                # reconcile-that-updates would re-enqueue itself forever)
                return current.deepcopy()
            self._rv += 1
            obj = obj.deepcopy()
            obj.metadata.resource_version = self._rv
            self._objects[k] = obj
            self._persist(obj)
            self._notify(("MODIFIED", obj.deepcopy()))
            # finalizer-gated deletion completes when the last finalizer is gone
            if obj.metadata.deletion_timestamp and not obj.metadata.finalizers:
                self._finalize_delete(k)
            return obj.deepcopy()

    def delete(self, kind, name, namespace="default"):
        """Marks deletion; object remains until finalizers are removed
        (k8s semantics the reference's finalizer handling relies on,
        finetune_controller.go:98-113)."""
        kind_name = kind if isinstance(kind, str) else kind.kind
        with self._lock:
            k = self._key(kind_name, namespace, name)
            if k not in self._objects:
                raise NotFound(f"{kind_name} {namespace}/{name}")
            obj = self._objects[k]
            if obj.metadata.finalizers:
                if not obj.metadata.deletion_timestamp:
                    self._rv += 1
                    obj.metadata.deletion_timestamp = time.time()
                    obj.metadata.resource_version = self._rv
                    self._persist(obj)
                    self._notify(("MODIFIED", obj.deepcopy()))
                return
            self._finalize_delete(k)

    def _finalize_delete(self, k):
        obj = self._objects.pop(k, None)
        if obj is None:
            return
        self._unpersist(obj)
        self._notify(("DELETED", obj.deepcopy()))
        # owner-reference cascade (controller-runtime GC equivalent)
        for child_key, child in list(self._objects.items()):
            for ref in child.metadata.owner_references:
                if (ref.get("kind") == obj.kind
                        and ref.get("name") == obj.metadata.name
                        and ref.get("uid") == obj.metadata.uid):
                    try:
                        self.delete(child.kind, child.metadata.name,
                                    child.metadata.namespace)
                    except NotFound:
                        pass

    def list(self, kind, namespace: Optional[str] = "default",
             labels: Optional[Dict[str, str]] = None) -> List[CustomResource]:
        kind_name = kind if isinstance(kind, str) else kind.kind
        with self._lock:
            out = []
            for (kn, _), obj in self._objects.items():
                if kn != kind_name:
                    continue
                if namespace and obj.metadata.namespace != namespace:
                    continue
                if labels and any(
                    obj.metadata.labels.get(k) != v for k, v in labels.items()
                ):
                    continue
                out.append(obj.deepcopy())
            return sorted(out, key=lambda o: o.metadata.name)

    # -------------------------------------------------------- persistence
    def _path(self, obj: CustomResource) -> str:
        return os.path.join(
            self.persist_dir,
            f"{obj.kind}__{obj.metadata.namespace}__{obj.metadata.name}.json",
        )

    def _persist(self, obj: CustomResource):
        if not self.persist_dir:
            return
        with open(self._path(obj), "w") as f:
            json.dump(obj.to_dict(), f, indent=1, sort_keys=True, default=str)

    def _unpersist(self, obj: CustomResource):
        if not self.persist_dir:
            return
        try:
            os.remove(self._path(obj))
        except FileNotFoundError:
            pass

    def _load(self):
        for fn in sorted(os.listdir(self.persist_dir)):
            if not fn.endswith(".json"):
                continue
            with open(os.path.join(self.persist_dir, fn)) as f:
                d = json.load(f)
            cls = KIND_BY_NAME.get(d.get("kind"))
            if cls is None:
                continue
            obj = cls.from_dict(d)
            k = self._key(obj.kind, obj.metadata.namespace, obj.metadata.name)
            self._objects[k] = obj
            self._rv = max(self._rv, obj.metadata.resource_version)


def set_owner(child: CustomResource, owner: CustomResource):
    child.metadata.owner_references.append(
        {"kind": owner.kind, "name": owner.metadata.name, "uid": owner.metadata.uid}
    )
