"""``dtx install`` — one-command install bundle (reference ``dtx-ctl``'s
Helm-driven install, reference INSTALL.md:26-48,115-144).

Renders the complete operator install as a list of manifests — Namespace,
the 8 CRDs, RBAC (ServiceAccount + ClusterRole + ClusterRoleBinding),
environment config (non-secret keys → ConfigMap, credentials → Secret),
webhook Service + configurations, and the controller-manager Deployment —
and optionally applies them to an apiserver, create-or-update style.

The env split mirrors the reference's viper config surface
(pkg/config/config.go:7-27): S3/registry credentials land in the Secret,
everything else in the ConfigMap; both are envFrom'd into the Deployment.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple

from datatunerx_tpu.operator.crdgen import all_crds, webhook_manifests

# Credential-ish env keys (reference config.go S3 + registry blocks) go to the
# Secret; anything else is plain config.
SECRET_KEYS = {
    "S3_ACCESS_KEY", "S3_SECRET_KEY", "REGISTRY_USER", "REGISTRY_PASSWORD",
    "DTX_API_TOKEN",
}

APP = "datatunerx-tpu-controller-manager"


def _rbac(namespace: str) -> List[dict]:
    crd_rules = [
        {"apiGroups": [g],
         "resources": rs,
         "verbs": ["create", "delete", "get", "list", "patch", "update",
                   "watch"]}
        for g, rs in (
            ("finetune.datatunerx.io",
             ["finetunes", "finetunejobs", "finetuneexperiments"]),
            ("core.datatunerx.io",
             ["llms", "hyperparameters", "llmcheckpoints"]),
            ("extension.datatunerx.io", ["datasets", "scorings"]),
        )
    ] + [
        {"apiGroups": [g],
         "resources": [f"{r}/status" for r in rs] +
                      [f"{r}/finalizers" for r in rs],
         "verbs": ["get", "patch", "update"]}
        for g, rs in (
            ("finetune.datatunerx.io",
             ["finetunes", "finetunejobs", "finetuneexperiments"]),
            ("core.datatunerx.io",
             ["llms", "hyperparameters", "llmcheckpoints"]),
            ("extension.datatunerx.io", ["datasets", "scorings"]),
        )
    ] + [
        # workload + coordination surface (JobSets, serving Deployments,
        # leader-election Leases, webhook config caBundle injection)
        {"apiGroups": ["jobset.x-k8s.io"], "resources": ["jobsets"],
         "verbs": ["create", "delete", "get", "list", "patch", "update",
                   "watch"]},
        {"apiGroups": ["apps"], "resources": ["deployments"],
         "verbs": ["create", "delete", "get", "list", "patch", "update",
                   "watch"]},
        {"apiGroups": [""], "resources": ["services", "events"],
         "verbs": ["create", "delete", "get", "list", "patch", "update",
                   "watch"]},
        {"apiGroups": ["coordination.k8s.io"], "resources": ["leases"],
         "verbs": ["create", "get", "update"]},
        # the shared webhook cert Secret (SecretBackedCertManager)
        {"apiGroups": [""], "resources": ["secrets"],
         "verbs": ["create", "get", "update"]},
        {"apiGroups": ["admissionregistration.k8s.io"],
         "resources": ["validatingwebhookconfigurations",
                       "mutatingwebhookconfigurations"],
         "verbs": ["get", "update", "patch", "create"]},
    ]
    return [
        {"apiVersion": "v1", "kind": "ServiceAccount",
         "metadata": {"name": APP, "namespace": namespace}},
        {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "ClusterRole",
         "metadata": {"name": "datatunerx-tpu-manager-role"},
         "rules": crd_rules},
        {"apiVersion": "rbac.authorization.k8s.io/v1",
         "kind": "ClusterRoleBinding",
         "metadata": {"name": "datatunerx-tpu-manager-rolebinding"},
         "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                     "kind": "ClusterRole",
                     "name": "datatunerx-tpu-manager-role"},
         "subjects": [{"kind": "ServiceAccount", "name": APP,
                       "namespace": namespace}]},
    ]


CERT_SECRET = "dtx-webhook-server-cert"


def _deployment(namespace: str, image: str, storage_path: str,
                leader_elect: bool, replicas: int) -> dict:
    args = [
        "--backend=kube",
        "--metrics-bind-address=:8080",
        "--health-probe-bind-address=:8081",
        "--webhook-bind-address=:9443",
        "--webhook-cert-dir=/var/lib/dtx/webhook-certs",
        # one CA for the whole Deployment, held in a Secret: replicas
        # converge on it at boot (CAS; exactly one generation wins) and only
        # the election leader rotates it (VERDICT r3 #6 / missing #1)
        f"--webhook-cert-secret={CERT_SECRET}",
        f"--webhook-service-namespace={namespace}",
        f"--kube-namespace={namespace}",
        f"--storage-path={storage_path}",
    ]
    if leader_elect:
        args.append("--leader-elect=true")
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": APP, "namespace": namespace,
                     "labels": {"app": APP}},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app": APP}},
            "template": {
                "metadata": {"labels": {"app": APP}},
                "spec": {
                    "serviceAccountName": APP,
                    "containers": [{
                        "name": "manager",
                        "image": image,
                        "command": ["python", "-m",
                                    "datatunerx_tpu.operator.manager"],
                        "args": args,
                        "envFrom": [
                            {"configMapRef": {"name": "dtx-config"}},
                            {"secretRef": {"name": "dtx-credentials",
                                           "optional": True}},
                        ],
                        "ports": [
                            {"containerPort": 8080, "name": "api-metrics"},
                            {"containerPort": 8081, "name": "probes"},
                            {"containerPort": 9443, "name": "webhooks"},
                        ],
                        "readinessProbe": {
                            "httpGet": {"path": "/readyz", "port": 8081}},
                        "livenessProbe": {
                            "httpGet": {"path": "/healthz", "port": 8081}},
                        "volumeMounts": [
                            {"name": "webhook-certs",
                             "mountPath": "/var/lib/dtx/webhook-certs"},
                            {"name": "storage", "mountPath": storage_path},
                        ],
                    }],
                    "volumes": [
                        # per-pod materialization dir of the shared
                        # --webhook-cert-secret (the operator syncs it via
                        # the API, not a kubelet mount, so standbys pick up
                        # leader rotations without a remount)
                        {"name": "webhook-certs", "emptyDir": {}},
                        {"name": "storage",
                         "persistentVolumeClaim":
                             {"claimName": "dtx-storage"}},
                    ],
                },
            },
        },
    }


def render_install_manifests(
    namespace: str = "datatunerx-dev",
    image: str = "datatunerx-tpu/operator:latest",
    env: Optional[Dict[str, str]] = None,
    storage_path: str = "/storage",
    leader_elect: bool = False,
    replicas: int = 1,
    include_webhooks: bool = True,
) -> List[dict]:
    env = dict(env or {})
    env.setdefault("STORAGE_PATH", storage_path)
    if replicas > 1:
        # HA is only coherent with exactly one active reconciler + one cert
        # rotator; never render a multi-replica deploy without an election
        leader_elect = True
    config = {k: v for k, v in env.items() if k not in SECRET_KEYS}
    secrets = {k: v for k, v in env.items() if k in SECRET_KEYS}

    docs: List[dict] = [
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": namespace}},
    ]
    docs += all_crds()
    docs += _rbac(namespace)
    docs.append({"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": "dtx-config", "namespace": namespace},
                 "data": config})
    if secrets:
        docs.append({"apiVersion": "v1", "kind": "Secret",
                     "metadata": {"name": "dtx-credentials",
                                  "namespace": namespace},
                     "type": "Opaque", "stringData": secrets})
    if include_webhooks:
        docs.append({
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": "datatunerx-webhook-service",
                         "namespace": namespace},
            "spec": {"selector": {"app": APP},
                     "ports": [{"port": 9443, "targetPort": 9443}]},
        })
        docs += webhook_manifests(namespace)
    docs.append(_deployment(namespace, image, storage_path, leader_elect,
                            replicas))
    return docs


# ----------------------------------------------------------------- applying

# kind → (group, version, plural, cluster_scoped)
_KIND_ROUTES: Dict[str, Tuple[str, str, str, bool]] = {
    "Namespace": ("", "v1", "namespaces", True),
    "ServiceAccount": ("", "v1", "serviceaccounts", False),
    "ConfigMap": ("", "v1", "configmaps", False),
    "Secret": ("", "v1", "secrets", False),
    "Service": ("", "v1", "services", False),
    "CustomResourceDefinition": (
        "apiextensions.k8s.io", "v1", "customresourcedefinitions", True),
    "ClusterRole": ("rbac.authorization.k8s.io", "v1", "clusterroles", True),
    "ClusterRoleBinding": (
        "rbac.authorization.k8s.io", "v1", "clusterrolebindings", True),
    "Deployment": ("apps", "v1", "deployments", False),
    "MutatingWebhookConfiguration": (
        "admissionregistration.k8s.io", "v1",
        "mutatingwebhookconfigurations", True),
    "ValidatingWebhookConfiguration": (
        "admissionregistration.k8s.io", "v1",
        "validatingwebhookconfigurations", True),
}


def _path_for(doc: dict, namespace: str, name: Optional[str] = None) -> str:
    kind = doc["kind"]
    group, version, plural, cluster = _KIND_ROUTES[kind]
    prefix = "/api/v1" if not group else f"/apis/{group}/{version}"
    p = prefix
    if not cluster:
        ns = (doc.get("metadata") or {}).get("namespace") or namespace
        p += f"/namespaces/{ns}"
    p += f"/{plural}"
    if name:
        p += f"/{name}"
    return p


def apply_manifest(client, doc: dict, namespace: str = "default") -> str:
    """Create-or-update one manifest through a KubeClient. Returns
    'created'/'configured'."""
    from datatunerx_tpu.operator.kubeclient import ApiError

    name = doc["metadata"]["name"]
    try:
        client.request("POST", _path_for(doc, namespace), body=doc)
        return "created"
    except ApiError as e:
        if e.status != 409:
            raise
    cur = client.request("GET", _path_for(doc, namespace, name))
    upd = copy.deepcopy(doc)
    upd.setdefault("metadata", {})["resourceVersion"] = (
        cur.get("metadata", {}).get("resourceVersion"))
    client.request("PUT", _path_for(doc, namespace, name), body=upd)
    return "configured"


def install(client, namespace: str = "datatunerx-dev", **render_kw) -> List[str]:
    """Apply the full bundle; returns 'kind/name action' lines."""
    out = []
    for doc in render_install_manifests(namespace=namespace, **render_kw):
        action = apply_manifest(client, doc, namespace=namespace)
        out.append(f"{doc['kind'].lower()}/{doc['metadata']['name']} {action}")
    return out
