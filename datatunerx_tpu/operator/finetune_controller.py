"""Finetune controller: one training run (reference
internal/controller/finetune/finetune_controller.go:81-237).

State machine (reference :115-234):
  "" → Init → (deps missing → Pending, retry) → submit training job →
  Pending/Running (poll, requeue) → Succeeded → read completion manifest
  (replaces pod-exec checkpoint-path scrape, :278-305) → status.llmCheckpoint →
  create LLMCheckpoint provenance snapshot (:307-353,621-653) → Successful
  | Failed (sticky terminal states, :115-123)
"""

from __future__ import annotations

import os
from typing import Optional

from datatunerx_tpu.operator import config
from datatunerx_tpu.operator.api import (
    Dataset,
    Finetune,
    FINETUNE_GROUP_FINALIZER,
    Hyperparameter,
    LLM,
    LLMCheckpoint,
    ObjectMeta,
)
from datatunerx_tpu.operator.errors import ErrRecalibrate
from datatunerx_tpu.operator.generate import (
    build_trainer_args,
    generate_training_spec,
    merge_hyperparameters,
    rand_suffix,
)
from datatunerx_tpu.operator.labels import generate_instance_label
from datatunerx_tpu.operator.reconciler import Result
from datatunerx_tpu.operator.store import NotFound, ObjectStore, set_owner
from datatunerx_tpu.training.checkpoint import read_manifest

# Reference parity defaults (finetune_controller.go:55 3s requeue; :171,190
# 30s running poll). Env-tunable so the test suite can run the same state
# machines at ~100ms without weakening any assertion (VERDICT r3 #7).
POLL_INTERVAL_S = float(os.environ.get("DTX_POLL_INTERVAL_S", "3.0"))
RUNNING_POLL_S = float(os.environ.get("DTX_RUNNING_POLL_S", "30.0"))


class FinetuneController:
    kind = Finetune

    def __init__(self, backend, storage_path: Optional[str] = None,
                 health_probe=None, slice_pool=None):
        self.backend = backend
        self.storage_path = storage_path or config.get_storage_path()
        # optional DeviceHealthProbe (operator/health.py): while unhealthy,
        # hold new submissions instead of queueing onto a wedged device
        self.health_probe = health_probe
        # optional SlicePool (operator/placement.py): concurrent jobs onto
        # disjoint sub-slices; no pool = single-tenant, no gating
        self.slice_pool = slice_pool

    # ------------------------------------------------------------ reconcile
    def reconcile(self, store: ObjectStore, ft: Finetune) -> Optional[Result]:
        meta = ft.metadata

        # deletion: tear down the training job, drop finalizer (reference :98-113)
        if meta.deletion_timestamp:
            self.backend.delete(meta.name)
            if self.slice_pool is not None:
                self.slice_pool.release(meta.name)
            if FINETUNE_GROUP_FINALIZER in meta.finalizers:
                meta.finalizers.remove(FINETUNE_GROUP_FINALIZER)
                store.update(ft)
            return None

        if FINETUNE_GROUP_FINALIZER not in meta.finalizers:
            meta.finalizers.append(FINETUNE_GROUP_FINALIZER)
            store.update(ft)
            return Result(requeue_after=0)

        state = ft.status.get("state", "")
        if state in (Finetune.STATE_SUCCESSFUL, Finetune.STATE_FAILED):
            # terminal states are sticky (reference :115-123); the slice goes
            # back to the pool for the next queued job
            if self.slice_pool is not None:
                self.slice_pool.release(meta.name)
            return None

        if state == "":
            ft.status["state"] = Finetune.STATE_INIT
            store.update(ft)
            return Result(requeue_after=0)

        # dependencies (reference :389-405: miss → Pending + retry)
        dataset = store.try_get(Dataset, ft.spec.get("dataset", ""), meta.namespace)
        hp_ref = ft.spec.get("hyperparameter", {}) or {}
        hyperparameter = store.try_get(
            Hyperparameter, hp_ref.get("hyperparameterRef", ""), meta.namespace
        )
        llm = store.try_get(LLM, ft.spec.get("llm", ""), meta.namespace)
        if dataset is None or hyperparameter is None or llm is None:
            if ft.status.get("state") != Finetune.STATE_PENDING:
                ft.status["state"] = Finetune.STATE_PENDING
                store.update(ft)
            raise ErrRecalibrate(
                f"{meta.namespace}/{meta.name}: waiting for dataset/hyperparameter/llm"
            )

        job_status = self.backend.status(meta.name)
        if job_status == "NotFound":
            if self.health_probe is not None and not self.health_probe.healthy:
                reason = self.health_probe.last_error or "device unhealthy"
                if ft.status.get("state") != Finetune.STATE_PENDING or (
                        ft.status.get("backendUnavailable") != reason):
                    ft.status["state"] = Finetune.STATE_PENDING
                    ft.status["backendUnavailable"] = reason
                    store.update(ft)
                return Result(requeue_after=RUNNING_POLL_S)
            # recovered: drop the hold note (persisted by the post-submit
            # update below — no extra write)
            ft.status.pop("backendUnavailable", None)
            placement = None
            hosts = None
            if self.slice_pool is not None:
                # controller-owned placement (SURVEY §7.4#3): every job gets
                # a DISJOINT sub-slice; none free -> hold in Pending
                placement = self.slice_pool.acquire(
                    meta.name, min_chips=int(ft.spec.get("node", 1) or 1) * 4)
                if placement is None:
                    if (ft.status.get("state") != Finetune.STATE_PENDING
                            or not ft.status.get("placementPending")):
                        ft.status["state"] = Finetune.STATE_PENDING
                        ft.status["placementPending"] = "no free TPU slice"
                        store.update(ft)
                    return Result(requeue_after=RUNNING_POLL_S)
                # hosts must match the ASSIGNED slice (4 chips per v5e host):
                # a multi-host podslice expects exactly its host count of
                # workers or TPU init hangs
                hosts = max(1, placement.chips // 4)
            params = merge_hyperparameters(
                hyperparameter.spec.get("parameters", {}),
                hp_ref.get("overrides"),
            )
            # HBM capacity admission (parallel/memory.py): a job whose
            # training state provably exceeds the slice's per-chip HBM is
            # failed HERE with a byte breakdown, not after minutes of
            # on-slice compilation (the reference has no equivalent — its
            # worker just OOMs)
            n_chips = (placement.chips if placement is not None
                       else max(1, int(ft.spec.get("node", 1) or 1)) * 4)
            from datatunerx_tpu.operator.capacity import check_admission

            denied = check_admission(
                ft.spec.get("image", {}).get("path") or "",
                params, n_chips=n_chips,
                generation=os.environ.get("DTX_TPU_GENERATION", "v5e"))
            if denied is not None:
                reason, breakdown = denied
                if self.slice_pool is not None and placement is not None:
                    self.slice_pool.release(meta.name)
                    ft.status.pop("placement", None)
                ft.status["state"] = Finetune.STATE_FAILED
                ft.status["admissionDenied"] = reason
                if breakdown:
                    ft.status["hbmEstimateGB"] = breakdown
                store.update(ft)
                return None
            args = build_trainer_args(ft, dataset.spec, params, uid=meta.uid,
                                      num_workers=hosts)
            spec = generate_training_spec(ft, args, num_hosts=hosts)
            if placement is not None:
                ft.status.pop("placementPending", None)
                ft.status["placement"] = placement.to_dict()
                spec["topology"] = placement.topology
                spec["node_selector"] = placement.node_selector
            self.backend.submit(meta.name, spec)
            ft.status["state"] = Finetune.STATE_PENDING
            ft.status["jobInfo"] = {"jobName": meta.name, "backend": type(self.backend).__name__}
            store.update(ft)
            return Result(requeue_after=POLL_INTERVAL_S)

        if job_status == "Pending":
            return Result(requeue_after=POLL_INTERVAL_S)
        if job_status == "Running":
            if ft.status.get("state") != Finetune.STATE_RUNNING:
                ft.status["state"] = Finetune.STATE_RUNNING
                store.update(ft)
            return Result(requeue_after=RUNNING_POLL_S)
        if job_status == "Failed":
            # bounded retry with checkpoint-resume (SURVEY.md §5.3 — the
            # reference has no retry at all): the trainer auto-resumes from its
            # latest Orbax checkpoint (same uid → same storage key), so a retry
            # continues rather than restarts
            # DTX_DEFAULT_BACKOFF_LIMIT: fleet-wide retry default for specs
            # that don't set backoffLimit (k8s Jobs default 6; ours stays 0
            # so failure-propagation semantics are explicit). Retries resume
            # from the latest checkpoint — a retry continues, not restarts.
            default_limit = int(os.environ.get("DTX_DEFAULT_BACKOFF_LIMIT",
                                               "0"))
            raw = ft.spec.get("backoffLimit")
            try:
                limit = default_limit if raw in (None, "") else int(raw)
            except (TypeError, ValueError):
                limit = default_limit  # junk in the spec must not wedge
                # the Failed transition in an error-requeue loop
            retries = int(ft.status.get("retries", 0))
            if retries < limit:
                self.backend.delete(meta.name)
                ft.status["retries"] = retries + 1
                ft.status["state"] = Finetune.STATE_PENDING
                store.update(ft)
                return Result(requeue_after=POLL_INTERVAL_S)
            ft.status["state"] = Finetune.STATE_FAILED
            store.update(ft)
            return None
        if job_status == "Succeeded":
            return self._on_succeeded(store, ft)
        return Result(requeue_after=POLL_INTERVAL_S)

    # ------------------------------------------------------- success path
    def _on_succeeded(self, store: ObjectStore, ft: Finetune) -> Optional[Result]:
        meta = ft.metadata
        manifest = read_manifest(self.storage_path, meta.uid)
        if manifest is None:
            # completion manifest not yet visible on shared storage
            return Result(requeue_after=POLL_INTERVAL_S)

        if not ft.status.get("llmCheckpoint"):
            ft.status["llmCheckpoint"] = {
                "llmCheckpointRef": f"{meta.name}-{rand_suffix()}",
                "checkpointPath": manifest["checkpoint"],
            }
            store.update(ft)
            return Result(requeue_after=0)

        ref = ft.status["llmCheckpoint"]["llmCheckpointRef"]
        if store.try_get(LLMCheckpoint, ref, meta.namespace) is None:
            self._create_checkpoint_cr(store, ft, ref, manifest)

        ft.status["state"] = Finetune.STATE_SUCCESSFUL
        store.update(ft)
        return None

    def _create_checkpoint_cr(self, store, ft: Finetune, ref: str, manifest: dict):
        """Provenance snapshot: deep-copied dependency specs (reference
        generateLLMCheckpoint, finetune_controller.go:621-653)."""
        meta = ft.metadata
        dataset = store.try_get(Dataset, ft.spec.get("dataset", ""), meta.namespace)
        hp = store.try_get(
            Hyperparameter,
            (ft.spec.get("hyperparameter") or {}).get("hyperparameterRef", ""),
            meta.namespace,
        )
        llm = store.try_get(LLM, ft.spec.get("llm", ""), meta.namespace)
        ckpt = LLMCheckpoint(
            metadata=ObjectMeta(
                name=ref,
                namespace=meta.namespace,
                labels=generate_instance_label(meta.name),
            ),
            spec={
                "llm": {"llmRef": ft.spec.get("llm"),
                        "spec": llm.spec if llm else None},
                "dataset": {"datasetRef": ft.spec.get("dataset"),
                            "spec": dataset.spec if dataset else None},
                "hyperparameter": {
                    "hyperparameterRef": (ft.spec.get("hyperparameter") or {}).get(
                        "hyperparameterRef"
                    ),
                    "spec": hp.spec if hp else None,
                },
                "image": ft.spec.get("image"),
                "checkpoint": manifest["checkpoint"],
                "metrics": manifest.get("metrics", {}),
            },
        )
        set_owner(ckpt, ft)
        store.create(ckpt)
