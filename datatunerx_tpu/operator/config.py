"""Operator environment config (reference pkg/config/config.go:7-87, viper env
bindings). Same keys, TPU-flavored defaults; S3 creds become object-store
endpoints (GCS or S3-compatible)."""

from __future__ import annotations

import os


def _get(key: str, default: str = "") -> str:
    return os.environ.get(key, default)


def get_s3_endpoint() -> str:
    return _get("S3_ENDPOINT")


def get_s3_access_key() -> str:
    return _get("S3_ACCESSKEYID")


def get_s3_secret_key() -> str:
    return _get("S3_SECRETACCESSKEY")


def get_s3_bucket() -> str:
    return _get("S3_BUCKET")


def get_s3_secure() -> bool:
    return _get("S3_SECURE", "false").lower() in ("true", "1")


def object_store_options(uri: str) -> dict:
    """fsspec storage options for a dataset/checkpoint URI, from the same env
    surface the reference binds via viper (S3_ENDPOINT/S3_ACCESSKEYID/
    S3_SECRETACCESSKEY/S3_SECURE, reference pkg/config/config.go:29-55).
    Consumed by utils/storage when opening s3:// URIs; gs:// relies on
    workload identity / application-default credentials."""
    if not uri.startswith("s3://"):
        return {}
    opts: dict = {}
    if get_s3_access_key():
        opts["key"] = get_s3_access_key()
    if get_s3_secret_key():
        opts["secret"] = get_s3_secret_key()
    if get_s3_endpoint():
        scheme = "https" if get_s3_secure() else "http"
        endpoint = get_s3_endpoint()
        if "://" not in endpoint:
            endpoint = f"{scheme}://{endpoint}"
        opts["client_kwargs"] = {"endpoint_url": endpoint}
    return opts


def get_registry_url() -> str:
    return _get("REGISTRY_URL")


def get_registry_repo() -> str:
    return _get("REGISTRY_REPOSITORY_NAME")


def get_registry_user() -> str:
    return _get("REGISTRY_USERNAME")


def get_registry_password() -> str:
    return _get("REGISTRY_PASSWORD")


def get_mount_path() -> str:
    return _get("MOUNT_PATH", "/data")


def get_base_image() -> str:
    # trainer image for TPU-host pods (reference default is the ray GPU image,
    # config.go / generate.go:46-51)
    return _get("BASE_IMAGE", "datatunerx-tpu/trainer:latest")


def get_default_model_path() -> str:
    return _get("LLM_URL", "/models/llama2-7b")


def get_metrics_export_address() -> str:
    return _get("METRICS_EXPORT_ADDRESS")


def get_storage_path() -> str:
    return _get("STORAGE_PATH", "/storage")


def get_log_level() -> str:
    return _get("LOG_LEVEL", "info")


def get_operator_namespace() -> str:
    """Reference pkg/util/util.go:32-42: serviceaccount namespace file with
    datatunerx-dev fallback."""
    path = "/var/run/secrets/kubernetes.io/serviceaccount/namespace"
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return _get("OPERATOR_NAMESPACE", "datatunerx-dev")


def get_tpu_topology() -> str:
    """TPU addition: default slice topology for training jobs (e.g. 2x4)."""
    return _get("TPU_TOPOLOGY", "")


def get_tpu_accelerator() -> str:
    return _get("TPU_ACCELERATOR", "tpu-v5-lite-podslice")
