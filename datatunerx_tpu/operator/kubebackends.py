"""Kubernetes-submitting backends: the JobSet/Deployment feedback loop.

Round-1's ManifestBackend rendered JobSets but could never submit or observe
them (its status() hardcoded "Pending"). These backends close the loop the way
the reference's controller does with RayJob/RayService status polling
(reference internal/controller/finetune/finetune_controller.go:169-199 polls
RayJob JobDeploymentStatus; finetunejob_controller.go:423-424 gates on the
Serve app reporting HEALTHY):

- KubeTrainingBackend: creates the rendered JobSet via the apiserver and maps
  JobSet conditions → Pending | Running | Succeeded | Failed
- KubeServingBackend: creates Deployment + Service and maps Deployment
  availability → PENDING | HEALTHY | FAILED
"""

from __future__ import annotations

from typing import Optional

from datatunerx_tpu.operator.backends import (
    ManifestBackend,
    deployment_state,
    jobset_state,
)
from datatunerx_tpu.operator.kubeclient import ApiError, KubeClient

JOBSET_GROUP, JOBSET_VERSION, JOBSET_PLURAL = "jobset.x-k8s.io", "v1alpha2", "jobsets"


class KubeTrainingBackend(ManifestBackend):
    """Renders the same JobSet as ManifestBackend, but submits it to the
    apiserver and derives status from the JobSet the cluster reports."""

    def __init__(self, client: KubeClient, namespace: str = "default",
                 out_dir: str = "/tmp/dtx-manifests", **render_kw):
        super().__init__(out_dir, **render_kw)
        self.client = client
        self.namespace = namespace

    def submit(self, name: str, spec: dict) -> None:
        manifest = self.render_training(name, spec)
        manifest["metadata"]["namespace"] = self.namespace
        try:
            self.client.create(JOBSET_GROUP, JOBSET_VERSION, JOBSET_PLURAL,
                               self.namespace, manifest)
        except ApiError as e:
            if e.status != 409:  # already submitted: idempotent
                raise

    def status(self, name: str) -> str:
        try:
            js = self.client.get(JOBSET_GROUP, JOBSET_VERSION, JOBSET_PLURAL,
                                 self.namespace, name)
        except ApiError as e:
            if e.status == 404:
                return "NotFound"
            raise
        return jobset_state(js.get("status") or {})

    def delete(self, name: str) -> None:
        try:
            self.client.delete(JOBSET_GROUP, JOBSET_VERSION, JOBSET_PLURAL,
                               self.namespace, name)
        except ApiError as e:
            if e.status != 404:
                raise


class KubeServingBackend(ManifestBackend):
    def __init__(self, client: KubeClient, namespace: str = "default",
                 out_dir: str = "/tmp/dtx-manifests", **render_kw):
        super().__init__(out_dir, **render_kw)
        self.client = client
        self.namespace = namespace

    def deploy(self, name: str, spec: dict) -> None:
        deployment, service = self.render_serving(name, {
            "model_path": spec.get("llmPath") or spec.get("model_path") or "",
            "checkpoint_path": spec.get("checkpointPath")
            or spec.get("checkpoint_path") or "",
            "labels": spec.get("labels", {}),
            "node_selector": spec.get("nodeSelector", {}),
            "tolerations": spec.get("tolerations", []),
            "quantization": spec.get("quantization", ""),
            "slots": spec.get("slots"),
            "replicas": spec.get("replicas"),
        })
        for group, version, plural, body in (
            ("apps", "v1", "deployments", deployment),
            ("", "v1", "services", service),
        ):
            body["metadata"]["namespace"] = self.namespace
            try:
                self.client.create(group, version, plural, self.namespace, body)
            except ApiError as e:
                if e.status != 409:
                    raise

    def status(self, name: str) -> str:
        try:
            dep = self.client.get("apps", "v1", "deployments",
                                  self.namespace, name)
        except ApiError as e:
            if e.status == 404:
                return "NotFound"
            raise
        return deployment_state(dep.get("status") or {})

    def endpoint(self, name: str) -> Optional[str]:
        if self.status(name) != "HEALTHY":
            return None
        return f"http://{name}.{self.namespace}.svc:8000"

    def delete(self, name: str) -> None:
        for group, version, plural in (("apps", "v1", "deployments"),
                                       ("", "v1", "services")):
            try:
                self.client.delete(group, version, plural, self.namespace, name)
            except ApiError as e:
                if e.status != 404:
                    raise
