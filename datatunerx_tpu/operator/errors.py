"""Sentinel errors + requeue policy (reference pkg/domain/valueobject/err.go,
pkg/util/handlererr/handler.go)."""

from __future__ import annotations

import os

from typing import Optional, Tuple


class ErrRecalibrate(Exception):
    """'waiting for dependent resources' — requeue quietly
    (reference valueobject/err.go:5-7)."""


# reference handlererr/handler.go:13,16 parity defaults; env-tunable for
# fast test suites (see tests/conftest.py)
RECALIBRATE_REQUEUE_S = float(os.environ.get("DTX_RECALIBRATE_REQUEUE_S", "10.0"))
ERROR_REQUEUE_S = float(os.environ.get("DTX_ERROR_REQUEUE_S", "30.0"))


def handle_err(err: Optional[BaseException]) -> Tuple[Optional[float], Optional[BaseException]]:
    """(requeue_after_seconds, error_to_surface) — reference
    handlererr/handler.go:11-19 semantics: ErrRecalibrate → 10s silent requeue;
    any other error → 30s requeue + surfaced error."""
    if err is None:
        return None, None
    if isinstance(err, ErrRecalibrate):
        return RECALIBRATE_REQUEUE_S, None
    return ERROR_REQUEUE_S, err
