"""Sentinel errors + requeue policy (reference pkg/domain/valueobject/err.go,
pkg/util/handlererr/handler.go)."""

from __future__ import annotations

from typing import Optional, Tuple


class ErrRecalibrate(Exception):
    """'waiting for dependent resources' — requeue quietly
    (reference valueobject/err.go:5-7)."""


RECALIBRATE_REQUEUE_S = 10.0  # reference handlererr/handler.go:13
ERROR_REQUEUE_S = 30.0  # reference handlererr/handler.go:16


def handle_err(err: Optional[BaseException]) -> Tuple[Optional[float], Optional[BaseException]]:
    """(requeue_after_seconds, error_to_surface) — reference
    handlererr/handler.go:11-19 semantics: ErrRecalibrate → 10s silent requeue;
    any other error → 30s requeue + surfaced error."""
    if err is None:
        return None, None
    if isinstance(err, ErrRecalibrate):
        return RECALIBRATE_REQUEUE_S, None
    return ERROR_REQUEUE_S, err
