"""Lease-based leader election (coordination.k8s.io/v1).

The reference gets HA from controller-runtime's leader election
(reference cmd/controller-manager/app/controller_manager.go:72-74; lease
timings from options.go:38-48). Round 1 accepted ``--leader-elect`` as a
no-op; with the kube adapter this is the real thing: replicas race on a Lease
object, the holder runs the reconcile loop, non-holders block, and a holder
that cannot renew within the lease duration is superseded.

Semantics match client-go's leaderelection package: acquire when the lease is
unheld or expired, renew on a period well under the lease duration, bump
``leaseTransitions`` on takeover, and call ``on_stopped_leading`` when a
renew discovers another holder (the replica should exit and let its
Deployment restart it — the same contract controller-runtime has).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from datatunerx_tpu.operator.kubeclient import ApiError, KubeClient

LEASE_GROUP, LEASE_VERSION, LEASE_PLURAL = "coordination.k8s.io", "v1", "leases"


def _micro_now() -> str:
    t = time.time()
    base = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(t))
    return f"{base}.{int((t % 1) * 1e6):06d}Z"


def _parse_micro(s: Optional[str]) -> Optional[float]:
    if not s:
        return None
    try:
        import calendar

        base, _, frac = s.rstrip("Z").partition(".")
        epoch = calendar.timegm(time.strptime(base, "%Y-%m-%dT%H:%M:%S"))
        return epoch + (float(f"0.{frac}") if frac else 0.0)
    except ValueError:
        return None


class LeaderElector:
    def __init__(
        self,
        client: KubeClient,
        lease_name: str = "datatunerx-tpu-controller-manager",
        namespace: str = "default",
        identity: Optional[str] = None,
        lease_duration_s: float = 15.0,
        renew_period_s: float = 5.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        import os
        import uuid

        self.client = client
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity or f"{os.uname().nodename}_{uuid.uuid4().hex[:8]}"
        self.lease_duration_s = lease_duration_s
        self.renew_period_s = renew_period_s
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.is_leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- lease ops
    def _get_lease(self) -> Optional[dict]:
        try:
            return self.client.get(LEASE_GROUP, LEASE_VERSION, LEASE_PLURAL,
                                   self.namespace, self.lease_name)
        except ApiError as e:
            if e.status == 404:
                return None
            raise

    def _lease_expired(self, lease: dict) -> bool:
        spec = lease.get("spec") or {}
        renew = _parse_micro(spec.get("renewTime")) or _parse_micro(
            spec.get("acquireTime"))
        duration = float(spec.get("leaseDurationSeconds")
                         or self.lease_duration_s)
        return renew is None or (time.time() - renew) > duration

    def try_acquire_or_renew(self) -> bool:
        """One acquire/renew attempt; returns current leadership."""
        now = _micro_now()
        lease = self._get_lease()
        try:
            if lease is None:
                self.client.create(
                    LEASE_GROUP, LEASE_VERSION, LEASE_PLURAL, self.namespace,
                    {
                        "apiVersion": "coordination.k8s.io/v1",
                        "kind": "Lease",
                        "metadata": {"name": self.lease_name,
                                     "namespace": self.namespace},
                        "spec": {
                            "holderIdentity": self.identity,
                            "leaseDurationSeconds": int(self.lease_duration_s),
                            "acquireTime": now,
                            "renewTime": now,
                            "leaseTransitions": 0,
                        },
                    },
                )
                return True
            spec = lease.get("spec") or {}
            holder = spec.get("holderIdentity")
            if holder == self.identity:
                spec["renewTime"] = now
            elif self._lease_expired(lease):
                # takeover: previous holder stopped renewing
                spec.update(
                    holderIdentity=self.identity,
                    acquireTime=now,
                    renewTime=now,
                    leaseTransitions=int(spec.get("leaseTransitions") or 0) + 1,
                )
            else:
                return False
            lease["spec"] = spec
            self.client.replace(LEASE_GROUP, LEASE_VERSION, LEASE_PLURAL,
                                self.namespace, self.lease_name, lease)
            return True
        except ApiError as e:
            if e.status in (409,):  # lost a create/update race this round
                return False
            raise

    # ------------------------------------------------------------ lifecycle
    def run(self, stop: Optional[threading.Event] = None):
        """Blocking election loop: waits for leadership, fires
        on_started_leading, renews until leadership is lost (fires
        on_stopped_leading) or ``stop`` is set.

        A leader that cannot RENEW past its renew deadline must abdicate —
        another replica will rightfully take the lease once it expires, and
        because that expiry clock started at the apiserver-side write of the
        LAST successful renew, waiting the full lease duration locally leaves
        a split-brain window of up to one renew period. client-go's contract
        is renewDeadline < leaseDuration; mirrored here as
        lease_duration − renew_period."""
        stop = stop or self._stop
        last_renew_ok = time.time()
        renew_deadline_s = max(self.lease_duration_s - self.renew_period_s,
                               self.renew_period_s)
        while not stop.is_set():
            try:
                leading = self.try_acquire_or_renew()
                if leading:
                    last_renew_ok = time.time()
            except ApiError:
                # transient apiserver error: hold state only while no standby
                # could yet have observed our lease as expired
                leading = self.is_leader
                if (leading
                        and time.time() - last_renew_ok > renew_deadline_s):
                    leading = False
            if leading and not self.is_leader:
                self.is_leader = True
                if self.on_started_leading:
                    self.on_started_leading()
            elif not leading and self.is_leader:
                self.is_leader = False
                if self.on_stopped_leading:
                    self.on_stopped_leading()
                return
            if stop.wait(self.renew_period_s if self.is_leader
                         else min(self.renew_period_s, 1.0)):
                return

    def start(self):
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="leader-elector")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
