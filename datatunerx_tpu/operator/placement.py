"""Slice placement: concurrent jobs onto disjoint TPU sub-slices.

SURVEY §7.4 hard part #3: the reference gets experiment concurrency for free
from per-job GPU nodes (one RayCluster each); TPU slices are rigid, so
concurrent FinetuneJobs must map to DISJOINT sub-slices/node pools and the
controller owns placement. A ``SlicePool`` is the operator's inventory of
schedulable slices (from the TPU_SLICE_POOL env, JSON); the Finetune
controller acquires one per job, stamps its topology/node-selector into the
rendered JobSet, records the assignment in Finetune.status.placement (so the
pool rebuilds across operator restarts), and releases it on terminal states.

North-star metric 2 (BASELINE.json): 4 concurrent 7B LoRA jobs on a v5e-32 =
a pool of 4 × 2x4 sub-slices.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional


class Slice:
    def __init__(self, name: str, topology: str = "2x4", chips: int = 8,
                 node_selector: Optional[dict] = None):
        self.name = name
        self.topology = topology
        self.chips = chips
        self.node_selector = dict(node_selector or {})

    def to_dict(self) -> dict:
        return {"name": self.name, "topology": self.topology,
                "chips": self.chips, "nodeSelector": self.node_selector}


def pool_from_env() -> Optional["SlicePool"]:
    """TPU_SLICE_POOL: JSON list of slices, e.g.
    ``[{"name":"a","topology":"2x4","chips":8,
        "nodeSelector":{"cloud.google.com/gke-nodepool":"tpu-a"}}, …]``.
    Unset/empty → no pool (single-tenant behavior, no placement gating)."""
    raw = os.environ.get("TPU_SLICE_POOL", "").strip()
    if not raw:
        return None
    slices = [
        Slice(d["name"], d.get("topology", "2x4"), int(d.get("chips", 8)),
              d.get("nodeSelector"))
        for d in json.loads(raw)
    ]
    return SlicePool(slices)


class SlicePool:
    def __init__(self, slices: List[Slice]):
        if len({s.name for s in slices}) != len(slices):
            raise ValueError("slice names must be unique")
        self._slices: Dict[str, Slice] = {s.name: s for s in slices}
        self._held: Dict[str, str] = {}  # slice name -> job name
        self._lock = threading.Lock()

    # ------------------------------------------------------------- queries
    def slices(self) -> List[Slice]:
        return list(self._slices.values())

    def assignment(self, job: str) -> Optional[Slice]:
        with self._lock:
            for sname, holder in self._held.items():
                if holder == job:
                    return self._slices[sname]
        return None

    def free_count(self) -> int:
        with self._lock:
            return len(self._slices) - len(self._held)

    # ------------------------------------------------------------ lifecycle
    def acquire(self, job: str, min_chips: int = 0) -> Optional[Slice]:
        """Smallest free slice with ≥ min_chips; idempotent per job."""
        with self._lock:
            for sname, holder in self._held.items():
                if holder == job:
                    return self._slices[sname]
            candidates = sorted(
                (s for s in self._slices.values()
                 if s.name not in self._held and s.chips >= min_chips),
                key=lambda s: s.chips,
            )
            if not candidates:
                return None
            chosen = candidates[0]
            self._held[chosen.name] = job
            return chosen

    def release(self, job: str) -> None:
        with self._lock:
            for sname, holder in list(self._held.items()):
                if holder == job:
                    del self._held[sname]

    def reset(self) -> None:
        """Drop all assignments (before a full rebuild from CR statuses —
        merging into a stale snapshot can double-book a slice)."""
        with self._lock:
            self._held.clear()

    def restore(self, job: str, slice_name: str) -> None:
        """Rebuild an assignment recorded in Finetune.status.placement (used
        at operator startup so restarts don't double-book slices)."""
        with self._lock:
            if slice_name in self._slices:
                holder = self._held.get(slice_name)
                if holder is not None and holder != job:
                    raise ValueError(
                        f"slice {slice_name} recorded for both {holder} and {job}"
                    )
                self._held[slice_name] = job
