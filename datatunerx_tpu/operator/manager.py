"""Controller-manager entrypoint (reference main.go +
cmd/controller-manager/app/controller_manager.go): wires store + webhooks +
the three finetune controllers + the built-in scoring controller over a chosen
backend pair, exposes health/metrics endpoints, and runs the reconcile loop.

CLI flags mirror the reference options (reference
cmd/controller-manager/app/options/options.go:38-48) where they still make
sense; leader election and cert rotation are meaningless without a real API
server and are accepted as no-ops for drop-in compatibility.
"""

from __future__ import annotations

import argparse
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from datatunerx_tpu.operator.backends import (
    FakeServingBackend,
    FakeTrainingBackend,
    LocalProcessBackend,
    ManifestBackend,
)
from datatunerx_tpu.operator.finetune_controller import FinetuneController
from datatunerx_tpu.operator.finetuneexperiment_controller import (
    FinetuneExperimentController,
)
from datatunerx_tpu.operator.finetunejob_controller import FinetuneJobController
from datatunerx_tpu.operator.reconciler import Manager
from datatunerx_tpu.operator.store import ObjectStore
from datatunerx_tpu.operator.webhooks import AdmittingStore


def build_manager(
    store: ObjectStore,
    training_backend,
    serving_backend,
    storage_path: str | None = None,
    with_scoring: bool = True,
    health_probe=None,
    slice_pool=None,
) -> Manager:
    mgr = Manager(store)
    mgr.training_backend = training_backend  # exposed for the /logs endpoint
    mgr.health_probe = health_probe  # exposed for /metrics
    mgr.slice_pool = slice_pool  # exposed for /metrics
    if slice_pool is not None:
        _restore_placements(store, slice_pool)
    mgr.register(FinetuneController(training_backend, storage_path=storage_path,
                                    health_probe=health_probe,
                                    slice_pool=slice_pool))
    mgr.register(FinetuneJobController(serving_backend,
                                       slice_pool=slice_pool))
    mgr.register(FinetuneExperimentController())
    if with_scoring:
        from datatunerx_tpu.scoring.controller import ScoringController

        mgr.register(ScoringController())
    return mgr


def _restore_placements(store, slice_pool, attempts: int = 5):
    """Rebuild slice assignments from Finetune.status.placement so restarts
    (and leadership takeovers) don't double-book sub-slices. A transient
    apiserver error must NOT silently skip restore — double-booked slices
    wedge both jobs — so this retries briefly and then raises (crash →
    pod restart → clean retry)."""
    import time as _time

    from datatunerx_tpu.operator.api import Finetune

    finetunes = None
    for i in range(attempts):
        try:
            finetunes = store.list(Finetune, namespace=None)
            break
        except Exception as e:  # noqa: BLE001
            print(f"[controller-manager] placement restore list failed "
                  f"({i + 1}/{attempts}): {e}", flush=True)
            if i == attempts - 1:
                raise
            _time.sleep(3)
    # full rebuild, never a merge: a boot-time snapshot in a standby can
    # record holds released (and re-assigned) by the old leader since
    slice_pool.reset()
    for ft in finetunes:
        placement = ft.status.get("placement")
        state = ft.status.get("state", "")
        if placement and state not in (Finetune.STATE_SUCCESSFUL,
                                       Finetune.STATE_FAILED):
            try:
                slice_pool.restore(ft.metadata.name, placement.get("name", ""))
            except ValueError as e:
                print(f"[controller-manager] placement restore: {e}", flush=True)


def _neutralize_webhook_configs(client) -> None:
    """With no webhook server running, leftover failurePolicy:Fail
    configurations reject every CREATE/UPDATE of the webhooked kinds
    cluster-wide (the apiserver can't reach :9443). Flip them to Ignore —
    loudly — so a cryptography-less deployment degrades to in-process-only
    admission instead of a silent cluster-wide outage."""
    for plural, name in (
        ("validatingwebhookconfigurations", "datatunerx-validating-webhook"),
        ("mutatingwebhookconfigurations", "datatunerx-mutating-webhook"),
    ):
        path = f"/apis/admissionregistration.k8s.io/v1/{plural}/{name}"
        try:
            cfg = client.request("GET", path)
        except Exception:  # noqa: BLE001 — absent: nothing to neutralize
            continue
        changed = False
        for wh in cfg.get("webhooks") or []:
            if wh.get("failurePolicy") != "Ignore":
                wh["failurePolicy"] = "Ignore"
                changed = True
        if not changed:
            continue
        try:
            client.request("PUT", path, body=cfg)
            print(f"[controller-manager] WARNING: set failurePolicy=Ignore "
                  f"on {name} — kubectl-applied CRs are NOT validated until "
                  "the webhook server is restored", flush=True)
        except Exception as pe:  # noqa: BLE001
            print(f"[controller-manager] ERROR: could not neutralize {name} "
                  f"({pe}); kubectl CREATE/UPDATE of webhooked kinds will "
                  "FAIL cluster-wide until it is deleted or the webhook "
                  "server is restored", flush=True)


def webhook_cert_sans(service_name: str, namespace: str) -> list:
    """Serving-cert SANs for the admission webhook server.

    A real apiserver routes service-style clientConfig traffic to
    ``<service>.<ns>.svc`` and verifies the webhook's serving certificate
    against that DNS name (the reference's cert-rotator certs the webhook
    Service name for the same reason). localhost stays FIRST: the default
    ``--webhook-url-base`` is derived from dns_names[0] and must keep
    resolving for url-style dev / fake-apiserver routing."""
    return [
        "localhost",
        "127.0.0.1",
        f"{service_name}.{namespace}.svc",
        f"{service_name}.{namespace}.svc.cluster.local",
    ]


class _HealthHandler(BaseHTTPRequestHandler):
    """Probe-only endpoint (reference --health-probe-bind-address,
    options.go:13-14); metrics live on the API address only."""

    manager: Manager = None

    def do_GET(self):
        if self.path in ("/healthz", "/readyz"):
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"ok")
        else:
            self.send_response(404)
            self.end_headers()

    def log_message(self, *a):
        pass


def main(argv=None):
    p = argparse.ArgumentParser(prog="datatunerx-tpu-controller-manager")
    # reference options.go:38-48
    p.add_argument("--metrics-bind-address", default=":8080")
    p.add_argument("--health-probe-bind-address", default=":8081")
    p.add_argument("--leader-elect", default="false",
                   help="lease-based leader election (kube backend; no-op "
                        "for in-process stores, which are single-replica "
                        "by construction)")
    p.add_argument("--leader-lease-duration", type=float, default=15.0)
    p.add_argument("--leader-renew-period", type=float, default=5.0)
    p.add_argument("--enable-cert-rotator", default="true",
                   help="kube backend: rotate the webhook TLS cert before "
                        "expiry and re-patch the caBundle (reference "
                        "cert-rotator, controller_manager.go:83-111)")
    p.add_argument("--webhook-bind-address", default=":9443",
                   help="kube backend: admission webhook HTTPS address "
                        "(reference webhook server port, "
                        "controller_manager.go:70); ':0' picks a free port, "
                        "'disabled' turns the webhook server off")
    p.add_argument("--webhook-cert-dir", default="/tmp/dtx-webhook-certs",
                   help="local TLS cert dir for the webhook server (with "
                        "--webhook-cert-secret: the materialization dir for "
                        "the shared Secret)")
    p.add_argument("--webhook-cert-secret", default=None,
                   help="name of a Secret holding the webhook CA + serving "
                        "cert, shared by every replica (HA; rotation is "
                        "gated on the election leader). Unset: certs are "
                        "generated per-process under --webhook-cert-dir, "
                        "which is only correct at replicas=1")
    p.add_argument("--webhook-url-base", default=None,
                   help="externally reachable base URL of this webhook "
                        "server, written into the webhook configurations "
                        "(default: https://<first-cert-SAN>:<port>)")
    p.add_argument("--webhook-service-name",
                   default="datatunerx-webhook-service",
                   help="Service routing admission traffic to this webhook "
                        "server (deploy/webhooks.yaml clientConfig.service); "
                        "its cluster DNS names are added to the serving-cert "
                        "SANs so a real apiserver's TLS verification of "
                        "service-style routing succeeds")
    p.add_argument("--webhook-service-namespace", default=None,
                   help="namespace of that Service (default: the pod's own "
                        "namespace via the serviceaccount file / "
                        "OPERATOR_NAMESPACE — NOT --kube-namespace, which "
                        "scopes the CRs being reconciled)")
    # TPU-native options
    p.add_argument("--persist-dir", default=None,
                   help="JSON object store directory (durable CRs)")
    p.add_argument("--backend", choices=["local", "manifest", "kube", "fake"],
                   default="local")
    p.add_argument("--workdir", default="/tmp/dtx-operator")
    p.add_argument("--storage-path", default=None)
    # kube mode: CRs + workloads through a real apiserver (in-cluster config
    # is auto-detected when --kube-url is omitted)
    p.add_argument("--kube-url", default=None,
                   help="apiserver base URL (default: in-cluster config)")
    p.add_argument("--kube-namespace", default="default")
    p.add_argument("--device-health-interval", type=float, default=0.0,
                   help="seconds between local-device health probes (0 = off; "
                        "--backend local only — cluster backends rely on "
                        "kubelet/JobSet health); while unhealthy, new "
                        "Finetunes hold in Pending instead of submitting "
                        "onto a wedged device")
    args = p.parse_args(argv)
    if args.device_health_interval > 0 and args.backend != "local":
        print("[controller-manager] warning: --device-health-interval only "
              f"applies to --backend local (got {args.backend!r}); ignored",
              flush=True)

    if args.storage_path:
        # one source of truth: generate.py renders --storage_path for trainers
        # from env config, and the Finetune controller reads manifests from the
        # same key — both must see this value
        import os

        os.environ["STORAGE_PATH"] = args.storage_path

    if args.backend == "kube":
        from datatunerx_tpu.operator.kubebackends import (
            KubeServingBackend,
            KubeTrainingBackend,
        )
        from datatunerx_tpu.operator.kubeclient import KubeClient
        from datatunerx_tpu.operator.kubestore import KubeObjectStore

        client = KubeClient(base_url=args.kube_url,
                            namespace=args.kube_namespace)
        from datatunerx_tpu.operator.placement import pool_from_env

        store = AdmittingStore(KubeObjectStore(client))
        training = KubeTrainingBackend(client, namespace=args.kube_namespace,
                                       out_dir=args.workdir)
        serving = KubeServingBackend(client, namespace=args.kube_namespace,
                                     out_dir=args.workdir)
        mgr = build_manager(store, training, serving,
                            storage_path=args.storage_path,
                            slice_pool=pool_from_env())
        mgr.leader_callbacks = []

        # Leader election BEFORE webhook setup: the cert-rotation loop gates
        # generation on leadership (standbys only hot-reload the shared
        # Secret), so the webhook server needs the elector handle.
        elector = None
        if str(args.leader_elect).lower() in ("true", "1", "yes"):
            import os as _os

            from datatunerx_tpu.operator.leaderelection import LeaderElector

            # lost leadership = exit; the Deployment restarts the replica,
            # which re-enters the election (controller-runtime's contract)
            elector = LeaderElector(
                client, namespace=args.kube_namespace,
                lease_duration_s=args.leader_lease_duration,
                renew_period_s=args.leader_renew_period,
                on_stopped_leading=lambda: _os._exit(1),
            )

        # Kubernetes-native admission: serve the webhook rules over TLS and
        # register the configurations so kubectl-applied CRs are validated by
        # the apiserver itself, not just by this process's AdmittingStore.
        if args.webhook_bind_address != "disabled":
            import importlib.util

            if importlib.util.find_spec("cryptography") is None:
                # precise probe, NOT a broad except ImportError around the
                # setup block: a genuine packaging/refactor bug in our own
                # modules must crash loudly, while a host without
                # cryptography degrades to in-process-only admission.
                # Existing failurePolicy:Fail configurations from a prior
                # run would keep rejecting EVERY kubectl CREATE/UPDATE
                # against an unserved :9443 — neutralize them (a later
                # healthy start's install_webhooks restores Fail).
                print("[controller-manager] WARNING: admission webhook "
                      "server disabled (no module named 'cryptography'); "
                      "install 'cryptography' to enforce validation on "
                      "kubectl-applied CRs (in-process admission via "
                      "AdmittingStore remains active)", flush=True)
                _neutralize_webhook_configs(client)
            else:
                from datatunerx_tpu.operator.webhook_server import (
                    AdmissionWebhookServer,
                    CertManager,
                    install_webhooks,
                )

                wh_host, _, wh_port = args.webhook_bind_address.rpartition(":")
                # SANs must cover service-style routing (failurePolicy Fail
                # would otherwise reject every CREATE/UPDATE cluster-wide).
                # The Service lives in the OPERATOR's namespace (the pod's
                # own, per the serviceaccount file), which is not the same
                # thing as --kube-namespace (the CR scope).
                from datatunerx_tpu.operator.config import (
                    get_operator_namespace,
                )

                wh_ns = (args.webhook_service_namespace
                         or get_operator_namespace())
                sans = webhook_cert_sans(args.webhook_service_name, wh_ns)
                if args.webhook_cert_secret:
                    from datatunerx_tpu.operator.webhook_server import (
                        SecretBackedCertManager,
                    )

                    # HA: one CA for the whole Deployment, held in a Secret.
                    # Boot is leaderless-CAS (first writer wins, losers
                    # converge); ongoing rotation is leader-gated below.
                    certs = SecretBackedCertManager(
                        client, namespace=wh_ns,
                        secret_name=args.webhook_cert_secret,
                        cert_dir=args.webhook_cert_dir, dns_names=sans)
                else:
                    certs = CertManager(args.webhook_cert_dir,
                                        dns_names=sans)
                wh_srv = AdmissionWebhookServer(
                    certs, host=wh_host or "0.0.0.0",
                    port=int(wh_port or 9443))
                base = (args.webhook_url_base
                        or f"https://{certs.dns_names[0]}:{wh_srv.port}")
                rotate = (3600.0 if str(args.enable_cert_rotator).lower()
                          in ("true", "1", "yes") else 0.0)
                wh_srv.start(
                    rotation_check_s=rotate,
                    on_rotate=lambda ca: install_webhooks(client, ca, base),
                    is_leader=(None if elector is None
                               else lambda: elector.is_leader),
                )
                install_webhooks(client, certs.ca_bundle_b64(), base)

                def _reassert_ca():
                    # A leader can rotate the Secret and crash before
                    # re-patching the caBundle; whoever takes over converges
                    # on the Secret (rotating it if it went stale), reloads
                    # its own TLS, and re-asserts the CURRENT CA into the
                    # webhook configs on promotion.
                    if certs.ensure(as_leader=True):
                        wh_srv._ssl_ctx.load_cert_chain(
                            certs.cert_path, certs.key_path)
                    install_webhooks(client, certs.ca_bundle_b64(), base)

                mgr.leader_callbacks.append(_reassert_ca)
                print("[controller-manager] admission webhooks on "
                      f":{wh_srv.port}", flush=True)

        return _run_manager(args, store, mgr, elector=elector)

    store = AdmittingStore(ObjectStore(persist_dir=args.persist_dir))
    probe = None
    if args.backend == "local":
        training = LocalProcessBackend(args.workdir)
        from datatunerx_tpu.serving.local_backend import LocalServingBackend

        serving = LocalServingBackend(args.workdir)
        if args.device_health_interval > 0:
            from datatunerx_tpu.operator.health import DeviceHealthProbe

            probe = DeviceHealthProbe(
                interval_s=args.device_health_interval,
                idle_check=lambda: not training.has_active_jobs(),
            ).start()
    elif args.backend == "manifest":
        training = ManifestBackend(args.workdir)
        serving = FakeServingBackend()
    else:
        training, serving = FakeTrainingBackend(), FakeServingBackend()

    from datatunerx_tpu.operator.placement import pool_from_env

    mgr = build_manager(store, training, serving, storage_path=args.storage_path,
                        health_probe=probe, slice_pool=pool_from_env())
    return _run_manager(args, store, mgr)


def _run_manager(args, store, mgr: Manager, elector=None) -> int:
    # REST API (kubectl-shaped user surface + metrics) on the metrics address,
    # plain health probes on the probe address — mirroring the reference's
    # :8080/:8081 split (options.go:13-14)
    from datatunerx_tpu.operator.apiserver import serve_api

    api_host, _, api_port = args.metrics_bind_address.rpartition(":")
    api_srv, api_port = serve_api(
        store, manager=mgr, port=int(api_port),
        host=api_host or "127.0.0.1",  # loopback unless explicitly widened
    )

    health_port = int(args.health_probe_bind_address.rsplit(":", 1)[-1])
    _HealthHandler.manager = mgr
    srv = ThreadingHTTPServer(("0.0.0.0", health_port), _HealthHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    if elector is not None:
        def lead():
            print(f"[controller-manager] became leader as {elector.identity}",
                  flush=True)
            for cb in getattr(mgr, "leader_callbacks", None) or []:
                try:
                    cb()
                except Exception as e:  # noqa: BLE001 — a failed CA
                    # re-assert must not block promotion; the rotation loop
                    # retries on its next check
                    print(f"[controller-manager] leader callback failed: {e}",
                          flush=True)
            if getattr(mgr, "slice_pool", None) is not None:
                # re-read assignments at takeover: the boot-time snapshot of
                # a standby predates jobs the previous leader placed
                _restore_placements(mgr.store, mgr.slice_pool)
            mgr.sync_all()
            mgr.start()

        elector.on_started_leading = lead
        elector.start()
        print(
            f"[controller-manager] standing by for leadership; api+metrics on "
            f":{api_port}, health on :{health_port}",
            flush=True,
        )
    else:
        mgr.sync_all()
        mgr.start()
        print(
            f"[controller-manager] running; api+metrics on :{api_port}, "
            f"health on :{health_port}",
            flush=True,
        )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        mgr.stop()
        srv.shutdown()
        api_srv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
