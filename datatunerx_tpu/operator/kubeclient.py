"""Minimal Kubernetes API client — stdlib only (urllib + ssl).

The real-cluster transport under ``KubeObjectStore`` and the kube-backed
training/serving backends. Plays the role controller-runtime's client plays in
the reference (reference internal/controller/finetune/finetune_controller.go
reads/writes CRs and RayJobs through the manager's client); here it is a thin
REST layer over the apiserver's group/version/plural endpoints:

  /apis/{group}/{version}/namespaces/{ns}/{plural}[/{name}[/status]]

Supports in-cluster configuration (service-account token + CA at the standard
mount paths) and explicit base-url/token for tests against a fake apiserver.
"""

from __future__ import annotations

import http.client
import json
import os
import ssl
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Iterable, Optional

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ApiError(Exception):
    def __init__(self, status: int, reason: str = "", body: str = ""):
        self.status = status
        self.reason = reason
        self.body = body
        super().__init__(f"kube api {status} {reason}: {body[:200]}")


class KubeClient:
    def __init__(
        self,
        base_url: Optional[str] = None,
        token: Optional[str] = None,
        ca_cert: Optional[str] = None,
        namespace: str = "default",
        timeout: float = 30.0,
    ):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "no base_url and not in-cluster (KUBERNETES_SERVICE_HOST unset)"
                )
            base_url = f"https://{host}:{port}"
            token_file = os.path.join(SA_DIR, "token")
            if token is None and os.path.exists(token_file):
                with open(token_file) as f:
                    token = f.read().strip()
            ca_file = os.path.join(SA_DIR, "ca.crt")
            if ca_cert is None and os.path.exists(ca_file):
                ca_cert = ca_file
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.namespace = namespace
        self.timeout = timeout
        if self.base_url.startswith("https"):
            self._ctx = ssl.create_default_context(cafile=ca_cert)
            if ca_cert is None:  # token-only auth against self-signed apiserver
                self._ctx.check_hostname = False
                self._ctx.verify_mode = ssl.CERT_NONE
        else:
            self._ctx = None

    # ------------------------------------------------------------- request
    def request(self, method: str, path: str, body: Optional[dict] = None,
                timeout: Optional[float] = None) -> dict:
        url = self.base_url + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(
                req, timeout=timeout or self.timeout, context=self._ctx
            ) as resp:
                raw = resp.read()
        except urllib.error.HTTPError as e:
            raise ApiError(e.code, e.reason, e.read().decode(errors="replace"))
        except urllib.error.URLError as e:
            raise ApiError(0, str(e.reason), "")
        except (OSError, http.client.HTTPException) as e:
            # raw socket / HTTP-protocol failures (ConnectionResetError,
            # RemoteDisconnected, …) are not URLError subclasses; callers —
            # the leader elector above all — rely on every transport failure
            # surfacing as ApiError, never a leaked socket exception
            raise ApiError(0, repr(e), "")
        return json.loads(raw) if raw else {}

    # ---------------------------------------------------------- path utils
    @staticmethod
    def path_for(group: str, version: str, plural: str,
                 namespace: Optional[str], name: Optional[str] = None,
                 subresource: Optional[str] = None) -> str:
        prefix = "/api/v1" if not group else f"/apis/{group}/{version}"
        p = prefix
        if namespace:
            p += f"/namespaces/{namespace}"
        p += f"/{plural}"
        if name:
            p += f"/{name}"
        if subresource:
            p += f"/{subresource}"
        return p

    # ----------------------------------------------------------- CRUD-ish
    def create(self, group, version, plural, namespace, body) -> dict:
        return self.request(
            "POST", self.path_for(group, version, plural, namespace), body
        )

    def get(self, group, version, plural, namespace, name) -> dict:
        return self.request(
            "GET", self.path_for(group, version, plural, namespace, name)
        )

    def replace(self, group, version, plural, namespace, name, body,
                subresource: Optional[str] = None) -> dict:
        return self.request(
            "PUT",
            self.path_for(group, version, plural, namespace, name, subresource),
            body,
        )

    def delete(self, group, version, plural, namespace, name) -> dict:
        return self.request(
            "DELETE", self.path_for(group, version, plural, namespace, name)
        )

    def list(self, group, version, plural, namespace=None,
             label_selector: Optional[str] = None) -> dict:
        path = self.path_for(group, version, plural, namespace)
        if label_selector:
            path += "?labelSelector=" + urllib.parse.quote(label_selector)
        return self.request("GET", path)

    # -------------------------------------------------------------- watch
    def watch(
        self,
        group, version, plural,
        namespace: Optional[str],
        on_event: Callable[[str, dict], None],
        stop: threading.Event,
        resource_version: Optional[str] = None,
        reconnect_delay: float = 1.0,
    ) -> None:
        """Blocking watch loop: streams JSON event lines, invoking
        ``on_event(type, object)``; reconnects (from the last seen
        resourceVersion) until ``stop`` is set. Run on a daemon thread."""
        rv = resource_version
        while not stop.is_set():
            path = self.path_for(group, version, plural, namespace)
            q = {"watch": "true"}
            if rv:
                q["resourceVersion"] = rv
            url = self.base_url + path + "?" + urllib.parse.urlencode(q)
            req = urllib.request.Request(url)
            if self.token:
                req.add_header("Authorization", f"Bearer {self.token}")
            try:
                with urllib.request.urlopen(
                    req, timeout=330, context=self._ctx
                ) as resp:
                    for line in resp:
                        if stop.is_set():
                            return
                        line = line.strip()
                        if not line:
                            continue
                        ev = json.loads(line)
                        obj = ev.get("object", {})
                        if ev.get("type") == "ERROR":
                            # in-stream Status (e.g. 410 Gone after etcd
                            # compaction): the bookmark is stale — restart
                            # from a fresh list or the watch wedges forever
                            if obj.get("code") == 410:
                                rv = None
                            break
                        new_rv = (obj.get("metadata") or {}).get("resourceVersion")
                        if new_rv:
                            rv = new_rv
                        if ev.get("type") == "BOOKMARK":
                            continue
                        on_event(ev.get("type", ""), obj)
            except urllib.error.HTTPError as e:
                if e.code == 410:  # history compacted: stale resourceVersion
                    rv = None
                if stop.wait(reconnect_delay):
                    return
            except (urllib.error.URLError, OSError, ValueError):
                if stop.wait(reconnect_delay):
                    return


def iter_chunked_json(lines: Iterable[bytes]):
    """Parse a k8s watch stream (one JSON object per line)."""
    for line in lines:
        line = line.strip()
        if line:
            yield json.loads(line)
