"""Event reasons (reference pkg/events/events.go:3-6)."""

REASON_FINETUNE_JOB_CREATED = "FinetuneJobCreated"
REASON_FINETUNE_JOB_FAILED = "FinetuneJobFailed"
REASON_CHECKPOINT_CAPTURED = "CheckpointCaptured"
REASON_SERVE_READY = "ServeReady"
REASON_SCORING_COMPLETE = "ScoringComplete"
