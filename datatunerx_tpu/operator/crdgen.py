"""CRD + webhook-configuration manifest rendering for the 8 kinds.

The reference gets its CRDs from `make manifests` (controller-gen over the
meta-server Go types, reference Makefile:96-113); here the source of truth is
operator/api.py + the webhook validation rules, rendered as
apiextensions.k8s.io/v1 CustomResourceDefinitions with the status subresource
enabled (the split KubeObjectStore.update relies on).

Lives in the package (not scripts/) so `dtx install` can render a complete
install bundle without a repo checkout; scripts/gen_crds.py is the
file-writing wrapper.
"""

from __future__ import annotations

from datatunerx_tpu.operator.api import ALL_KINDS
from datatunerx_tpu.operator.webhooks import OPTIMIZERS, SCHEDULERS

ANY = {"x-kubernetes-preserve-unknown-fields": True}
STR = {"type": "string"}
INT = {"type": "integer"}
BOOL = {"type": "boolean"}


def obj(props: dict, required=None, open_ended=True) -> dict:
    d: dict = {"type": "object", "properties": props}
    if required:
        d["required"] = list(required)
    if open_ended:
        # forward-compatible: extra fields tolerated (the admission webhook
        # enforces the strict rules)
        d["x-kubernetes-preserve-unknown-fields"] = True
    return d


def arr(items: dict) -> dict:
    return {"type": "array", "items": items}


HYPERPARAMETERS = obj({
    "scheduler": {"type": "string", "enum": sorted(SCHEDULERS)},
    "optimizer": {"type": "string", "enum": sorted(OPTIMIZERS)},
    "int4": STR, "int8": STR,
    "loRA_R": STR, "loRA_Alpha": STR, "loRA_Dropout": STR,
    "learningRate": STR, "epochs": STR, "blockSize": STR, "batchSize": STR,
    "warmupRatio": STR, "weightDecay": STR, "gradAccSteps": STR,
    "trainerType": STR, "PEFT": STR, "FP16": STR,
    # TPU additions (SURVEY.md §7.1 Hyperparameter row)
    "topology": STR,
    # CLOSED node (open_ended=False): the SPMD driver consumes exactly these
    # axes (tuning/train.py:149-157) — unknown keys here are typos that
    # would silently change the mesh, so the apiserver prunes them
    "meshShape": obj({"dcn": INT, "dp": INT, "fsdp": INT, "tp": INT,
                      "sp": INT}, open_ended=False),
    "packSequences": STR,
    "loRATarget": STR, "attention": STR,
    "rewardModel": STR,  # trainerType ppo: rm-stage run dir
    "quantImpl": {"type": "string", "enum": ["pallas", "xla"]},
})

FINETUNE_SPEC = obj({
    "dataset": STR,
    "llm": STR,
    "hyperparameter": obj({
        "hyperparameterRef": STR,
        "overrides": HYPERPARAMETERS,
    }),
    "image": obj({"name": STR, "path": STR, "imagePullPolicy": STR}),
    "node": INT,
    "resource": ANY,
    "backoffLimit": INT,
}, required=["dataset", "llm"])

SPECS = {
    "Finetune": FINETUNE_SPEC,
    "FinetuneJob": obj({
        "finetune": obj({"name": STR, "finetuneSpec": FINETUNE_SPEC},
                        required=["finetuneSpec"]),
        "scoringPluginConfig": obj({"name": STR, "parameters": STR}),
        "serveConfig": obj({
            "nodeSelector": ANY, "tolerations": arr(ANY),
            # TPU additions (generate.py generate_serving_spec)
            "quantization": {"type": "string",
                             "enum": ["", "int8", "int4", "nf4"]},
            "slots": INT,
            # dynamic multi-adapter plane (serving --adapter_pool /
            # --adapter_rank_max): N HBM pool slots tenant adapters load
            # into at runtime via /admin/adapters, rank-padded to the max
            "adapterPool": INT,
            "adapterRankMax": INT,
            # gateway tier (gateway/server.py): N replicas behind one
            # endpoint with routing/admission/failover; min/max bound the
            # autoscale hint the controller applies
            "replicas": INT,
            "gateway": BOOL,
            "policy": {"type": "string",
                       "enum": ["least_busy", "round_robin"]},
            "minReplicas": INT,
            "maxReplicas": INT,
            # paged-KV overcommit (serving --kv_overcommit): admission by
            # prompt-need + headroom, on-demand growth, preempt-and-park
            "kvOvercommit": {"type": "string", "enum": ["", "off", "on"]},
            # speculative decoding (serving --spec_draft_config/--spec_k/
            # --spec_mode): draft-propose / verify-k decode
            "specDraft": STR,
            "specK": INT,
            "specMode": {"type": "string",
                         "enum": ["", "auto", "on", "off"]},
            # tree-draft verification (serving --spec_tree): 'WxD' flattens
            # a W-wide, D-deep token tree into one batched verify forward
            "specTree": STR,
            # fused on-chip sampling epilogue (serving --sampling_epilogue):
            # decode programs sample in the traced computation instead of
            # materializing full-vocab logits for the host sampler
            "samplingEpilogue": {"type": "string",
                                 "enum": ["", "auto", "on", "off"]},
            # disaggregated fleet plane (gateway/server.py): role is a
            # single role for one server or a comma cycle the gateway
            # assigns across spawned replicas; prompts >= the threshold
            # prefer prefill specialists; the fleet knobs enable the
            # shared prefix tier / prefill→decode handoff / peer KV spill
            "role": STR,
            "prefillThreshold": INT,
            "fleetPrefixMb": {"type": "number"},
            "fleetHandoff": BOOL,
            "fleetSpill": BOOL,
            # multi-tenant QoS plane (datatunerx_tpu/tenancy/): tenants is
            # an inline tenant -> {tier, adapters, share, kvBlockQuota,
            # ttftP95Ms} map (webhook-validated) or tenantsConfig a file
            # path mounted into the pod; hostAdapterCacheMb bounds the
            # host-RAM adapter tier evicted pool adapters fall back to
            "tenants": ANY,
            "tenantsConfig": STR,
            "hostAdapterCacheMb": {"type": "number"},
        }),
    }, required=["finetune"]),
    "FinetuneExperiment": obj({
        "finetuneJobs": arr(obj({"name": STR, "spec": ANY})),
        "pending": BOOL,
    }, required=["finetuneJobs"]),
    "LLM": obj({"path": STR, "image": ANY}),
    "Hyperparameter": obj({"parameters": HYPERPARAMETERS}),
    "LLMCheckpoint": obj({
        "llm": ANY, "dataset": ANY, "hyperparameter": ANY,
        "image": ANY, "checkpoint": STR, "checkpointImage": ANY,
        "metrics": ANY,
    }),
    "Dataset": obj({
        "datasetMetadata": obj({
            "datasetInfo": obj({
                "subsets": arr(obj({
                    "name": STR,
                    "splits": obj({
                        "train": obj({"file": STR}),
                        "validate": obj({"file": STR}),
                        "test": obj({"file": STR}),
                    }),
                })),
                "features": arr(obj({"name": STR, "mapTo": STR})),
            }),
        }),
    }, required=["datasetMetadata"]),
    "Scoring": obj({
        "inferenceService": STR,
        # named adapter on a multi-adapter engine: N Scorings against ONE
        # endpoint compare N tuned checkpoints side-by-side (BASELINE row 6)
        "model": STR,
        "plugin": obj({"loadPlugin": BOOL, "name": STR, "parameters": STR}),
        # closed: the scorer consumes exactly prompt/reference per probe
        "probes": arr(obj({"prompt": STR, "reference": STR},
                          open_ended=False)),
        # dataset-driven scoring (beyond the reference's probe-only sibling)
        "datasetRef": STR,
        "metric": {"type": "string", "enum": ["generation", "perplexity"]},
        "maxExamples": INT,
    }),
}


def crd_for(cls) -> dict:
    group, _, version = cls.api_version.partition("/")
    plural = cls.kind.lower() + "s"
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{group}"},
        "spec": {
            "group": group,
            "names": {
                "kind": cls.kind,
                "listKind": f"{cls.kind}List",
                "plural": plural,
                "singular": cls.kind.lower(),
            },
            "scope": "Namespaced",
            "versions": [{
                "name": version,
                "served": True,
                "storage": True,
                "subresources": {"status": {}},
                "schema": {
                    "openAPIV3Schema": {
                        "type": "object",
                        "properties": {
                            "spec": SPECS[cls.kind],
                            "status": ANY,
                        },
                    },
                },
                "additionalPrinterColumns": [{
                    "name": "State",
                    "type": "string",
                    "jsonPath": ".status.state",
                }],
            }],
        },
    }


def all_crds() -> list:
    return [crd_for(cls) for cls in ALL_KINDS]


def webhook_manifests(namespace: str = "datatunerx-dev") -> list:
    """Deploy-time Mutating/ValidatingWebhookConfiguration manifests
    (service-style clientConfig; the operator's cert manager injects the
    caBundle at startup — reference cert-rotator behavior,
    controller_manager.go:83-111). The test/dev path installs url-style
    configs directly via operator.webhook_server.install_webhooks."""
    from datatunerx_tpu.operator.webhook_server import webhook_configurations

    configs = webhook_configurations(ca_bundle_b64="", base_url="")
    for cfg in configs:
        for wh in cfg["webhooks"]:
            path = wh["clientConfig"]["url"].rsplit("/", 1)[-1]
            wh["clientConfig"] = {
                "service": {
                    "name": "datatunerx-webhook-service",
                    "namespace": namespace,
                    "path": f"/{path}",
                    "port": 9443,
                },
                "caBundle": "",  # injected by the operator at startup
            }
    return configs
