"""Reconciler framework: controller-runtime semantics in ~150 lines.

Mirrors what the reference gets from sigs.k8s.io/controller-runtime
(SURVEY.md §2.1 G2): a Manager owning a work queue per controller, watch-driven
re-entry, `Result{requeue_after}`, MaxConcurrentReconciles=1 (the reference
pins this, finetunejob_controller.go:209), conflict retry, and the
handle_err requeue policy applied to reconciler exceptions.

Controllers implement:
    kind: the CR class they own
    reconcile(store, obj) -> Result | None
    watches(event) -> list[(namespace, name)]   # optional cross-kind triggers
"""

from __future__ import annotations

import os
import dataclasses
import heapq
import threading
import time
from typing import List, Optional, Protocol, Tuple

from datatunerx_tpu.operator.errors import handle_err
from datatunerx_tpu.operator.store import Conflict, NotFound, ObjectStore


@dataclasses.dataclass
class Result:
    requeue_after: Optional[float] = None  # seconds


class Controller(Protocol):
    kind: type

    def reconcile(self, store: ObjectStore, obj) -> Optional[Result]: ...


class Manager:
    """Drives all registered controllers off one store. `run_until_idle` is the
    envtest-style synchronous mode used by tests and the local pipeline runner;
    `start`/`stop` run the same loop on a background thread."""

    def __init__(self, store: ObjectStore):
        self.store = store
        self.controllers: List[Controller] = []
        self._queue: List[Tuple[float, int, str, str, str]] = []  # (t, seq, kind, ns, name)
        self._seq = 0
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.errors: List[Tuple[str, BaseException]] = []
        self.reconcile_counts: dict = {}  # kind -> reconciles run
        store.watch(self._on_event)

    # ------------------------------------------------------------ plumbing
    def register(self, controller: Controller):
        self.controllers.append(controller)

    def _on_event(self, event):
        etype, obj = event
        # owner gets re-queued when a child changes (controller-runtime Owns())
        self.enqueue(obj.kind, obj.metadata.namespace, obj.metadata.name)
        for ref in obj.metadata.owner_references:
            self.enqueue(ref["kind"], obj.metadata.namespace, ref["name"])
        # explicit cross-kind watches (reference Watches(...) wiring,
        # finetunejob_controller.go:162-206)
        for c in self.controllers:
            watches = getattr(c, "watches", None)
            if watches is None:
                continue
            for ns, name in watches(event) or []:
                self.enqueue(c.kind.kind, ns, name)

    def enqueue(self, kind: str, namespace: str, name: str, after: float = 0.0):
        kind = kind if isinstance(kind, str) else kind.kind
        if not any(c.kind.kind == kind for c in self.controllers):
            return
        with self._cv:
            self._seq += 1
            heapq.heappush(
                self._queue, (time.monotonic() + after, self._seq, kind, namespace, name)
            )
            self._cv.notify()

    # ----------------------------------------------------------- execution
    def _reconcile_one(self, kind: str, namespace: str, name: str):
        controller = next((c for c in self.controllers if c.kind.kind == kind), None)
        if controller is None:
            return
        obj = self.store.try_get(kind, name, namespace)
        if obj is None:
            return
        self.reconcile_counts[kind] = self.reconcile_counts.get(kind, 0) + 1
        try:
            result = controller.reconcile(self.store, obj)
        except Conflict:
            self.enqueue(kind, namespace, name, after=0.0)  # retry on fresh read
            return
        except BaseException as e:  # noqa: BLE001 - reconcilers must not kill the loop
            after, err = handle_err(e)
            if err is not None:
                self.errors.append((f"{kind}/{namespace}/{name}", err))
            if after is not None:
                self.enqueue(kind, namespace, name, after=after)
            return
        if result and result.requeue_after is not None:
            self.enqueue(kind, namespace, name, after=result.requeue_after)

    # test suites that shrink the poll intervals (conftest DTX_*_S envs) must
    # shrink the idle horizon below the smallest interval, or run_until_idle
    # would spin-reconcile poll-style waits until max_wall_s
    IDLE_HORIZON_S = float(os.environ.get("DTX_IDLE_HORIZON_S", "0.5"))

    def run_until_idle(self, max_wall_s: float = 30.0,
                       treat_delayed_as_idle: float = None):
        """Process the queue synchronously until it only holds far-future
        requeues (poll-style waits) or is empty. Virtual time: delayed items
        under `treat_delayed_as_idle`s run immediately."""
        if treat_delayed_as_idle is None:
            treat_delayed_as_idle = self.IDLE_HORIZON_S
        deadline = time.monotonic() + max_wall_s
        while time.monotonic() < deadline:
            with self._cv:
                if not self._queue:
                    return True
                t, seq, kind, ns, name = self._queue[0]
                now = time.monotonic()
                if t > now + treat_delayed_as_idle:
                    return True  # only long-delay requeues remain
                heapq.heappop(self._queue)
            if t > time.monotonic():
                time.sleep(max(t - time.monotonic(), 0))
            self._reconcile_one(kind, ns, name)
        return False

    def drain_scheduled(self, horizon_s: float = 60.0, max_wall_s: float = 30.0):
        """Testing helper: fast-forward requeues due within `horizon_s` by
        collapsing their delay, then run until idle."""
        with self._cv:
            self._queue = [
                (min(t, time.monotonic()), s, k, ns, n)
                for (t, s, k, ns, n) in self._queue
                if t <= time.monotonic() + horizon_s
            ]
            heapq.heapify(self._queue)
        return self.run_until_idle(max_wall_s=max_wall_s)

    def _loop(self):
        while not self._stop.is_set():
            with self._cv:
                while not self._queue and not self._stop.is_set():
                    self._cv.wait(timeout=0.5)
                if self._stop.is_set():
                    return
                t, seq, kind, ns, name = self._queue[0]
                now = time.monotonic()
                if t > now:
                    self._cv.wait(timeout=min(t - now, 0.5))
                    continue
                heapq.heappop(self._queue)
            self._reconcile_one(kind, ns, name)

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread:
            self._thread.join(timeout=5)

    def sync_all(self):
        """Enqueue every existing object of every registered kind (startup
        resync, like controller-runtime's initial list)."""
        for c in self.controllers:
            for obj in self.store.list(c.kind, namespace=None):
                self.enqueue(c.kind.kind, obj.metadata.namespace, obj.metadata.name)
