"""Workload model + record/replay trace format.

``WorkloadModel.generate()`` produces a deterministic (seeded) list of
request events shaped like production chat traffic rather than a uniform
probe stream:

  heavy tails    — prompt/output lengths are Pareto-distributed (a few
                   huge prompts among many small ones: the head-of-line
                   shape chunked prefill exists for), capped so a trace
                   can't exceed the fleet's context budget.
  multi-turn     — requests belong to sessions; every turn of a session
                   repeats the session's system prompt and grows the
                   history, so prefix caches and session-affinity routing
                   see realistic reuse.
  adapter churn  — the ``model`` field cycles a Zipf-weighted adapter
                   population (hot tenants dominate, a long tail keeps the
                   pool contested), with every k-th request on base.
  arrivals       — exponential inter-arrival times at a target RPS.

The trace is JSONL: a header line
``{"kind": "dtx-load-trace", "version": 1, "meta": {...}}`` then one event
per line, each ``{"t": seconds-from-start, "session", "turn", "messages",
"max_tokens", "model"}``. Traces recorded once replay bit-identically —
the chaos schedule, not the traffic, is the experiment variable.
"""

from __future__ import annotations

import json
import random
from typing import Dict, List, Optional, TextIO, Tuple

TRACE_KIND = "dtx-load-trace"
TRACE_VERSION = 1

_WORDS = ("the quick brown fox jumps over the lazy dog while tokens "
          "stream past attention heads and caches fill with state").split()


def _text(rng: random.Random, n_chars: int) -> str:
    """Deterministic filler text of roughly n_chars (word-granular)."""
    out: List[str] = []
    size = 0
    while size < n_chars:
        w = _WORDS[rng.randrange(len(_WORDS))]
        out.append(w)
        size += len(w) + 1
    return " ".join(out)


def _pareto_int(rng: random.Random, base: float, alpha: float,
                cap: int) -> int:
    """Heavy-tail length draw: base × Pareto(alpha), capped. alpha ~1.5
    gives the long-tail mass production prompt mixes show."""
    return max(1, min(cap, int(base * rng.paretovariate(alpha))))


class WorkloadModel:
    """Seeded generator of production-shaped request events."""

    def __init__(self, requests: int = 50, sessions: int = 8,
                 rps: float = 20.0, seed: int = 0,
                 adapters: Optional[List[str]] = None,
                 base_every: int = 4,
                 prompt_chars: int = 80, prompt_cap_chars: int = 2000,
                 output_tokens: int = 16, output_cap_tokens: int = 96,
                 tail_alpha: float = 1.5, temperature: float = 0.8,
                 tenants: Optional[Dict[str, dict]] = None):
        if requests < 1 or sessions < 1 or rps <= 0:
            raise ValueError("requests/sessions must be >= 1, rps > 0")
        self.requests = requests
        self.sessions = sessions
        self.rps = rps
        self.seed = seed
        self.adapters = list(adapters or [])
        self.base_every = max(0, base_every)
        # multi-tenant mix: tenant -> {"adapters": [...], "weight": w}.
        # Each event draws a tenant by arrival weight, then an adapter
        # Zipf-weighted WITHIN that tenant's set, and carries a "tenant"
        # tag the replay clients forward as X-DTX-Tenant. Empty = the
        # untagged single-tenant mix, bit-identical to older traces.
        self.tenants = {str(n): dict(e) for n, e in (tenants or {}).items()}
        self.prompt_chars = prompt_chars
        self.prompt_cap_chars = prompt_cap_chars
        self.output_tokens = output_tokens
        self.output_cap_tokens = output_cap_tokens
        self.tail_alpha = tail_alpha
        # sampled decode by default: greedy traffic on tiny models EOSes
        # instantly, which starves the TTFT/TPOT signal a replay exists
        # to measure
        self.temperature = temperature

    def _pick_adapter(self, rng: random.Random, i: int,
                      adapters: Optional[List[str]] = None) -> str:
        pool = self.adapters if adapters is None else adapters
        if not pool:
            return ""
        if self.base_every and i % self.base_every == 0:
            return ""  # every k-th request exercises the base model
        # Zipf-ish: weight 1/rank — hot tenants dominate, the tail churns
        weights = [1.0 / (r + 1) for r in range(len(pool))]
        return rng.choices(pool, weights=weights, k=1)[0]

    def _pick_tenant(self, rng: random.Random) -> Tuple[str, Optional[List[str]]]:
        if not self.tenants:
            return "", None
        names = sorted(self.tenants)
        weights = [max(0.0, float(self.tenants[n].get("weight", 1.0)))
                   for n in names]
        name = rng.choices(names, weights=weights, k=1)[0]
        return name, list(self.tenants[name].get("adapters") or [])

    def generate(self) -> List[dict]:
        rng = random.Random(self.seed)
        # per-session state: system prompt (the reused prefix) + history
        systems = [
            f"You are assistant s{j}. " + _text(rng, self.prompt_chars)
            for j in range(self.sessions)
        ]
        histories: List[List[dict]] = [[] for _ in range(self.sessions)]
        turns = [0] * self.sessions
        events: List[dict] = []
        t = 0.0
        for i in range(self.requests):
            t += rng.expovariate(self.rps)
            s = rng.randrange(self.sessions)
            user = _text(rng, _pareto_int(
                rng, self.prompt_chars, self.tail_alpha,
                self.prompt_cap_chars))
            messages = ([{"role": "system", "content": systems[s]}]
                        + histories[s]
                        + [{"role": "user", "content": user}])
            max_tokens = _pareto_int(rng, self.output_tokens,
                                     self.tail_alpha,
                                     self.output_cap_tokens)
            tenant, tenant_adapters = self._pick_tenant(rng)
            event = {
                "t": round(t, 4),
                "session": f"s{s}",
                "turn": turns[s],
                "messages": messages,
                "max_tokens": max_tokens,
                "temperature": self.temperature,
                "model": self._pick_adapter(rng, i, tenant_adapters),
            }
            if tenant:
                event["tenant"] = tenant
            events.append(event)
            turns[s] += 1
            # the assistant's (synthetic) reply joins the history, so the
            # next turn replays a strictly-grown prefix; histories are
            # bounded so late turns can't blow the context window
            histories[s].append({"role": "user", "content": user})
            histories[s].append({
                "role": "assistant",
                "content": _text(rng, max_tokens * 4)})
            if len(histories[s]) > 6:
                histories[s] = histories[s][-6:]
        return events

    def meta(self) -> dict:
        doc = {
            "requests": self.requests, "sessions": self.sessions,
            "rps": self.rps, "seed": self.seed,
            "adapters": list(self.adapters),
            "tail_alpha": self.tail_alpha,
        }
        if self.tenants:
            doc["tenants"] = {n: dict(e) for n, e in self.tenants.items()}
        return doc


# ------------------------------------------------- gateway trace-log import

def from_trace_log(path: str, prompt_chars: int = 80,
                   chars_per_token: float = 4.0,
                   temperature: float = 0.8) -> Tuple[dict, List[dict]]:
    """Convert a gateway ``--trace_log`` JSONL (one completed span per
    line, obs/trace.py format) into replay events — so replays are driven
    by REAL recorded traffic instead of the synthetic workload model.

    What the spans carry is what the replay gets: true arrival offsets
    (``start_ms``), the adapter mix (``attrs.adapter``), per-request
    output sizes (``attrs.chars``, streamed requests), and the trace id as
    the session key (affinity-stable across a multi-turn id). Spans do NOT
    record message content, so prompts are synthetic filler of
    ``prompt_chars`` — shape-true timing/mix, not content replay.

    Only gateway ROOT spans (``gateway.request`` / ``gateway.stream``)
    become events; replica/engine halves of the same trace are skipped.
    """
    rng = random.Random(0)
    rows: List[dict] = []
    with open(path, encoding="utf-8") as f:
        for n, line in enumerate(f, 1):
            if not line.strip():
                continue
            try:
                sp = json.loads(line)
            except json.JSONDecodeError:
                raise ValueError(f"{path}: line {n} is not JSON — is this "
                                 "a gateway --trace_log file?")
            if not isinstance(sp, dict):
                continue
            if sp.get("name") in ("gateway.request", "gateway.stream"):
                rows.append(sp)
    if not rows:
        raise ValueError(
            f"{path}: no gateway request spans found (expect "
            "gateway.request/gateway.stream lines from --trace_log)")
    rows.sort(key=lambda s: s.get("start_ms") or 0.0)
    t0 = rows[0].get("start_ms") or 0.0
    events: List[dict] = []
    for i, sp in enumerate(rows):
        attrs = sp.get("attrs") or {}
        chars = attrs.get("chars")
        if isinstance(chars, (int, float)) and chars > 0:
            max_tokens = max(1, int(round(chars / chars_per_token)))
        else:
            max_tokens = 16  # non-streamed spans don't record output size
        events.append({
            "t": round(max(0.0, ((sp.get("start_ms") or t0) - t0) / 1e3), 4),
            "session": sp.get("trace_id") or f"t{i}",
            "turn": 0,
            "messages": [{"role": "user",
                          "content": _text(rng, prompt_chars)}],
            "max_tokens": max_tokens,
            "temperature": temperature,
            "model": attrs.get("adapter") or "",
        })
    meta = {"source": "trace_log", "path": path,
            "requests": len(events), "prompt_chars": prompt_chars,
            "chars_per_token": chars_per_token}
    return meta, events


# ----------------------------------------------------------------- trace io

def write_trace(path_or_fp, events: List[dict],
                meta: Optional[dict] = None) -> None:
    """One header line + one event per line (JSONL)."""
    def _write(fp: TextIO):
        fp.write(json.dumps({"kind": TRACE_KIND, "version": TRACE_VERSION,
                             "meta": meta or {}}) + "\n")
        for ev in events:
            fp.write(json.dumps(ev) + "\n")

    if hasattr(path_or_fp, "write"):
        _write(path_or_fp)
    else:
        with open(path_or_fp, "w", encoding="utf-8") as f:
            _write(f)


def read_trace(path_or_fp) -> Tuple[dict, List[dict]]:
    """→ (meta, events). Validates the header and each event's shape so a
    stale or foreign file fails loudly before any traffic fires."""
    def _read(fp: TextIO):
        header_line = fp.readline()
        try:
            header = json.loads(header_line)
        except json.JSONDecodeError:
            raise ValueError("not a dtx-load-trace: bad header line")
        if header.get("kind") != TRACE_KIND:
            raise ValueError(
                f"not a dtx-load-trace (kind={header.get('kind')!r})")
        if header.get("version") != TRACE_VERSION:
            raise ValueError(
                f"unsupported trace version {header.get('version')!r}")
        events = []
        for n, line in enumerate(fp, 2):
            if not line.strip():
                continue
            ev = json.loads(line)
            if not isinstance(ev.get("t"), (int, float)) \
                    or not isinstance(ev.get("messages"), list) \
                    or not ev["messages"]:
                raise ValueError(f"line {n}: bad event {ev!r}")
            events.append(ev)
        events.sort(key=lambda e: e["t"])
        return header.get("meta") or {}, events

    if hasattr(path_or_fp, "read"):
        return _read(path_or_fp)
    with open(path_or_fp, encoding="utf-8") as f:
        return _read(f)


def summarize(events: List[dict]) -> Dict[str, float]:
    """Shape summary for reports/logs (counts, tail sizes, adapter mix)."""
    if not events:
        return {"requests": 0}
    chars = sorted(sum(len(m.get("content", "")) for m in e["messages"])
                   for e in events)
    adapters = {e.get("model") or "" for e in events}
    multi = sum(1 for e in events if e.get("turn", 0) > 0)
    out = {
        "requests": len(events),
        "duration_s": round(events[-1]["t"], 3),
        "prompt_chars_p50": chars[len(chars) // 2],
        "prompt_chars_max": chars[-1],
        "multi_turn": multi,
        "adapters": len(adapters - {""}),
    }
    tenants = {e.get("tenant") or "" for e in events} - {""}
    if tenants:
        out["tenants"] = len(tenants)
    return out
