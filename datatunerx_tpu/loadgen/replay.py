"""Replay a workload trace at a gateway and judge the run with SLOs.

The runner fires trace events at their recorded offsets (speed-scalable),
streams every response to measure client-side TTFT, records outcomes into
a private obs Registry (``dtx_loadgen_requests_total{code}``,
``dtx_loadgen_ttft_ms`` / ``dtx_loadgen_latency_ms`` histograms with
trace-id exemplars), and ends with an SLO epilogue: the same
``obs/slo.py`` evaluator the gateway's ``GET /debug/slo`` serves judges
the replay's own registry, and the process exits nonzero NAMING any
violated objective. A chaos injector (loadgen/chaos.py) runs alongside,
so the verdict is "the SLOs held *through* the faults", not "on a quiet
fleet".

Two clients:

  HTTPClient   — a real gateway URL (SSE streaming, trace-id header).
  LocalClient  — an in-process ``Gateway`` object: the test/CI/bench path
                 (``--selftest``, DTX_BENCH_REPLAY), where chaos can also
                 reach surfaces that have no wire form (replica kill,
                 slice-pool shrink) via injected actions.

CLI (``dtx replay`` / ``python -m datatunerx_tpu.loadgen.replay``):

  dtx replay --url http://gw:8000 --requests 200 --rps 50 \\
      --chaos chaos.json --slo slos.json --report_json out.json
  dtx replay --record trace.jsonl --requests 500   # generate only
  dtx replay --url ... --trace trace.jsonl         # replay a recording
  dtx replay --selftest                            # 2-replica in-process
                                                   # fleet + drain chaos
  dtx replay --selftest --tighten loadgen-fast-ttft=0.999@0.001
                                                   # prove detection: the
                                                   # tightened objective
                                                   # must exit nonzero
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import List, Optional

from datatunerx_tpu.obs.metrics import (
    MS_BUCKETS,
    Registry,
    sample_percentile,
)
from datatunerx_tpu.obs.slo import (
    SLO,
    SLOEvaluator,
    default_slos,
    load_slos,
    violations,
)
from datatunerx_tpu.loadgen.chaos import ChaosInjector, load_chaos
from datatunerx_tpu.loadgen.workload import (
    WorkloadModel,
    read_trace,
    summarize,
    write_trace,
)


# ------------------------------------------------------------------- clients

class HTTPClient:
    """Streams POST /chat/completions against a gateway/serving URL."""

    def __init__(self, base_url: str, timeout: float = 120.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def send(self, event: dict, trace_id: str) -> dict:
        payload = {"messages": event["messages"],
                   "max_tokens": event.get("max_tokens", 32),
                   "temperature": event.get("temperature", 0.0),
                   "stream": True}
        if event.get("model"):
            payload["model"] = event["model"]
        headers = {"Content-Type": "application/json",
                   "X-DTX-Trace-Id": trace_id,
                   "X-DTX-Session-Id": event.get("session") or ""}
        if event.get("tenant"):
            headers["X-DTX-Tenant"] = event["tenant"]
        req = urllib.request.Request(
            self.base_url + "/chat/completions",
            data=json.dumps(payload).encode(),
            headers=headers,
            method="POST")
        t0 = time.perf_counter()
        ttft = None
        chars = 0
        code = 200
        error = None
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                for raw in r:
                    line = raw.decode("utf-8", "replace").strip()
                    if not line.startswith("data: "):
                        continue
                    data = line[len("data: "):]
                    if data == "[DONE]":
                        break
                    evt = json.loads(data)
                    if "error" in evt:
                        code, error = 500, str(evt["error"].get("message"))
                        break
                    delta = evt["choices"][0]["delta"].get("content")
                    if delta:
                        if ttft is None:
                            ttft = time.perf_counter() - t0
                        chars += len(delta)
        except urllib.error.HTTPError as e:
            code, error = e.code, str(e.reason)
        except Exception as e:  # noqa: BLE001 — a dead gateway IS the data
            code, error = 503, str(e)
        return {"code": code, "error": error, "chars": chars,
                "ttft_ms": None if ttft is None else ttft * 1e3,
                "latency_ms": (time.perf_counter() - t0) * 1e3}


class LocalClient:
    """Drives an in-process ``gateway.server.Gateway`` — same outcome
    classification the HTTP handler would produce, without sockets."""

    def __init__(self, gateway):
        self.gateway = gateway

    def send(self, event: dict, trace_id: str) -> dict:
        from datatunerx_tpu.gateway.admission import Overloaded
        from datatunerx_tpu.gateway.replica_pool import (
            NoReplicaAvailable,
            ReplicaError,
        )

        req = {"messages": event["messages"],
               "max_tokens": event.get("max_tokens", 32),
               "temperature": event.get("temperature", 0.0)}
        if event.get("model"):
            req["model"] = event["model"]
        t0 = time.perf_counter()
        ttft = None
        chars = 0
        code = 200
        error = None
        try:
            for delta in self.gateway.chat_stream(
                    req, trace_id=trace_id,
                    session_id=event.get("session"),
                    tenant=event.get("tenant") or ""):
                if ttft is None:
                    ttft = time.perf_counter() - t0
                chars += len(delta)
        except Overloaded as e:
            code, error = 429, str(e.reason)
        except ValueError as e:
            code, error = 400, str(e)
        except NoReplicaAvailable as e:
            code, error = 503, str(e)
        except ReplicaError as e:
            code, error = 502, str(e)
        except Exception as e:  # noqa: BLE001
            code, error = 500, str(e)
        return {"code": code, "error": error, "chars": chars,
                "ttft_ms": None if ttft is None else ttft * 1e3,
                "latency_ms": (time.perf_counter() - t0) * 1e3}


# -------------------------------------------------------------------- runner

class ReplayRunner:
    """Fires events at their trace offsets, bounded-concurrency, and
    aggregates outcomes into ``registry`` + a summary report."""

    def __init__(self, client, registry: Optional[Registry] = None,
                 max_inflight: int = 32):
        self.client = client
        self.registry = registry if registry is not None else Registry()
        self.max_inflight = max(1, max_inflight)
        self._requests = self.registry.counter(
            "dtx_loadgen_requests_total",
            "Replayed requests by terminal code as the client saw them.")
        self._ttft = self.registry.histogram(
            "dtx_loadgen_ttft_ms",
            "Client-observed time to first streamed delta.",
            buckets=MS_BUCKETS)
        self._latency = self.registry.histogram(
            "dtx_loadgen_latency_ms",
            "Client-observed end-to-end request latency.",
            buckets=MS_BUCKETS)
        self._lock = threading.Lock()
        self.results: List[dict] = []

    def _one(self, event: dict, sem: threading.Semaphore):
        trace_id = f"dtx-load-{uuid.uuid4().hex[:12]}"
        try:
            out = self.client.send(event, trace_id)
            out["trace_id"] = trace_id
            out["session"] = event.get("session")
            if event.get("tenant"):
                out["tenant"] = event["tenant"]
            self._requests.inc({"code": str(out["code"])})
            if out["ttft_ms"] is not None:
                self._ttft.observe(out["ttft_ms"], trace_id=trace_id)
            self._latency.observe(out["latency_ms"], trace_id=trace_id)
            with self._lock:
                self.results.append(out)
        finally:
            sem.release()

    def run(self, events: List[dict], speed: float = 1.0,
            chaos: Optional[ChaosInjector] = None,
            join_timeout_s: float = 600.0) -> dict:
        speed = max(speed, 1e-9)
        sem = threading.Semaphore(self.max_inflight)
        threads: List[threading.Thread] = []
        if chaos is not None:
            chaos.start(speed)
        t0 = time.monotonic()
        for ev in events:
            delay = ev["t"] / speed - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            sem.acquire()  # backpressure: at most max_inflight in the air
            th = threading.Thread(target=self._one, args=(ev, sem),
                                  daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=join_timeout_s)
        if chaos is not None:
            chaos.stop()
        duration = time.monotonic() - t0
        return self._report(duration, chaos)

    def _report(self, duration_s: float,
                chaos: Optional[ChaosInjector]) -> dict:
        with self._lock:
            results = list(self.results)
        ttfts = [r["ttft_ms"] for r in results if r["ttft_ms"] is not None]
        lats = [r["latency_ms"] for r in results]
        codes: dict = {}
        for r in results:
            codes[str(r["code"])] = codes.get(str(r["code"]), 0) + 1
        errors = sum(1 for r in results if r["code"] >= 500)
        rep = {
            "requests": len(results),
            "errors": errors,
            "codes": codes,
            "duration_s": round(duration_s, 3),
            "rps_achieved": round(len(results) / duration_s, 2)
            if duration_s > 0 else 0.0,
            "ttft_ms_p50": round(sample_percentile(ttfts, 0.5), 2),
            "ttft_ms_p95": round(sample_percentile(ttfts, 0.95), 2),
            "ttft_ms_p99": round(sample_percentile(ttfts, 0.99), 2),
            "latency_ms_p50": round(sample_percentile(lats, 0.5), 2),
            "latency_ms_p95": round(sample_percentile(lats, 0.95), 2),
            "latency_ms_p99": round(sample_percentile(lats, 0.99), 2),
        }
        by_tenant: dict = {}
        for r in results:
            if r.get("tenant"):
                by_tenant.setdefault(r["tenant"], []).append(r)
        if by_tenant:
            # per-tenant QoS breakdown — the isolation evidence: a pinned
            # tenant's tail must hold while a bulk tenant gets shed
            rep["tenants"] = {}
            for name in sorted(by_tenant):
                rs = by_tenant[name]
                tt = [r["ttft_ms"] for r in rs if r["ttft_ms"] is not None]
                lat = [r["latency_ms"] for r in rs
                       if r.get("latency_ms") is not None]
                rep["tenants"][name] = {
                    "requests": len(rs),
                    "ok": sum(1 for r in rs if r["code"] < 400),
                    "shed": sum(1 for r in rs if r["code"] == 429),
                    "errors": sum(1 for r in rs if r["code"] >= 500),
                    "ttft_ms_p95": round(sample_percentile(tt, 0.95), 2),
                    # empty completions (tiny models sampling EOS first)
                    # leave ttft None; latency is always measured, so
                    # consumers can fall back to it
                    "latency_ms_p95": round(sample_percentile(lat, 0.95), 2),
                }
        if chaos is not None:
            rep["chaos"] = chaos.report()
        return rep


# ------------------------------------------------------------- SLO epilogue

def slo_epilogue(evaluator: SLOEvaluator, since_t: float,
                 out=print) -> dict:
    """Judge the run and SAY SO: one line per objective, violations named.
    Returns {"pass": bool, "violations": [...], "verdicts": [...]} — the
    CLI exits 1 when ``pass`` is False."""
    verdicts = evaluator.verdicts(since_t=since_t)
    broken = violations(verdicts)
    for v in verdicts:
        if v["no_data"]:
            out(f"[slo] {v['name']}: no events — vacuously compliant")
            continue
        rel = ">=" if v["compliant"] else "<"
        out(f"[slo] {v['name']}: compliance {v['compliance']:.4f} {rel} "
            f"objective {v['objective']:g} over {v['total']} events "
            f"({'OK' if v['compliant'] else 'VIOLATED'})")
    for line in broken:
        out(f"[slo] {line}")
    out(f"[replay] SLO verdict: "
        + ("PASS" if not broken else f"FAIL ({len(broken)} violated)"))
    return {"pass": not broken, "violations": broken, "verdicts": verdicts}


# ----------------------------------------------------------- selftest fleet

class _FakeEngine:
    """A serving-engine stand-in for the self-test fleet: streams a few
    deltas with a small per-token delay, supports adapter names, an
    injectable mid-stream fault, AND the KV-migration surface
    (export_sessions / import_session / resume_stream with the real
    engines' duck-typed contract) — enough for routing, failover, drain
    handoff and adapter-evict chaos without loading a model."""

    def __init__(self, name: str, delay_s: float = 0.002,
                 adapters: Optional[List[str]] = None,
                 prefill_steps: int = 0):
        from datatunerx_tpu.obs.trace import TraceStore

        self.name = name
        self.delay_s = delay_s
        # chunked-prefill stand-in: each session burns this many silent
        # steps (no deltas) before its first token — a drain that lands
        # inside them exercises the mid-prefill export/import tail path
        self.prefill_steps = max(0, int(prefill_steps))
        self.mid_prefill_imports = 0
        self.fail = False
        self.adapter_ids = {"": 0}
        for i, a in enumerate(adapters or []):
            self.adapter_ids[a] = i + 1
        self.resident_adapters = {a for a in self.adapter_ids if a}
        self.slots = 4
        self._slot_req = [None] * 4
        # a real (tiny) trace store so InProcessReplica forwards trace ids
        # — the handoff buffer is keyed by them
        self.trace_store = TraceStore(capacity=64)
        self._lock = threading.Lock()
        self._live: dict = {}

    def unload_adapter(self, name: str) -> bool:
        present = name in self.resident_adapters
        self.resident_adapters.discard(name)
        return present

    def chat_stream(self, messages, max_new_tokens: int = 16,
                    trace_id: str = "", **kw):
        if self.fail:
            raise RuntimeError(f"{self.name}: injected fault")
        n = max(1, min(int(max_new_tokens), 8))
        sess = {"trace_id": trace_id, "total": n, "emitted": 0,
                "migrate": False, "adapter": kw.get("adapter", ""),
                "prefill_done": 0, "prefill_total": self.prefill_steps}
        if trace_id:
            with self._lock:
                self._live[trace_id] = sess
        try:
            while sess["prefill_done"] < sess["prefill_total"]:
                time.sleep(self.delay_s)
                if sess["migrate"]:
                    raise RuntimeError(
                        f"session migrated off {self.name}")
                sess["prefill_done"] += 1
            for i in range(n):
                time.sleep(self.delay_s)
                if self.fail and i > 0:
                    raise RuntimeError(f"{self.name}: killed mid-stream")
                if sess["migrate"]:
                    # same marker literal the real engine dies with
                    # (gateway/replica_pool.MIGRATED_MARKER)
                    raise RuntimeError(
                        f"session migrated off {self.name}")
                sess["emitted"] += 1
                yield "tok "
        finally:
            if trace_id:
                with self._lock:
                    self._live.pop(trace_id, None)

    def chat(self, messages, **kw):
        return "".join(self.chat_stream(messages, **kw))

    # ------------------------------------------ KV migration (fake twin)
    def export_sessions(self, slots=None, wire_quant=None,
                        include_prefill: bool = False) -> dict:
        with self._lock:
            live = list(self._live.values())
        sessions = []
        skipped = []
        for sess in live:
            mid_prefill = sess["prefill_done"] < sess["prefill_total"]
            if mid_prefill and not include_prefill:
                # real-engine contract: mid-prefill sessions only ship
                # when the caller asks for tails (the drain path)
                skipped.append(sess["trace_id"])
                continue
            sess["migrate"] = True  # the stream dies with the marker
            sessions.append({"fake": True, "trace_id": sess["trace_id"],
                             "emitted": int(sess["emitted"]),
                             "total": int(sess["total"]),
                             "adapter": sess["adapter"],
                             "prefill_done": int(sess["prefill_done"]),
                             "prefill_total": int(sess["prefill_total"])})
        return {"sessions": sessions, "skipped": skipped}

    def import_session(self, payload: dict) -> dict:
        if not payload.get("fake"):
            raise ValueError("foreign session payload")
        adapter = payload.get("adapter") or ""
        if adapter and adapter not in self.adapter_ids:
            raise ValueError(f"unknown adapter {adapter!r}")
        emitted = int(payload["emitted"])
        pf_done = int(payload.get("prefill_done") or 0)
        pf_total = int(payload.get("prefill_total") or 0)
        if pf_done < pf_total:
            self.mid_prefill_imports += 1
        handle = {"remaining": max(0, int(payload["total"]) - emitted),
                  # resume the prompt where the source stopped — the done
                  # part is NOT redone (the zero-re-prefill contract)
                  "prefill_remaining": max(0, pf_total - pf_done)}
        return {"session": payload.get("trace_id"), "tokens": emitted,
                "text_so_far": "tok " * emitted, "_request": handle}

    def resume_stream(self, handle: dict):
        for _ in range(handle.get("prefill_remaining", 0)):
            time.sleep(self.delay_s)
            if self.fail:
                raise RuntimeError(f"{self.name}: killed mid-resume")
        for _ in range(handle["remaining"]):
            time.sleep(self.delay_s)
            if self.fail:
                raise RuntimeError(f"{self.name}: killed mid-resume")
            yield "tok "

    def healthy(self) -> bool:
        return not self.fail


#: the two-tier tenant selftest: a pinned tenant with a TTFT objective
#: and a bulk tenant whose KV-block quota is deliberately tight, so the
#: bulk flood sheds at admission instead of queueing in front of the
#: pinned tenant's traffic.
SELFTEST_TENANTS = {
    "plat": {"tier": "pinned", "adapters": ["tenant-a"], "share": 8.0,
             "ttft_p95_ms": 500.0},
    "batch": {"tier": "bulk", "adapters": ["tenant-b"], "share": 1.0,
              "kv_block_quota": 8},
}

#: the matching workload mix: the bulk tenant arrives 4x as often — the
#: overload is the experiment, the pinned tenant's p95 is the verdict
SELFTEST_TENANT_MIX = {
    "plat": {"adapters": ["tenant-a"], "weight": 1.0},
    "batch": {"adapters": ["tenant-b"], "weight": 4.0},
}


def build_selftest_fleet(adapters: Optional[List[str]] = None,
                         session_handoff: bool = True,
                         delay_s: float = 0.002,
                         roles: Optional[List[str]] = None,
                         prefill_steps: int = 0,
                         tenants: Optional[dict] = None):
    """2 in-process fake replicas behind a real Gateway — the CI smoke
    fleet. Returns (gateway, engines). ``roles`` assigns disaggregation
    roles by replica index and turns the fleet handoff plane on, so a
    drain ships mid-prefill tails instead of skipping them. ``tenants``
    turns the multi-tenant QoS plane on (directory config, tenancy/)."""
    from datatunerx_tpu.gateway.replica_pool import (
        InProcessReplica,
        ReplicaPool,
    )
    from datatunerx_tpu.gateway.server import Gateway

    adapters = adapters if adapters is not None else ["tenant-a", "tenant-b"]
    roles = roles or []
    engines = [_FakeEngine(f"replica-{i}", delay_s=delay_s,
                           adapters=adapters, prefill_steps=prefill_steps)
               for i in range(2)]
    pool = ReplicaPool([
        InProcessReplica(e.name, e,
                         role=roles[i] if i < len(roles) else "mixed")
        for i, e in enumerate(engines)])
    gw = Gateway(pool, model_name="selftest",
                 session_handoff=session_handoff,
                 fleet_handoff=bool(roles),
                 tenants=tenants)
    return gw, engines


def drain_when_busy(gw, name: str, wait_s: float = 3.0) -> dict:
    """Chaos action: wait (bounded) until the replica actually holds
    in-flight work, then drain it — a time-offset drain that lands on an
    idle replica proves nothing about mid-stream handoff."""
    r = gw.pool.get(name)
    deadline = time.monotonic() + wait_s
    while (r is not None and r.inflight == 0
           and time.monotonic() < deadline):
        time.sleep(0.002)
    busy = r.inflight if r is not None else None
    return {"drained": gw.drain(name), "inflight_at_drain": busy,
            "handoff": gw.last_handoff}


def selftest_chaos(gw, engines, duration_s: float,
                   drain_replica: str = "replica-1") -> ChaosInjector:
    """The default self-test schedule: one /admin/drain mid-run, fired
    when the replica is mid-stream (the drained replica stops taking
    traffic; its sessions hand off and availability must hold on the
    survivor)."""
    ops = [{"t": round(duration_s * 0.5, 3), "op": "drain",
            "replica": drain_replica}]
    actions = {
        "drain": lambda op: drain_when_busy(gw, op["replica"]),
        "kill": lambda op: _kill_engine(engines, op["replica"]),
        "adapter_unload": lambda op: {
            "unloaded": [e.unload_adapter(op["adapter"])
                         for e in engines
                         if e.name == op.get("replica", e.name)]},
    }
    return ChaosInjector(ops, actions)


def _kill_engine(engines, name: str) -> dict:
    for e in engines:
        if e.name == name:
            e.fail = True
            return {"killed": name}
    raise ValueError(f"no engine {name!r}")


# ------------------------------------------------------------------ tighten

def apply_tighten(slos: List[SLO], specs: List[str]) -> List[SLO]:
    """``--tighten NAME=OBJECTIVE[@THRESHOLD]`` overrides — CI's way of
    proving the epilogue DETECTS a breach without a second config file."""
    out = list(slos)
    for spec in specs:
        name, sep, rest = spec.partition("=")
        if not sep:
            raise ValueError(f"--tighten wants NAME=OBJECTIVE, got {spec!r}")
        obj_s, _, thr_s = rest.partition("@")
        for i, slo in enumerate(out):
            if slo.name != name:
                continue
            sli = dict(slo.sli)
            if thr_s:
                if sli.get("kind") != "latency":
                    raise ValueError(
                        f"--tighten {name}: @threshold only applies to "
                        "latency SLIs")
                sli["threshold"] = float(thr_s)
            # back through from_dict, not dataclasses.replace: the
            # override must pass the same validation a config file would
            # (objective=1.0 leaves no budget to divide by — reject it
            # with a message, not a ZeroDivisionError mid-epilogue)
            try:
                objective = float(obj_s)
            except ValueError:
                raise ValueError(
                    f"--tighten {name}: objective {obj_s!r} is not a "
                    "number")
            out[i] = SLO.from_dict({
                "name": slo.name, "objective": objective, "sli": sli,
                "windows_s": list(slo.windows_s),
                "description": slo.description})
            break
        else:
            raise ValueError(
                f"--tighten {name!r}: no such SLO "
                f"(have {[s.name for s in out]})")
    return out


# ---------------------------------------------------------------------- CLI

def _san_setup():
    """Install the runtime sanitizers when DTX_SAN asks for them: the
    chaos harness is exactly the kind of concurrency-heavy path whose
    lock orders / thread lifetimes / recompiles the plane exists to
    watch. Returns (classes, live-thread snapshot) — () when off."""
    from datatunerx_tpu.analysis.sanitizers.runtime import install_from_env

    classes = install_from_env()
    return classes, set(threading.enumerate())


def _san_epilogue(classes, before, rc: int) -> int:
    """End-of-replay sanitizer sweep: lock-order cycles, module compile
    budgets, and any repo-spawned thread still alive after the fleet
    closed. New findings (vs the empty baseline) fail the run like an
    SLO breach does."""
    if not classes:
        return rc
    from datatunerx_tpu.analysis.sanitizers import report as _report
    from datatunerx_tpu.analysis.sanitizers.runtime import COLLECTOR, finalize
    from datatunerx_tpu.analysis.sanitizers.threads import THREAD_SANITIZER

    finalize(COLLECTOR)
    if "thread" in classes and THREAD_SANITIZER.installed:
        THREAD_SANITIZER.audit(before, COLLECTOR, testid="dtx replay")
    findings, suppressed = COLLECTOR.snapshot()
    evaluation = _report.evaluate(
        findings, suppressed,
        baseline_path=os.environ.get("DTX_SAN_BASELINE") or None,
        no_baseline=os.environ.get("DTX_SAN_NO_BASELINE") == "1")
    counters = None
    if "compile" in classes:
        from datatunerx_tpu.analysis.sanitizers.compile import COMPILE_SANITIZER

        counters = COMPILE_SANITIZER.counts()
    print("[replay] " + _report.render_text(
        evaluation, counters).replace("\n", "\n[replay] "))
    report_path = os.environ.get("DTX_SAN_REPORT")
    if report_path:
        _report.write_raw(report_path, findings, suppressed,
                          counters=counters, classes=classes)
    if evaluation["failed"]:
        print("[replay] sanitizer assertion FAILED: new dtxsan findings")
        return 1
    return rc


def main(argv=None) -> int:
    san_classes, san_before = _san_setup()
    rc = _replay_main(argv)
    return _san_epilogue(san_classes, san_before, rc)


def _replay_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="dtx replay",
        description="trace-driven load replay + chaos harness with an SLO "
                    "epilogue (exits 1 naming any violated objective)")
    p.add_argument("--url", default="",
                   help="gateway/serving base URL to replay against")
    p.add_argument("--selftest", action="store_true",
                   help="replay against a 2-replica in-process fake fleet "
                        "with one injected /admin/drain (the CI smoke)")
    p.add_argument("--trace", default="",
                   help="replay this recorded JSONL trace instead of "
                        "generating traffic")
    p.add_argument("--from_trace_log", default="",
                   help="convert a gateway --trace_log JSONL (completed "
                        "request spans) into the replay workload: real "
                        "arrival times, adapter mix and output sizes, "
                        "synthetic prompt text (spans don't record "
                        "message content); combine with --record to save "
                        "the converted dtx-load-trace")
    p.add_argument("--record", default="",
                   help="write the generated trace here (with no --url/"
                        "--selftest: generate-and-exit)")
    p.add_argument("--requests", type=int, default=40)
    p.add_argument("--sessions", type=int, default=6)
    p.add_argument("--rps", type=float, default=25.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--adapters", default="",
                   help="comma-separated adapter names the model field "
                        "churns through (selftest default: "
                        "tenant-a,tenant-b)")
    p.add_argument("--speed", type=float, default=1.0,
                   help="time-scale: 2.0 replays a trace twice as fast")
    p.add_argument("--max_inflight", type=int, default=32)
    p.add_argument("--chaos", default="",
                   help="chaos schedule: JSON file or inline JSON "
                        "(loadgen/chaos.py op format)")
    p.add_argument("--slo", default="",
                   help="SLO specs: JSON file or inline JSON (obs/slo.py "
                        "format); default: the loadgen availability + "
                        "TTFT objectives")
    p.add_argument("--tighten", action="append", default=[],
                   metavar="NAME=OBJECTIVE[@THRESHOLD]",
                   help="override an SLO's objective (and latency "
                        "threshold) — prove the epilogue detects a breach")
    p.add_argument("--handoff", choices=["on", "off"], default="on",
                   help="selftest fleet: drain hands in-flight sessions "
                        "to the surviving replica (on, default) or drops "
                        "them on today's cold re-prefill path (off)")
    p.add_argument("--expect_handoff", action="store_true",
                   help="fail (exit 1) unless the run handed off at least "
                        "one session with zero cold fallbacks and zero "
                        "5xx — the drain-mid-stream CI assertion")
    p.add_argument("--selftest_delay", type=float, default=0.002,
                   help="selftest per-token delay (raise it so a "
                        "mid-stream drain reliably catches sessions)")
    p.add_argument("--roles", default="",
                   help="selftest fleet: comma-separated disaggregation "
                        "roles by replica index (e.g. 'prefill,decode') — "
                        "turns the fleet handoff plane on and points the "
                        "default drain chaos at the first prefill replica")
    p.add_argument("--tenants", choices=["on", "off"], default="off",
                   help="selftest: turn the multi-tenant QoS plane on — a "
                        "pinned tenant (plat, TTFT objective) and a bulk "
                        "tenant (batch, tight KV-block quota) share the "
                        "fleet, with the bulk tenant arriving 4x as often")
    p.add_argument("--expect_tenant_qos", action="store_true",
                   help="fail (exit 1) unless the pinned tenant's ttft p95 "
                        "held under its objective with zero sheds/5xx "
                        "while the bulk overload was shed at admission — "
                        "the multi-tenant isolation CI assertion")
    p.add_argument("--selftest_prefill", type=int, default=0,
                   help="selftest: silent prefill steps per session before "
                        "the first token; with --roles + --expect_handoff "
                        "the drain must catch and re-home at least one "
                        "session mid-prefill with its prompt work kept")
    p.add_argument("--report_json", default="",
                   help="write the full report (results + chaos log + SLO "
                        "verdicts) to this file")
    args = p.parse_args(argv)

    adapters = [a.strip() for a in args.adapters.split(",") if a.strip()]
    if args.from_trace_log:
        from datatunerx_tpu.loadgen.workload import from_trace_log

        meta, events = from_trace_log(args.from_trace_log)
        print(f"[replay] converted {args.from_trace_log}: "
              f"{summarize(events)}")
    elif args.trace:
        meta, events = read_trace(args.trace)
        print(f"[replay] trace {args.trace}: {summarize(events)}")
    else:
        model = WorkloadModel(
            requests=args.requests, sessions=args.sessions, rps=args.rps,
            seed=args.seed,
            adapters=adapters or (["tenant-a", "tenant-b"]
                                  if args.selftest else []),
            tenants=SELFTEST_TENANT_MIX if args.tenants == "on" else None)
        events = model.generate()
        meta = model.meta()
        print(f"[replay] generated workload: {summarize(events)}")
    if args.record:
        write_trace(args.record, events, meta)
        print(f"[replay] trace recorded to {args.record}")
        if not args.url and not args.selftest:
            return 0

    if not args.url and not args.selftest:
        p.error("need --url, --selftest, or --record")

    slos = load_slos(args.slo) if args.slo else default_slos("loadgen")
    try:
        slos = apply_tighten(slos, args.tighten)
    except ValueError as e:
        p.error(str(e))

    gw = engines = None
    # chaos op offsets live in TRACE time (the injector applies --speed
    # itself, like the traffic loop does)
    trace_duration = events[-1]["t"] if events else 0.0
    try:
        if args.selftest:
            roles = [r.strip() for r in args.roles.split(",") if r.strip()]
            for r in roles:
                if r not in ("prefill", "decode", "mixed"):
                    p.error(f"--roles: {r!r} is not prefill/decode/mixed")
            gw, engines = build_selftest_fleet(
                adapters or None, session_handoff=args.handoff == "on",
                delay_s=args.selftest_delay, roles=roles or None,
                prefill_steps=args.selftest_prefill,
                tenants=SELFTEST_TENANTS if args.tenants == "on" else None)
            client = LocalClient(gw)
            # with roles on, the interesting drain is the prefill
            # specialist — caught mid-prompt, its tail must ship
            drain_target = "replica-1"
            if roles and "prefill" in roles:
                drain_target = f"replica-{roles.index('prefill')}"
            default = selftest_chaos(gw, engines, trace_duration,
                                     drain_replica=drain_target)
            chaos = (ChaosInjector(load_chaos(args.chaos), default.actions)
                     if args.chaos else default)
        else:
            client = HTTPClient(args.url)
            from datatunerx_tpu.loadgen.chaos import http_actions

            chaos = (ChaosInjector(load_chaos(args.chaos),
                                   http_actions(args.url))
                     if args.chaos else None)

        runner = ReplayRunner(client, max_inflight=args.max_inflight)
        evaluator = SLOEvaluator(runner.registry, slos)
        t_start = time.monotonic()
        report = runner.run(events, speed=args.speed, chaos=chaos)
        print(f"[replay] {report['requests']} requests in "
              f"{report['duration_s']}s ({report['rps_achieved']} rps) — "
              f"errors={report['errors']} codes={report['codes']}")
        print(f"[replay] ttft ms p50={report['ttft_ms_p50']} "
              f"p95={report['ttft_ms_p95']} p99={report['ttft_ms_p99']} · "
              f"latency ms p50={report['latency_ms_p50']} "
              f"p95={report['latency_ms_p95']} p99={report['latency_ms_p99']}")
        for entry in report.get("chaos") or []:
            print(f"[chaos] t={entry['t']}s {entry['op']} "
                  f"{entry['args']} ok={entry['ok']} — {entry['detail']}")
        if gw is not None:
            report["handoff"] = gw.handoff_stats()
            report["handoff_enabled"] = gw.session_handoff
            print(f"[replay] session handoff "
                  f"({'on' if gw.session_handoff else 'off'}): "
                  f"{report['handoff'] or 'no sessions moved'}")
        for name, st in sorted((report.get("tenants") or {}).items()):
            print(f"[replay] tenant {name}: {st['requests']} requests "
                  f"ok={st['ok']} shed={st['shed']} errors={st['errors']} "
                  f"ttft p95={st['ttft_ms_p95']}ms")
        verdict = slo_epilogue(evaluator, since_t=t_start - 1.0)
        report["slo"] = verdict
        report["workload"] = meta
        rc = 0 if verdict["pass"] else 1
        if args.expect_handoff:
            problems = []
            hs = report.get("handoff") or {}
            if hs.get("imported", 0) < 1:
                problems.append("no session was handed off")
            if hs.get("cold", 0):
                problems.append(f"{hs['cold']} session(s) fell back cold")
            dropped = sum(n for c, n in report["codes"].items()
                          if int(c) >= 500)
            if dropped:
                problems.append(f"{dropped} request(s) dropped (5xx)")
            if engines is not None and args.selftest_prefill > 0:
                mid = sum(e.mid_prefill_imports for e in engines)
                report["mid_prefill_imports"] = mid
                if mid < 1:
                    problems.append(
                        "no session was re-homed mid-prefill (the drain "
                        "missed the prompt phase — raise "
                        "--selftest_prefill or --selftest_delay)")
                else:
                    print(f"[replay] {mid} session(s) re-homed "
                          "mid-prefill with prompt work kept")
            for p_ in problems:
                print(f"[replay] handoff assertion FAILED: {p_}")
            if problems:
                rc = 1
            else:
                print("[replay] handoff assertion PASSED: sessions moved, "
                      "zero cold fallbacks, zero drops")
        if args.expect_tenant_qos:
            problems = []
            ts = report.get("tenants") or {}
            plat, batch = ts.get("plat") or {}, ts.get("batch") or {}
            if not plat.get("requests") or not batch.get("requests"):
                problems.append("both selftest tenants must see traffic "
                                "(run with --selftest --tenants on)")
            else:
                objective = SELFTEST_TENANTS["plat"]["ttft_p95_ms"]
                if plat.get("shed") or plat.get("errors"):
                    problems.append(
                        "pinned tenant was not isolated: "
                        f"shed={plat['shed']} errors={plat['errors']}")
                if plat.get("ttft_ms_p95", 0.0) > objective:
                    problems.append(
                        f"pinned tenant ttft p95 {plat['ttft_ms_p95']}ms "
                        f"blew its {objective:g}ms objective under bulk "
                        "overload")
                if not batch.get("shed"):
                    problems.append(
                        "bulk tenant was never shed — the overload this "
                        "assertion exists to survive did not happen")
            for p_ in problems:
                print(f"[replay] tenant QoS assertion FAILED: {p_}")
            if problems:
                rc = 1
            else:
                print("[replay] tenant QoS assertion PASSED: pinned p95 "
                      f"{plat['ttft_ms_p95']}ms held its objective; bulk "
                      f"shed {batch['shed']}/{batch['requests']} at "
                      "admission")
        if args.report_json:
            with open(args.report_json, "w", encoding="utf-8") as f:
                json.dump(report, f, indent=1)
        return rc
    finally:
        if gw is not None:
            gw.close()


if __name__ == "__main__":
    sys.exit(main())
