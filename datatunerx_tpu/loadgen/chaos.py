"""Chaos injection for load replays: scheduled faults over the EXISTING
control surfaces — nothing here reaches into engine internals.

A chaos schedule is a list of ops, each fired at ``t`` seconds into the
replay (scaled by the replay's speed factor):

  {"t": 5.0, "op": "drain",          "replica": "replica-1"}
  {"t": 6.0, "op": "scale",          "replicas": 3}
  {"t": 7.0, "op": "adapter_unload", "url": "http://r0:8001",
                                     "adapter": "tenant-3"}
  {"t": 8.0, "op": "adapter_load",   "url": "http://r0:8001",
                                     "name": "tenant-3",
                                     "checkpoint": "/ckpts/t3"}
  {"t": 9.0, "op": "kill",           "replica": "replica-0"}
  {"t": 10., "op": "slice_shrink",   "slice": "slice-1"}

``drain``/``scale`` map to the gateway's ``POST /admin/drain`` /
``/admin/scale``; ``adapter_*`` to a replica's ``/admin/adapters``. ``kill``
and ``slice_shrink`` have no HTTP surface by design (killing a process is
the supervisor's job, shrinking a slice pool is the scheduler's) — they
require injected actions, which the in-process harness (``--selftest``,
bench replay, tests) provides. An op with no action available is logged as
skipped, never an error: a chaos run against a production gateway simply
can't kill what it can't reach.

Every op's outcome lands in ``injector.log`` so the replay report shows
WHAT was injected WHEN next to the SLO verdict it did (or didn't) dent.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional


def _http(method: str, url: str, payload: Optional[dict] = None,
          timeout: float = 10.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode() if payload is not None else None,
        headers={"Content-Type": "application/json"}, method=method)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.load(r)


def http_actions(gateway_url: str) -> Dict[str, Callable[[dict], dict]]:
    """The over-the-wire op set, bound to one gateway base URL."""
    base = gateway_url.rstrip("/")
    return {
        "drain": lambda op: _http(
            "POST", base + "/admin/drain",
            {"replica": op.get("replica", "")}),
        "scale": lambda op: _http(
            "POST", base + "/admin/scale",
            {"replicas": int(op.get("replicas", 0))}),
        "adapter_unload": lambda op: _http(
            "DELETE",
            op["url"].rstrip("/") + "/admin/adapters/" + op["adapter"]),
        "adapter_load": lambda op: _http(
            "POST", op["url"].rstrip("/") + "/admin/adapters",
            {"name": op["name"], "checkpoint": op["checkpoint"],
             "load": op.get("load", True)}),
    }


def load_chaos(path_or_json: str) -> List[dict]:
    """Chaos schedule from a file path or inline JSON ('[' / '{' prefix)."""
    text = path_or_json.strip()
    if not text.startswith(("[", "{")):
        with open(path_or_json, encoding="utf-8") as f:
            text = f.read()
    doc = json.loads(text)
    if isinstance(doc, dict):
        doc = doc.get("ops")
    if not isinstance(doc, list):
        raise ValueError("chaos config must be a list of ops "
                         "(or {\"ops\": [...]})")
    for op in doc:
        if not isinstance(op.get("t"), (int, float)) or not op.get("op"):
            raise ValueError(f"bad chaos op {op!r}: needs t and op")
    return sorted(doc, key=lambda o: o["t"])


class ChaosInjector:
    """Fires a chaos schedule on its own thread while a replay runs.

    ``actions`` maps op name → callable(op_dict) → detail; in-process
    harnesses inject callables for ops with no wire surface (kill,
    slice_shrink) or to override the HTTP defaults."""

    def __init__(self, ops: List[dict],
                 actions: Optional[Dict[str, Callable]] = None):
        self.ops = sorted(ops, key=lambda o: o["t"])
        self.actions = dict(actions or {})
        self.log: List[dict] = []
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _fire(self, op: dict, at_s: float):
        entry = {"t": round(at_s, 3), "op": op.get("op"),
                 "args": {k: v for k, v in op.items()
                          if k not in ("t", "op")}}
        action = self.actions.get(op["op"])
        if action is None:
            entry.update(ok=None, detail="skipped: no action for op")
        else:
            try:
                out = action(op)
                entry.update(ok=True, detail=out if isinstance(out, (str, dict))
                             else repr(out))
            except urllib.error.HTTPError as e:
                entry.update(ok=False, detail=f"HTTP {e.code}")
            except Exception as e:  # noqa: BLE001 — chaos failing is data
                entry.update(ok=False, detail=str(e))
        with self._lock:
            self.log.append(entry)

    def _log_skipped(self, ops: List[dict], at_s: float):
        with self._lock:
            for missed in ops:
                self.log.append({
                    "t": round(at_s, 3), "op": missed.get("op"),
                    "args": {k: v for k, v in missed.items()
                             if k not in ("t", "op")},
                    "ok": None,
                    "detail": "skipped: replay ended before "
                              f"op time t={missed['t']}"})

    def run(self, speed: float = 1.0):
        """Blocking: fire every op at its (speed-scaled) offset. Ops the
        replay ends before — still in the future when stop() lands, OR
        overdue behind a slow earlier action — are LOGGED as skipped,
        never fired post-run and never silently dropped: a report must
        not show a clean verdict next to a schedule that half-ran (or a
        fault that landed AFTER the judgment)."""
        t0 = time.monotonic()
        for i, op in enumerate(self.ops):
            delay = op["t"] / max(speed, 1e-9) - (time.monotonic() - t0)
            if (delay > 0 and self._shutdown.wait(delay)) \
                    or self._shutdown.is_set():
                self._log_skipped(self.ops[i:], time.monotonic() - t0)
                return
            self._fire(op, time.monotonic() - t0)

    def start(self, speed: float = 1.0) -> "ChaosInjector":
        self._thread = threading.Thread(
            target=self.run, args=(speed,), name="dtx-chaos", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0):
        self._shutdown.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def report(self) -> List[dict]:
        with self._lock:
            return list(self.log)
