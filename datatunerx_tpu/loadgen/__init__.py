"""Trace-driven load replay + chaos harness (stdlib-only).

The standing proof behind the "heavy traffic from millions of users"
claims: generate production-shaped traffic (heavy-tail prompt/output
lengths, multi-turn sessions reusing prefixes, adapter-churning ``model``
fields), record it as a replayable JSONL trace, fire it at a gateway while
a chaos injector drives the existing control surfaces (``/admin/drain``,
adapter unload, replica kill, slice-pool shrink), and judge the run with
an SLO epilogue — the same ``obs/slo.py`` evaluator the gateway's
``GET /debug/slo`` serves — exiting nonzero NAMING any violated objective.

  loadgen.workload — the workload model + trace format
  loadgen.chaos    — scheduled fault injection over control surfaces
  loadgen.replay   — the runner, clients, SLO epilogue, and the
                     ``dtx replay`` CLI

Entry points: ``dtx replay``, ``python -m datatunerx_tpu.loadgen.replay``,
and bench.py's ``DTX_BENCH_REPLAY`` mode.
"""

from datatunerx_tpu.loadgen.workload import (  # noqa: F401
    WorkloadModel,
    read_trace,
    write_trace,
)
from datatunerx_tpu.loadgen.chaos import ChaosInjector  # noqa: F401
from datatunerx_tpu.loadgen.replay import ReplayRunner  # noqa: F401
