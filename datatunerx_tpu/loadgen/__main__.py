"""``python -m datatunerx_tpu.loadgen`` — the replay CLI."""

import sys

from datatunerx_tpu.loadgen.replay import main

if __name__ == "__main__":
    sys.exit(main())
