"""Prefill→decode handoff: prefill specialists do prompt work, decode
replicas own token production.

Steady state (``tick``): every available role=prefill replica with live
sessions is drained of its DECODE-READY work — sessions whose chunked
prefill finished are exported (PR 12 migration payloads) and imported
onto the decode-preferring peer with the most free KV blocks. Sessions
still mid-chunked-prefill are left to finish their prompt (the export
skips them); they move on the NEXT tick, one prefill-to-decode pipeline
per session. Imported continuations park in the gateway handoff buffer
keyed by trace id; the client stream that dies with the migrated marker
splices them — one uninterrupted SSE stream.

Drain-time (gateway ``handoff_sessions`` with the fleet plane enabled)
additionally ships MID-prefill tails: ``export_sessions(
include_prefill=True)`` exports the blocks written so far plus the
remaining prompt tokens, and the importer resumes chunking exactly where
the source stopped (BatchedEngine._import_prefill_tail) — a prefill
specialist can be drained mid-prompt with zero re-prefill.

Counters → dtx_fleet_handoff_total{outcome}:
  ok       session re-homed onto a decode peer (continuation parked)
  cold     no peer could admit it; the client falls back to re-prefill
  skipped  source sessions not exportable this tick (mid-prefill)
  none     a prefill source had work but no decode-side peer existed
"""

from __future__ import annotations

from typing import Callable, List, Optional


def decode_targets(pool, source_name: str) -> List:
    """Peers that should RECEIVE decode work: available, not the source,
    decode-preferring first (non-prefill roles), most free KV blocks
    first within a role class — the same greedy placement the spill
    coordinator uses, so both re-homing paths agree on where decode
    capacity lives."""

    def _rank(r):
        prefill = 1 if getattr(r, "role", "mixed") == "prefill" else 0
        try:
            free = int(r.stats_snapshot().get("kv_blocks_free") or 0)
        except Exception:  # noqa: BLE001 — stats are advisory
            free = 0
        return (prefill, -free, r.name)

    return sorted((r for r in pool.available() if r.name != source_name),
                  key=_rank)


class HandoffCoordinator:
    def __init__(self, pool, park: Callable[[str, dict], None],
                 wire: Optional[str] = None):
        self.pool = pool
        self.park = park
        self.wire = wire
        self.counters = {"ok": 0, "cold": 0, "skipped": 0, "none": 0}

    def tick(self) -> dict:
        out = {"moved": 0, "cold": 0, "skipped": 0}
        for source in list(self.pool.available()):
            if getattr(source, "role", "mixed") != "prefill":
                continue
            one = self._drain_source(source)
            for k in out:
                out[k] += one.get(k, 0)
        return out

    def _drain_source(self, source) -> dict:
        out = {"moved": 0, "cold": 0, "skipped": 0}
        try:
            st = source.stats_snapshot()
        except Exception:  # noqa: BLE001 — stats are advisory
            st = {}
        if not int(st.get("slots_busy") or 0):
            return out
        targets = decode_targets(self.pool, source.name)
        if not targets:
            self.counters["none"] += 1
            return out
        try:
            # include_prefill stays False here: steady-state ticks move
            # FINISHED prompt work only; a session mid-chunked-prefill
            # keeps its specialist until the prompt is done (its tail
            # ships only when the replica is actually draining)
            doc = source.export_sessions(wire=self.wire)
        except Exception:  # noqa: BLE001 — source busy/faulted; next tick
            return out
        if doc is None:
            return out
        skipped = len(doc.get("skipped") or [])
        out["skipped"] = skipped
        self.counters["skipped"] += skipped
        for payload in doc.get("sessions") or []:
            if self._rehome(payload, targets):
                out["moved"] += 1
            else:
                out["cold"] += 1
        return out

    def _rehome(self, payload: dict, targets: List) -> bool:
        tid = str(payload.get("trace_id") or "")
        for target in targets:
            try:
                res = target.import_session(payload)
            except Exception:  # noqa: BLE001 — refused or faulted; next peer
                continue
            if res is None:
                continue
            meta, stream = res
            self.park(tid, {
                "target": target.name, "meta": meta, "stream": stream,
                "text_so_far": str(meta.get("text_so_far") or "")})
            self.counters["ok"] += 1
            return True
        # tombstone: the dying stream stops waiting and re-prefills cold
        self.park(tid, {"failed": True})
        self.counters["cold"] += 1
        return False
