"""Peer-replica KV spill: a preempted session waits for the FLEET's
capacity, not one replica's.

KV overcommit (PR 15) preempts the youngest session when a replica's
block pool runs dry and parks its payload for local resumption. When a
PEER has free blocks, waiting locally is the wrong call — the spill
coordinator re-homes the parked payload there instead, token-exactly,
via the existing import path. Two-phase, so the session always has
exactly one owner:

  hold     source.hold_parked(...) leases parked sessions (time-bounded:
           a dead coordinator never wedges local resumption — the lease
           expires and the source resumes as before). A HELD head still
           blocks younger cold admissions on the source, so the
           fleet-wide oldest-live-session guarantee survives the move.
  import   peer with the most free blocks admits the payload
           (decode-preferring peers first); the continuation parks in
           the gateway handoff buffer for the client stream to splice.
  drop     source.drop_parked([trace_id]) — the source counts the
           preemption ``spilled`` and terminates the original request
           with the migrated marker, which is what sends the client
           stream to the handoff buffer.
  release  on any import failure, source.release_parked clears the
           lease immediately instead of waiting out the hold.

Counters → dtx_fleet_spill_total{outcome}: ok / refused (every peer
409'd — no slot or blocks) / error (transport or drop fault) / skipped
(parked work with no eligible peer).
"""

from __future__ import annotations

from typing import Callable, List

from datatunerx_tpu.fleet.handoff import decode_targets


class SpillCoordinator:
    def __init__(self, pool, park: Callable[[str, dict], None],
                 max_sessions: int = 2, hold_s: float = 10.0):
        self.pool = pool
        self.park = park
        self.max_sessions = max_sessions
        self.hold_s = hold_s
        self.counters = {"ok": 0, "refused": 0, "error": 0, "skipped": 0}

    def tick(self) -> dict:
        out = {"moved": 0, "refused": 0, "skipped": 0}
        for source in list(self.pool.available()):
            try:
                st = source.stats_snapshot()
            except Exception:  # noqa: BLE001 — stats are advisory
                continue
            if not int(st.get("sessions_parked") or 0):
                continue
            one = self._spill_source(source)
            for k in out:
                out[k] += one.get(k, 0)
        return out

    def _spill_source(self, source) -> dict:
        out = {"moved": 0, "refused": 0, "skipped": 0}
        targets = decode_targets(self.pool, source.name)
        targets = [t for t in targets if self._has_free_blocks(t)]
        if not targets:
            # nothing can take the work: don't lease — the source's own
            # resume path stays the session's owner
            self.counters["skipped"] += 1
            out["skipped"] += 1
            return out
        try:
            doc = source.hold_parked(max_sessions=self.max_sessions,
                                     hold_s=self.hold_s)
        except Exception:  # noqa: BLE001 — lease refused/faulted; next tick
            return out
        if doc is None:
            return out  # replica kind without the spill surface
        for sess in doc.get("sessions") or []:
            outcome = self._spill_one(source, sess, targets)
            if outcome == "ok":
                out["moved"] += 1
            elif outcome == "refused":
                out["refused"] += 1
        return out

    @staticmethod
    def _has_free_blocks(replica) -> bool:
        """Only paged peers reporting free blocks are spill targets —
        re-homing onto a peer that will itself immediately preempt just
        shuttles the same session around the fleet."""
        try:
            st = replica.stats_snapshot()
        except Exception:  # noqa: BLE001 — stats are advisory
            return False
        return int(st.get("kv_blocks_free") or 0) > 0

    def _spill_one(self, source, sess: dict, targets: List) -> str:
        tid = str(sess.get("trace_id") or "")
        payload = sess.get("payload")
        if not isinstance(payload, dict):
            self._release(source, tid)
            self.counters["error"] += 1
            return "error"
        refused = False
        for target in targets:
            try:
                res = target.import_session(payload)
            except Exception as e:  # noqa: BLE001 — refused or faulted
                if getattr(e, "status", None) == 409:
                    refused = True
                continue
            if res is None:
                continue
            meta, stream = res
            # park BEFORE drop: dropping terminates the source request
            # with the migrated marker, and the dying client stream must
            # find its continuation already waiting
            self.park(tid, {
                "target": target.name, "meta": meta, "stream": stream,
                "text_so_far": str(meta.get("text_so_far") or "")})
            try:
                source.drop_parked([tid])
            except Exception as e:  # noqa: BLE001 — the lease still owns it
                # the peer now runs the session; the source's copy stays
                # leased until the hold expires, after which a local
                # resume would FORK the stream — loud, because this is
                # the one path where single-ownership depends on the
                # drop landing
                print(f"[fleet] spill drop of {tid or '<no-trace>'} on "
                      f"{source.name} failed: {e}", flush=True)
                self.counters["error"] += 1
                return "error"
            self.counters["ok"] += 1
            return "ok"
        self._release(source, tid)
        outcome = "refused" if refused else "error"
        self.counters[outcome] += 1
        return outcome

    @staticmethod
    def _release(source, tid: str):
        try:
            source.release_parked([tid])
        except Exception:  # noqa: BLE001 — the lease expiry is the backstop
            pass
