"""Disaggregated fleet plane: the coordination layer that makes N serving
replicas behave as ONE KV pool.

Three composable pieces, each independently flag-gated and each built on
the replica migration surfaces (gateway/replica_pool.py) rather than any
new wire format:

  PrefixTier (prefix_tier.py)
      A gateway-side directory of published prefix-cache payloads
      (dtx-kv-prefix, serving/migration.py). The first replica to prefill
      a shared system prompt publishes it; the tier pushes it to peers so
      their FIRST request against that prompt activates with zero prefill
      chunks. LRU + byte-budget bounded.

  HandoffCoordinator (handoff.py)
      Steady-state prefill→decode disaggregation: sessions whose prompt
      work finished on a role=prefill specialist are exported and
      re-homed onto a decode-preferring peer; the client's SSE stream
      splices the imported continuation (gateway handoff buffer) and
      never notices. Drains additionally ship MID-chunked-prefill tails
      (``export_sessions(include_prefill=True)``).

  SpillCoordinator (spill.py)
      Preemption-parked sessions (KV overcommit, PR 15) are re-homed
      onto a peer with free blocks instead of waiting for local capacity:
      two-phase hold → import-on-peer → drop, leases time-bounded so a
      dead coordinator never wedges local resumption. The fleet-wide
      oldest-live-session guarantee holds: a held head blocks younger
      local admissions until it is dropped (moved) or released.

``FleetPlane`` owns whichever pieces are enabled, ticks them from one
daemon thread, and exposes their counters for the gateway's /metrics
restatement (``dtx_fleet_*``). With every flag at its default the plane
is never constructed and the gateway is byte-identical to a fleet-less
build.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from datatunerx_tpu.fleet.handoff import HandoffCoordinator
from datatunerx_tpu.fleet.prefix_tier import PrefixTier
from datatunerx_tpu.fleet.spill import SpillCoordinator

__all__ = [
    "FleetPlane",
    "HandoffCoordinator",
    "PrefixTier",
    "SpillCoordinator",
]


class FleetPlane:
    """Facade over the enabled coordinators. ``park`` is the gateway's
    handoff-buffer put (trace_id, entry) — both re-homing coordinators
    park imported continuations there for the dying client streams to
    splice. Tests drive ``tick()`` directly; production starts the
    daemon loop via ``start()``."""

    def __init__(self, pool, park: Callable[[str, dict], None],
                 prefix_budget_bytes: int = 0,
                 handoff: bool = False, spill: bool = False,
                 spill_max_sessions: int = 2, spill_hold_s: float = 10.0):
        self.pool = pool
        self.prefix: Optional[PrefixTier] = (
            PrefixTier(prefix_budget_bytes)
            if prefix_budget_bytes > 0 else None)
        self.handoff: Optional[HandoffCoordinator] = (
            HandoffCoordinator(pool, park) if handoff else None)
        self.spill: Optional[SpillCoordinator] = (
            SpillCoordinator(pool, park,
                             max_sessions=spill_max_sessions,
                             hold_s=spill_hold_s) if spill else None)
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serializes tick() against itself: the daemon loop and a test /
        # admin-triggered tick must not interleave two-phase spills
        self._tick_lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return (self.prefix is not None or self.handoff is not None
                or self.spill is not None)

    def tick(self) -> dict:
        """One coordination pass over the fleet; returns a per-piece
        summary (the /debug/fleet body)."""
        with self._tick_lock:
            out: dict = {}
            if self.handoff is not None:
                out["handoff"] = self.handoff.tick()
            if self.spill is not None:
                out["spill"] = self.spill.tick()
            if self.prefix is not None:
                out["prefix"] = self.prefix.sync_all(self.pool.available())
            return out

    def start(self, interval_s: float = 1.0):
        if self._thread is not None or interval_s <= 0:
            return

        def _loop():
            while not self._shutdown.wait(interval_s):
                try:
                    self.tick()
                except Exception as e:  # noqa: BLE001 — the loop must survive
                    print(f"[fleet] tick failed: {e}", flush=True)

        self._thread = threading.Thread(target=_loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._shutdown.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def stats(self) -> dict:
        """Counter snapshot for /metrics restatement and /debug/fleet."""
        out: dict = {}
        if self.prefix is not None:
            out["prefix"] = self.prefix.stats()
        if self.handoff is not None:
            out["handoff"] = dict(self.handoff.counters)
        if self.spill is not None:
            out["spill"] = dict(self.spill.counters)
        return out
