"""Fleet-shared prefix tier: one replica prefills a shared system prompt,
every replica serves it warm.

The tier is a gateway-side DIRECTORY of prefix payloads (dtx-kv-prefix,
serving/migration.py) keyed by fingerprint — sha1 over (adapter name,
prompt-prefix token ids), computed engine-side so the key is identical
across replicas regardless of tokenizer plumbing. ``sync(replica)`` is a
pull-then-push pass:

  pull  replica.export_prefix_entries(exclude=<known fingerprints>)
        — entries the tier has not seen are PUBLISHED (stored, LRU-fresh).
  push  every directory entry the replica is not known to hold is offered
        via replica.import_prefix_entry; ``{"imported": True}`` activates
        it in the replica's local _PrefixCache (COW block scatter on paged
        engines), so the replica's next request against that prompt
        admits with ZERO prefill chunks.

Byte budget: payloads are resident KV (b64 on the wire); the directory
evicts LRU past ``byte_budget`` so the gateway's footprint is bounded by
flag, not by traffic. Eviction only forgets the DIRECTORY copy — replicas
that already imported keep serving their local entries.

Counters (restated as dtx_fleet_prefix_* by the gateway):
  publishes  entries pulled into the directory
  hits       peer imports that activated an entry
  misses     pushes refused or failed (no free slot/blocks, unknown
             adapter on the target, transport fault)
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Optional


def payload_bytes(payload: dict) -> int:
    """Approximate wire size of one prefix payload: the b64 KV strings
    dominate; scalar fields are noise next to them."""
    n = 0
    for doc in (payload.get("kv"), payload):
        if not isinstance(doc, dict):
            continue
        for v in doc.values():
            if isinstance(v, str):
                n += len(v)
    return max(1, n)


class PrefixTier:
    def __init__(self, byte_budget: int, max_pull: int = 4):
        self.byte_budget = int(byte_budget)
        self.max_pull = max_pull
        # fingerprint -> {"payload", "bytes", "adapter", "cursor",
        #                 "replicas": set(activated), "failed": set}
        self._d: "OrderedDict[str, dict]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.counters = {"publishes": 0, "hits": 0, "misses": 0,
                         "evicted": 0}

    # ------------------------------------------------------------- directory
    def publish(self, payload: dict,
                source: Optional[str] = None) -> bool:
        """Store one exported prefix payload. Returns True when the
        fingerprint is new (a publish); re-offers of a known fingerprint
        only refresh its LRU position and mark the source as holding it."""
        fp = str(payload.get("fingerprint") or "")
        if not fp:
            return False
        with self._lock:
            ent = self._d.get(fp)
            if ent is not None:
                self._d.move_to_end(fp)
                if source:
                    ent["replicas"].add(source)
                return False
            ent = {"payload": dict(payload),
                   "bytes": payload_bytes(payload),
                   "adapter": str(payload.get("adapter") or ""),
                   "cursor": int(payload.get("cursor") or 0),
                   "replicas": {source} if source else set(),
                   "failed": set()}
            self._d[fp] = ent
            self._bytes += ent["bytes"]
            self.counters["publishes"] += 1
            self._evict_locked()
        return True

    def _evict_locked(self):
        while self._bytes > self.byte_budget and len(self._d) > 1:
            _, ent = self._d.popitem(last=False)
            self._bytes -= ent["bytes"]
            self.counters["evicted"] += 1

    # ----------------------------------------------------------------- sync
    def sync(self, replica) -> dict:
        """One pull-then-push pass against one replica. Replicas without
        the prefix surface (None returns) are skipped quietly; refusals
        count as misses but stay retryable (a 409 today — no free slot,
        adapter not yet loaded — may succeed next pass). A transport
        fault marks the replica failed for the entry so a permanently
        incompatible peer is not re-offered forever."""
        out = {"pulled": 0, "pushed": 0, "refused": 0}
        name = getattr(replica, "name", "")
        with self._lock:
            known = list(self._d.keys())
        try:
            doc = replica.export_prefix_entries(exclude=known,
                                                max_entries=self.max_pull)
        except Exception:  # noqa: BLE001 — export is advisory; push anyway
            doc = None
        for payload in (doc or {}).get("entries") or []:
            if self.publish(payload, source=name):
                out["pulled"] += 1
        with self._lock:
            todo = [(fp, ent["payload"]) for fp, ent in
                    reversed(list(self._d.items()))
                    if name not in ent["replicas"]
                    and name not in ent["failed"]]
        for fp, payload in todo:
            try:
                res = replica.import_prefix_entry(payload)
            except Exception as e:  # noqa: BLE001 — refusal or fault
                self.counters["misses"] += 1
                out["refused"] += 1
                if getattr(e, "status", None) != 409:
                    with self._lock:
                        ent = self._d.get(fp)
                        if ent is not None:
                            ent["failed"].add(name)
                continue
            if res is None:
                break  # replica kind without the prefix surface
            with self._lock:
                ent = self._d.get(fp)
                if ent is not None:
                    ent["replicas"].add(name)
            if res.get("imported"):
                self.counters["hits"] += 1
                out["pushed"] += 1
        return out

    def sync_all(self, replicas: List) -> dict:
        out = {"pulled": 0, "pushed": 0, "refused": 0}
        for r in replicas:
            one = self.sync(r)
            for k in out:
                out[k] += one[k]
        return out

    # -------------------------------------------------------------- reports
    @property
    def entries(self) -> int:
        with self._lock:
            return len(self._d)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def holders(self, fingerprint: str) -> set:
        with self._lock:
            ent = self._d.get(fingerprint)
            return set(ent["replicas"]) if ent else set()

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._d), "bytes": self._bytes,
                    **self.counters}
