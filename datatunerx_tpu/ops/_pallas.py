"""Shared interpret-mode gate for the Pallas kernels.

Default: interpret (emulate with standard JAX ops) everywhere except on a
real TPU backend — CPU tests exercise kernel numerics without Mosaic.

``DTX_PALLAS_INTERPRET=0`` forces REAL Mosaic lowering regardless of the
default backend: deviceless AOT certification (scripts/aot_certify.py)
compiles against a TPU topology while ``jax_platforms=cpu`` is set (the
wedged-relay workaround, VERDICT r4 next #1), where ``default_backend()``
says "cpu" but the compile target is the real XLA-TPU/Mosaic pipeline —
without the override the certification would silently compile the
emulation path and prove nothing.
"""

from __future__ import annotations

import os

import jax


def pick_block_n(n: int, cap: int) -> int:
    """Largest divisor of ``n`` that is ≤ cap and lane-aligned (multiple of
    128), preferred; else the largest power-of-two divisor ≤ cap.

    ``min(cap, n)`` + divisibility assert is NOT enough in general: real
    model dims are not all multiples of 256 (Qwen1.5-14B intermediate size
    13696 = 128 × 107 broke the nf4 path's ``assert N % 256 == 0`` — caught
    by AOT certification, never reachable while the relay was wedged)."""
    cap = min(cap, n)
    for bn in range(cap - cap % 128, 0, -128):
        if n % bn == 0:
            return bn
    bn = 1
    while bn * 2 <= cap and n % (bn * 2) == 0:
        bn *= 2
    return bn


def interpret_default() -> bool:
    env = (os.environ.get("DTX_PALLAS_INTERPRET") or "").strip()
    if env:  # empty/unset -> backend default ("VAR= cmd" must not force Mosaic)
        return env.lower() not in ("0", "false", "no")
    return jax.default_backend() != "tpu"
