"""Shared interpret-mode gate for the Pallas kernels.

Default: interpret (emulate with standard JAX ops) everywhere except on a
real TPU backend — CPU tests exercise kernel numerics without Mosaic.

``DTX_PALLAS_INTERPRET=0`` forces REAL Mosaic lowering regardless of the
default backend: deviceless AOT certification (scripts/aot_certify.py)
compiles against a TPU topology while ``jax_platforms=cpu`` is set (the
wedged-relay workaround, VERDICT r4 next #1), where ``default_backend()``
says "cpu" but the compile target is the real XLA-TPU/Mosaic pipeline —
without the override the certification would silently compile the
emulation path and prove nothing.
"""

from __future__ import annotations

import os

import jax


def interpret_default() -> bool:
    env = (os.environ.get("DTX_PALLAS_INTERPRET") or "").strip()
    if env:  # empty/unset -> backend default ("VAR= cmd" must not force Mosaic)
        return env.lower() not in ("0", "false", "no")
    return jax.default_backend() != "tpu"
