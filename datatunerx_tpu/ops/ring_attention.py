"""Ring attention: sequence-parallel causal attention over a mesh axis.

Long-context support the reference lacks entirely (SURVEY.md §5.7: the
reference only truncates to block_size). Sequences shard over the mesh's
``sp`` axis; K/V chunks rotate around the ring via ``ppermute`` (ICI
neighbor exchange) while each device accumulates online-softmax statistics —
attention memory per device stays O(T_local), total sequence length scales
with the ring size.

`ring_attention` is written to run inside `shard_map` (it uses
`lax.axis_index`/`lax.ppermute`); `ring_attention_sharded` wraps it for a
given mesh. The plain GSPMD path (all-gather K/V) remains the fallback the
compiler picks when the model runs without the explicit ring (sp axis in
parallel/sharding.py batch specs).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30

# set by the Trainer when cfg.attention_impl == "ring" and a mesh is active;
# the model-level dispatch (ops/attention.py) reads it
_RING: dict = {"mesh": None, "axis": "sp", "batch_axes": ("dp", "fsdp")}


def set_ring_context(mesh: Optional[Mesh], axis_name: str = "sp",
                     batch_axes=("dp", "fsdp")) -> None:
    _RING.update(mesh=mesh, axis=axis_name, batch_axes=batch_axes)


def get_ring_context():
    return _RING["mesh"], _RING["axis"], _RING["batch_axes"]


def _chunk_attention(q, k, v, q_pos, k_pos, scale):
    """One K/V chunk's unnormalized contribution + stats, GQA-aware (no KV
    head expansion).

    q: [B, Tq, KV, G, d]; k, v: [B, Tk, KV, d].
    Returns (o [B, Tq, KV, G, d], m, l both [B, Tq, KV, G, 1]).
    """
    s = jnp.einsum("bqkgd,bskd->bkgqs", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    mask = k_pos[:, None, None, None, :] <= q_pos[:, None, None, :, None]
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # [B, KV, G, Tq, 1]
    p = jnp.exp(s - jnp.maximum(m, NEG_INF / 2))
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bkgqs,bskd->bqkgd", p, v.astype(jnp.float32))
    perm = (0, 3, 1, 2, 4)
    return o, m.transpose(perm), l.transpose(perm)


def _axis_size(axis_name):
    """jax.lax.axis_size is jax >= 0.6; psum(1, axis) is the classic
    spelling and constant-folds to the same static mesh-axis size."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def ring_attention(
    q: jnp.ndarray,  # [B, T_local, H, d]  (local sequence shard)
    k: jnp.ndarray,  # [B, T_local, KV, d]
    v: jnp.ndarray,
    axis_name: str = "sp",
) -> jnp.ndarray:
    """Causal ring attention; call under shard_map with sequence sharded on
    `axis_name`. Chunks are laid out contiguously: device i owns global
    positions [i*T_local, (i+1)*T_local)."""
    B, T, H, d = q.shape
    KV = k.shape[2]
    G = H // KV
    q = q.reshape(B, T, KV, G, d)
    scale = 1.0 / (d ** 0.5)

    n = _axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    q_pos = my * T + jnp.arange(T, dtype=jnp.int32)[None].repeat(B, 0)

    perm = [(i, (i + 1) % n) for i in range(n)]  # send to next, recv from prev

    def step(carry, _):
        kc, vc, src, acc, m_run, l_run = carry
        k_pos = src * T + jnp.arange(T, dtype=jnp.int32)[None].repeat(B, 0)
        o_c, m_c, l_c = _chunk_attention(q, kc, vc, q_pos, k_pos, scale)

        m_new = jnp.maximum(m_run, m_c)
        corr_run = jnp.exp(m_run - m_new)
        corr_c = jnp.exp(m_c - m_new)
        acc = acc * corr_run + o_c * corr_c
        l_run = l_run * corr_run + l_c * corr_c

        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        src = (src - 1) % n  # after rotation we hold the previous device's chunk
        return (kc, vc, src, acc, m_new, l_run), None

    acc0 = jnp.zeros((B, T, KV, G, d), jnp.float32)
    m0 = jnp.full((B, T, KV, G, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, T, KV, G, 1), jnp.float32)
    (_, _, _, acc, m_run, l_run), _ = jax.lax.scan(
        step, (k, v, my, acc0, m0, l0), None, length=n
    )
    out = acc / jnp.maximum(l_run, 1e-30)
    return out.reshape(B, T, H, d).astype(q.dtype)


# ------------------------------------------------------- ring of flash

def _ring_steps(axis_name: str):
    n = _axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return n, my, perm


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ring_flash(static, qf, kf, vf, seg):
    out, _ = _ring_flash_fwd_impl(static, qf, kf, vf, seg)
    return out


def _ring_flash_fwd_impl(static, qf, kf, vf, seg):
    from datatunerx_tpu.ops.flash_attention import _fwd

    axis_name, block_q, block_k, interpret, H, G = static
    n, my, perm = _ring_steps(axis_name)
    o0, lse0 = _fwd(qf, kf, vf, seg, seg, block_q=block_q, block_k=block_k,
                    interpret=interpret, H=H, G=G, causal=True)
    acc_o = o0.astype(jnp.float32)
    acc_lse = lse0
    if n == 1:
        return acc_o.astype(qf.dtype), acc_lse

    def step(carry, r):
        kc, vc, acc_o, acc_lse = carry
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        # after r rotations this device holds chunk src = (my - r) mod n:
        # strictly past iff my >= r (wrapped chunks are the future — masked)
        o_c, lse_c = _fwd(qf, kc, vc, seg, seg, block_q=block_q,
                          block_k=block_k, interpret=interpret, H=H, G=G,
                          causal=False)
        valid = my >= r
        lse_c = jnp.where(valid, lse_c, -jnp.inf)
        m = jnp.maximum(acc_lse, lse_c)
        wa = jnp.exp(acc_lse - m)
        wb = jnp.exp(lse_c - m)
        denom = wa + wb
        acc_o = (acc_o * wa[..., None]
                 + o_c.astype(jnp.float32) * wb[..., None]) / denom[..., None]
        acc_lse = m + jnp.log(denom)
        return (kc, vc, acc_o, acc_lse), None

    (kc, vc, acc_o, acc_lse), _ = jax.lax.scan(
        step, (kf, vf, acc_o, acc_lse), jnp.arange(1, n))
    return acc_o.astype(qf.dtype), acc_lse


def _ring_flash_vjp_fwd(static, qf, kf, vf, seg):
    out, lse = _ring_flash_fwd_impl(static, qf, kf, vf, seg)
    return out, (qf, kf, vf, seg, out, lse)


def _ring_flash_vjp_bwd(static, res, do):
    """Reverse ring: dq accumulates locally; (dk, dv) accumulators travel
    WITH their K/V chunk around the ring and arrive home after n rotations."""
    from datatunerx_tpu.ops.flash_attention import _bwd

    axis_name, block_q, block_k, interpret, H, G = static
    qf, kf, vf, seg, out, lse = res
    n, my, perm = _ring_steps(axis_name)

    dq0, dk0, dv0 = _bwd(block_q, block_k, interpret, G,
                         (qf, kf, vf, seg, seg, out, lse), do, causal=True)
    dq_acc = dq0.astype(jnp.float32)
    dk_acc = dk0.astype(jnp.float32)
    dv_acc = dv0.astype(jnp.float32)
    if n == 1:
        return dq_acc.astype(qf.dtype), dk_acc.astype(kf.dtype), \
            dv_acc.astype(vf.dtype), None

    def step(carry, r):
        kc, vc, dk_acc, dv_acc, dq_acc = carry
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
        dq_c, dk_c, dv_c = _bwd(block_q, block_k, interpret, G,
                                (qf, kc, vc, seg, seg, out, lse), do,
                                causal=False)
        valid = (my >= r).astype(jnp.float32)
        dq_acc = dq_acc + valid * dq_c.astype(jnp.float32)
        dk_acc = dk_acc + valid * dk_c.astype(jnp.float32)
        dv_acc = dv_acc + valid * dv_c.astype(jnp.float32)
        return (kc, vc, dk_acc, dv_acc, dq_acc), None

    (kc, vc, dk_acc, dv_acc, dq_acc), _ = jax.lax.scan(
        step, (kf, vf, dk_acc, dv_acc, dq_acc), jnp.arange(1, n))
    # one more rotation brings each chunk's accumulator home (n total)
    dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
    dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
    return (dq_acc.astype(qf.dtype), dk_acc.astype(kf.dtype),
            dv_acc.astype(vf.dtype), None)


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_flash_attention(
    q: jnp.ndarray,  # [B, T_local, H, d]  (local sequence shard)
    k: jnp.ndarray,  # [B, T_local, KV, d]
    v: jnp.ndarray,
    axis_name: str = "sp",
) -> jnp.ndarray:
    """Ring attention whose per-chunk compute is the Pallas flash kernel:
    O(T_local · block) memory instead of the XLA ring's O(T_local²) score
    tensors (which OOM'd the T=32k AOT certification at 34 GB/step, r5).
    Chunk visibility (self → causal kernel, past → full kernel, wrapped →
    masked out via -inf lse weight) is decided per ring step OUTSIDE the
    kernel, so the kernel itself stays static. Backward runs a reverse ring
    of flash-backward kernels with (dk, dv) accumulators rotating alongside
    their chunk."""
    from datatunerx_tpu.ops.flash_attention import _pick_block

    B, T, H, d = q.shape
    KV = k.shape[2]
    G = H // KV
    block_q = _pick_block(T)
    block_k = _pick_block(T)
    from datatunerx_tpu.ops.flash_attention import _interpret

    static = (axis_name, block_q, block_k, _interpret(), H, G)
    seg = jnp.ones((B, T), jnp.int32)
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, T, d)
    kf = k.transpose(0, 2, 1, 3).reshape(B * KV, T, d)
    vf = v.transpose(0, 2, 1, 3).reshape(B * KV, T, d)
    out = _ring_flash(static, qf, kf, vf, seg)
    return out.reshape(B, H, T, d).transpose(0, 2, 1, 3)


def ring_attention_sharded(
    q: jnp.ndarray,  # [B, T_global, H, d]
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axes=("dp", "fsdp"),
) -> jnp.ndarray:
    """Convenience wrapper: shard_map over (batch, sequence) with KV/head dims
    replicated; tp sharding of heads composes by adding 'tp' to the H spec.

    ``DTX_RING_IMPL`` picks the per-chunk engine: ``flash`` (default — the
    Pallas kernel per chunk, O(T_local) memory) or ``xla`` (the chunked
    einsum reference path, O(T_local²) scores — parity baseline and
    fallback)."""
    import os

    spec_q = P(batch_axes, axis_name, None, None)
    spec_kv = P(batch_axes, axis_name, None, None)
    impl = os.environ.get("DTX_RING_IMPL", "flash").strip().lower()
    base = ring_flash_attention if impl != "xla" else ring_attention
    fn = functools.partial(base, axis_name=axis_name)
    from datatunerx_tpu.parallel.sharding import compat_shard_map

    return compat_shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec_q, spec_kv, spec_kv),
        out_specs=spec_q,
        check=False,
    )(q, k, v)
