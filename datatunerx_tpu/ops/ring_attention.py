"""Ring attention: sequence-parallel causal attention over a mesh axis.

Long-context support the reference lacks entirely (SURVEY.md §5.7: the
reference only truncates to block_size). Sequences shard over the mesh's
``sp`` axis; K/V chunks rotate around the ring via ``ppermute`` (ICI
neighbor exchange) while each device accumulates online-softmax statistics —
attention memory per device stays O(T_local), total sequence length scales
with the ring size.

`ring_attention` is written to run inside `shard_map` (it uses
`lax.axis_index`/`lax.ppermute`); `ring_attention_sharded` wraps it for a
given mesh. The plain GSPMD path (all-gather K/V) remains the fallback the
compiler picks when the model runs without the explicit ring (sp axis in
parallel/sharding.py batch specs).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30

# set by the Trainer when cfg.attention_impl == "ring" and a mesh is active;
# the model-level dispatch (ops/attention.py) reads it
_RING: dict = {"mesh": None, "axis": "sp", "batch_axes": ("dp", "fsdp")}


def set_ring_context(mesh: Optional[Mesh], axis_name: str = "sp",
                     batch_axes=("dp", "fsdp")) -> None:
    _RING.update(mesh=mesh, axis=axis_name, batch_axes=batch_axes)


def get_ring_context():
    return _RING["mesh"], _RING["axis"], _RING["batch_axes"]


def _chunk_attention(q, k, v, q_pos, k_pos, scale):
    """One K/V chunk's unnormalized contribution + stats, GQA-aware (no KV
    head expansion).

    q: [B, Tq, KV, G, d]; k, v: [B, Tk, KV, d].
    Returns (o [B, Tq, KV, G, d], m, l both [B, Tq, KV, G, 1]).
    """
    s = jnp.einsum("bqkgd,bskd->bkgqs", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    mask = k_pos[:, None, None, None, :] <= q_pos[:, None, None, :, None]
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # [B, KV, G, Tq, 1]
    p = jnp.exp(s - jnp.maximum(m, NEG_INF / 2))
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bkgqs,bskd->bqkgd", p, v.astype(jnp.float32))
    perm = (0, 3, 1, 2, 4)
    return o, m.transpose(perm), l.transpose(perm)


def ring_attention(
    q: jnp.ndarray,  # [B, T_local, H, d]  (local sequence shard)
    k: jnp.ndarray,  # [B, T_local, KV, d]
    v: jnp.ndarray,
    axis_name: str = "sp",
) -> jnp.ndarray:
    """Causal ring attention; call under shard_map with sequence sharded on
    `axis_name`. Chunks are laid out contiguously: device i owns global
    positions [i*T_local, (i+1)*T_local)."""
    B, T, H, d = q.shape
    KV = k.shape[2]
    G = H // KV
    q = q.reshape(B, T, KV, G, d)
    scale = 1.0 / (d ** 0.5)

    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    q_pos = my * T + jnp.arange(T, dtype=jnp.int32)[None].repeat(B, 0)

    perm = [(i, (i + 1) % n) for i in range(n)]  # send to next, recv from prev

    def step(carry, _):
        kc, vc, src, acc, m_run, l_run = carry
        k_pos = src * T + jnp.arange(T, dtype=jnp.int32)[None].repeat(B, 0)
        o_c, m_c, l_c = _chunk_attention(q, kc, vc, q_pos, k_pos, scale)

        m_new = jnp.maximum(m_run, m_c)
        corr_run = jnp.exp(m_run - m_new)
        corr_c = jnp.exp(m_c - m_new)
        acc = acc * corr_run + o_c * corr_c
        l_run = l_run * corr_run + l_c * corr_c

        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        src = (src - 1) % n  # after rotation we hold the previous device's chunk
        return (kc, vc, src, acc, m_new, l_run), None

    acc0 = jnp.zeros((B, T, KV, G, d), jnp.float32)
    m0 = jnp.full((B, T, KV, G, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, T, KV, G, 1), jnp.float32)
    (_, _, _, acc, m_run, l_run), _ = jax.lax.scan(
        step, (k, v, my, acc0, m0, l0), None, length=n
    )
    out = acc / jnp.maximum(l_run, 1e-30)
    return out.reshape(B, T, H, d).astype(q.dtype)


def ring_attention_sharded(
    q: jnp.ndarray,  # [B, T_global, H, d]
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axes=("dp", "fsdp"),
) -> jnp.ndarray:
    """Convenience wrapper: shard_map over (batch, sequence) with KV/head dims
    replicated; tp sharding of heads composes by adding 'tp' to the H spec."""
    spec_q = P(batch_axes, axis_name, None, None)
    spec_kv = P(batch_axes, axis_name, None, None)
    fn = functools.partial(ring_attention, axis_name=axis_name)
    return jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec_q, spec_kv, spec_kv),
        out_specs=spec_q,
        check_vma=False,
    )(q, k, v)
