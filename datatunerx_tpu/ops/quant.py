"""Weight quantization: int8 (per-channel) and int4-nf4 (per-block, QLoRA).

Functional replacement for bitsandbytes' CUDA kernels (reference
cmd/tuning/train.py:224-234 selects int8 `load_in_8bit` or int4 nf4
`bnb_4bit_quant_type`; flags from cmd/tuning/parser.py:40-55). This module is
the XLA path + pack/dequant math; Pallas fused kernels (ops/pallas_quant.py)
are validated against it.

Design constraint: quantized param collections contain ONLY arrays (static
metadata — shapes, block size, mode — travels in ModelConfig / call sites), so
stacked [L, ...] quantized layers slice cleanly through `lax.scan`.

Formats:
- int8: symmetric per-output-channel absmax. {"q": int8[in, out], "scale": f32[out]}
- nf4 (QLoRA): per-block (64) absmax-normalized weights snapped to the 16-level
  NormalFloat4 codebook, two nibbles per uint8, channel-contiguous blocks.
  Double quantization: block scales stored int8 against a per-tensor meta scale
  (reference `double_quantization` default True, parser.py:48-51).
  {"packed": uint8[n_blocks, block/2], "scale_q": int8[n_blocks],
   "meta": f32[2] = [per-tensor scale, nibble-layout version]}
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NF4_BLOCK = 64
NF4_LAYOUT_VERSION = 2  # 2 = planar nibble halves (Mosaic-lowerable unpack)

# QLoRA NF4 codebook (16 quantiles of N(0,1), normalized to [-1, 1]).
NF4_CODE = np.array(
    [
        -1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
        -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
        0.07958029955625534, 0.16093020141124725, 0.24611230194568634,
        0.33791524171829224, 0.44070982933044434, 0.5626170039176941,
        0.7229568362236023, 1.0,
    ],
    dtype=np.float32,
)


# ----------------------------------------------------------------- int8

def quantize_int8(w: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """w: [in, out] → per-out-channel symmetric int8."""
    w = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(w), axis=0) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(w / scale[None, :]), -127, 127).astype(jnp.int8)
    return {"q": q, "scale": scale}


def dequant_int8(q: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    return (q.astype(jnp.float32) * scale[None, :]).astype(dtype)


def matmul_int8(x: jnp.ndarray, q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """x: [..., in] @ int8 weights → [..., out]; scale applied after the dot so
    the contraction runs mixed-precision on the MXU without a dequant copy."""
    y = jnp.einsum(
        "...i,io->...o", x, q.astype(x.dtype),
        preferred_element_type=jnp.float32,
    )
    return (y * scale[None, :]).astype(x.dtype)


# ------------------------------------------------------------------ nf4

def _nearest_nf4(normed: jnp.ndarray) -> jnp.ndarray:
    """Nearest NF4 code index via searchsorted on the codebook midpoints —
    identical to the 16-way |x − code| argmin (the codebook is sorted; exact
    midpoint ties are measure-zero) at 1/16th the arithmetic, which is what
    makes host-side quantization of a 7B tree tractable."""
    mids = jnp.asarray((NF4_CODE[1:] + NF4_CODE[:-1]) / 2.0)
    return jnp.searchsorted(mids, normed).astype(jnp.uint8)


def quantize_nf4(w: jnp.ndarray, block_size: int = NF4_BLOCK) -> Dict[str, jnp.ndarray]:
    """w: [in, out] → packed nf4 (channel-contiguous blocks: tensor is
    transposed to [out, in] then flattened, so each block holds one channel's
    consecutive input weights)."""
    in_dim, out_dim = w.shape
    if in_dim % block_size != 0:
        raise ValueError(
            f"nf4 requires in_dim % block_size == 0 (got {in_dim} % "
            f"{block_size}): blocks must not straddle output channels"
        )
    flat = w.astype(jnp.float32).T.reshape(-1)
    blocks = flat.reshape(-1, block_size)
    absmax = jnp.maximum(jnp.max(jnp.abs(blocks), axis=1), 1e-12)
    normed = blocks / absmax[:, None]
    idx = _nearest_nf4(normed)
    # planar nibble layout: lo nibbles hold the block's first half, hi the
    # second — dequant is then a minor-dim concat instead of an interleave,
    # which Mosaic can lower (vector shape-cast on the lane dim can't)
    half = block_size // 2
    lo, hi = idx[:, :half], idx[:, half:]
    packed = (lo | (hi << 4)).astype(jnp.uint8)

    meta = jnp.maximum(jnp.max(absmax) / 127.0, 1e-12)
    scale_q = jnp.clip(jnp.round(absmax / meta), 1, 127).astype(jnp.int8)
    # meta[1] is the nibble-layout version (2 = planar halves; 1, the round-1
    # interleaved layout, shipped as shape-(1,) meta). The SHAPE change is the
    # actual guard: a checkpoint quantized under the old layout fails Orbax
    # restore loudly instead of silently dequantizing permuted weights.
    meta = jnp.stack([meta, jnp.asarray(NF4_LAYOUT_VERSION, jnp.float32)])
    return {"packed": packed, "scale_q": scale_q, "meta": meta}


def nf4_scales(qw: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    return qw["scale_q"].astype(jnp.float32) * qw["meta"][0]


def dequant_nf4(
    qw: Dict[str, jnp.ndarray], shape: Tuple[int, int], dtype=jnp.float32
) -> jnp.ndarray:
    in_dim, out_dim = shape
    packed = qw["packed"]
    lo = (packed & 0x0F).astype(jnp.int32)
    hi = (packed >> 4).astype(jnp.int32)
    idx = jnp.concatenate([lo, hi], axis=-1)
    vals = jnp.asarray(NF4_CODE)[idx] * nf4_scales(qw)[:, None]
    return vals.reshape(out_dim, in_dim).T.astype(dtype)


def matmul_nf4(
    x: jnp.ndarray, qw: Dict[str, jnp.ndarray], shape: Tuple[int, int]
) -> jnp.ndarray:
    """XLA path: dequantize then matmul (XLA fuses the unpack chain into the
    dot's operand pipeline). The Pallas kernel does the unpack per-tile."""
    w = dequant_nf4(qw, shape, dtype=x.dtype)
    return jnp.einsum(
        "...i,io->...o", x, w, preferred_element_type=jnp.float32
    ).astype(x.dtype)


# ------------------------------------------------------- param-tree level

QUANT_KERNELS = (
    "q_proj", "k_proj", "v_proj", "o_proj", "gate_proj", "up_proj", "down_proj",
)


@jax.jit
def _quantize_int8_stacked(kern: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """[L, in, out] → stacked int8, all layers in one fused program."""
    w = kern.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(w), axis=1) / 127.0, 1e-12)  # [L, out]
    q = jnp.clip(jnp.round(w / scale[:, None, :]), -127, 127).astype(jnp.int8)
    return {"q": q, "scale": scale}


@jax.jit
def _quantize_nf4_stacked(kern: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """[L, in, out] → stacked planar-nibble nf4, all layers in one fused
    program (layerwise-identical to quantize_nf4; one dispatch per kernel
    name instead of L unjitted calls with [nb, b, 16] argmin temps — the
    difference between minutes and an hour for a 7B host-side quantize)."""
    L, in_dim, out_dim = kern.shape
    block_size = NF4_BLOCK
    flat = jnp.swapaxes(kern.astype(jnp.float32), 1, 2).reshape(
        L, -1, block_size)                                   # channel-contig
    absmax = jnp.maximum(jnp.max(jnp.abs(flat), axis=2), 1e-12)  # [L, nb]
    idx = _nearest_nf4(flat / absmax[..., None])
    half = block_size // 2
    packed = (idx[..., :half] | (idx[..., half:] << 4)).astype(jnp.uint8)
    meta0 = jnp.maximum(jnp.max(absmax, axis=1) / 127.0, 1e-12)  # [L]
    scale_q = jnp.clip(jnp.round(absmax / meta0[:, None]), 1, 127).astype(jnp.int8)
    meta = jnp.stack(
        [meta0, jnp.full((L,), NF4_LAYOUT_VERSION, jnp.float32)], axis=1)
    # STACKED layout is flat bytes per layer [L, nb*b/2]: a [L, nb, 32] stack
    # tiles to T(8,128) with a 4.0× lane-padding expansion (minor dim 32 vs
    # 128 lanes) and XLA materializes padded copies of the whole weight stack
    # as HLO temps — ~12 GB extra on a 7B model, an instant HBM OOM. Flat
    # rows are 128-divisible → zero padding; consumers reshape ONE layer's
    # slice back to [nb, b/2] inside the scan body (a ~21 MB transient).
    return {"packed": packed.reshape(L, -1), "scale_q": scale_q, "meta": meta}


def quantize_model_params(params, mode: str):
    """Quantize the stacked [L, in, out] transformer kernels in-tree.
    Embeddings, norms, and lm_head stay full-precision (bnb's skip list).
    Array-only leaves: int8 → q [L,in,out] + scale [L,out];
    nf4 → packed [L, nb*b/2] (flat bytes; see _quantize_nf4_stacked for why)
    + scale_q [L,nb] + meta [L,2]."""
    if mode not in ("int8", "int4", "nf4"):
        raise ValueError(f"unknown quantization mode {mode!r}")
    layers = dict(params["layers"])
    for name in QUANT_KERNELS:
        proj = dict(layers[name])
        kern = proj.pop("kernel")
        if mode == "int8":
            proj["quant"] = _quantize_int8_stacked(kern)
        else:
            if kern.shape[1] % NF4_BLOCK != 0:
                raise ValueError(
                    f"nf4 requires in_dim % {NF4_BLOCK} == 0 (got "
                    f"{kern.shape[1]}): blocks must not straddle channels")
            proj["quant"] = _quantize_nf4_stacked(kern)
        layers[name] = proj
    out = dict(params)
    out["layers"] = layers
    return out


def dequantize_model_params(params, mode: str, dims_fn):
    """Inverse of quantize_model_params (for export): dims_fn(name) -> (in, out)."""
    layers = dict(params["layers"])
    for name in QUANT_KERNELS:
        proj = dict(layers[name])
        quant = proj.pop("quant")
        L = jax.tree_util.tree_leaves(quant)[0].shape[0]
        if mode == "int8":
            kern = jnp.stack(
                [dequant_int8(quant["q"][i], quant["scale"][i]) for i in range(L)]
            )
        else:
            nb = quant["scale_q"].shape[1]
            per = [
                dequant_nf4(
                    {"packed": quant["packed"][i].reshape(nb, NF4_BLOCK // 2),
                     "scale_q": quant["scale_q"][i], "meta": quant["meta"][i]},
                    dims_fn(name))
                for i in range(L)
            ]
            kern = jnp.stack(per)
        proj["kernel"] = kern
        layers[name] = proj
    out = dict(params)
    out["layers"] = layers
    return out


def quantized_matmul(
    x: jnp.ndarray,
    quant: Dict[str, jnp.ndarray],
    mode: str,
    shape: Tuple[int, int],
    use_pallas: bool = False,
) -> jnp.ndarray:
    if mode == "int8":
        if use_pallas:
            from datatunerx_tpu.ops.pallas_quant import pallas_matmul_int8

            return pallas_matmul_int8(x, quant["q"], quant["scale"])
        return matmul_int8(x, quant["q"], quant["scale"])
    if quant["packed"].ndim == 1:
        # layer slice of the stacked flat-byte layout → per-block view
        quant = dict(quant, packed=quant["packed"].reshape(
            quant["scale_q"].shape[0], NF4_BLOCK // 2))
    if use_pallas:
        from datatunerx_tpu.ops.pallas_quant import pallas_matmul_nf4

        return pallas_matmul_nf4(x, quant, shape)
    return matmul_nf4(x, quant, shape)
