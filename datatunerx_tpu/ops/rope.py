"""Rotary position embeddings with linear / dynamic-NTK scaling.

The reference exposes ``--rope_scaling {linear,dynamic}`` (reference
cmd/tuning/parser.py:57-60) which patches HF llama rope at runtime. Here scaling
is a first-class config knob, computed statically so everything stays jittable.

Convention: HF-llama "rotate half" — for x = [x1 | x2] split down the middle of
the head dim, rope(x) = [x1*cos - x2*sin | x2*cos + x1*sin].
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_cos_sin(
    positions: jnp.ndarray,  # [B, T] int32
    head_dim: int,
    *,
    theta: float = 10000.0,
    scaling_type: str | None = None,
    scaling_factor: float = 1.0,
    max_seq_len: int = 4096,
    seq_len: int | None = None,
    dtype=jnp.float32,
):
    """Returns (cos, sin) each of shape [B, T, head_dim//2]."""
    half = head_dim // 2
    if scaling_type == "dynamic" and seq_len is not None and seq_len > max_seq_len:
        # Dynamic NTK: inflate the base theta as the window grows past training
        # length (same formula transformers uses for rope_scaling="dynamic").
        theta = theta * (
            (scaling_factor * seq_len / max_seq_len) - (scaling_factor - 1)
        ) ** (head_dim / (head_dim - 2))
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    pos = positions.astype(jnp.float32)
    if scaling_type == "linear":
        pos = pos / scaling_factor
    freqs = pos[..., None] * inv_freq  # [B, T, half]
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: [B, T, H, head_dim]; cos/sin: [B, T, head_dim//2]."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[:, :, None, :].astype(x.dtype)
    s = sin[:, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
