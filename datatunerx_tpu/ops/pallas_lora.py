"""Pallas fused LoRA matmul: y = x·W + (x·A)·B·scale in one kernel.

The SURVEY-mandated native replacement for peft's separate adapter matmuls
(SURVEY.md §2.4(a)): the adapter delta is computed per output tile while the
base tile is already resident in VMEM, so the [M, N] intermediate from the
adapter branch never round-trips through HBM. The rank-r contraction (r ≤ 64)
rides the same MXU pass.

XLA reference path: models/llama._proj; parity test tests/test_pallas_lora.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    from datatunerx_tpu.ops._pallas import interpret_default

    return interpret_default()


def _lora_kernel(x_ref, w_ref, a_ref, b_ref, o_ref, *, scale: float):
    x = x_ref[:]
    acc = jnp.dot(x, w_ref[:].astype(x.dtype),
                  preferred_element_type=jnp.float32)
    xa = jnp.dot(x, a_ref[:].astype(x.dtype),
                 preferred_element_type=jnp.float32)  # [bm, r]
    acc += jnp.dot(xa.astype(x.dtype), b_ref[:].astype(x.dtype),
                   preferred_element_type=jnp.float32) * scale
    o_ref[:] = acc.astype(o_ref.dtype)


def pallas_lora_matmul(
    x: jnp.ndarray,        # [..., K]
    w: jnp.ndarray,        # [K, N]
    a: jnp.ndarray,        # [K, r]
    b: jnp.ndarray,        # [r, N]
    scale: float,
    block_m: int = 256,
    block_n: int = 256,
) -> jnp.ndarray:
    *lead, K = x.shape
    N = w.shape[1]
    x2d = x.reshape(-1, K)
    m = x2d.shape[0]
    pad = (-m) % block_m
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    M = x2d.shape[0]
    from datatunerx_tpu.ops._pallas import pick_block_n

    bn = pick_block_n(N, block_n)
    r = a.shape[1]

    out = pl.pallas_call(
        functools.partial(_lora_kernel, scale=scale),
        grid=(M // block_m, N // bn),
        in_specs=[
            pl.BlockSpec((block_m, K), lambda i, j: (i, 0)),
            pl.BlockSpec((K, bn), lambda i, j: (0, j)),
            pl.BlockSpec((K, r), lambda i, j: (0, 0)),
            pl.BlockSpec((r, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        interpret=_interpret(),
    )(x2d, w, a, b)
    return out[:m].reshape(*lead, N)
