"""Paged KV cache: block pool + per-slot block tables (vLLM PagedAttention,
Sarathi chunked prefill — PAPERS.md).

The dense serving cache reserves ``slots × max_seq_len`` KV rows up front, so
a 40-token chat strands the other 984 positions of its slot in HBM for its
whole lifetime. Here the cache is a POOL of fixed-size blocks
(``block_size`` tokens each, shaped ``[L, num_blocks, block_size, KV, d]``)
plus a per-slot block table mapping linear cache positions to physical
blocks. Admission reserves ``ceil((prompt + max_new) / block_size)`` blocks
from a host-side free list instead of a full-width row, so short requests
release most of the HBM a dense slot would strand and the same pool admits
more concurrent work (or the same work in less HBM).

Reads go through a GATHER over the block table: the slot's blocks are
gathered back into a ``[B, blocks_per_slot × block_size]`` linear view and
attention runs over it exactly as over a dense row — the gathered view is
element-identical to the dense layout (token at linear index ``i`` lives in
block ``i // block_size`` at offset ``i % block_size``), so paged and dense
decode produce the same tokens. Unallocated table entries (-1) gather block
0's values but their rope positions are forced to ``POS_SENTINEL``, which
the causal bias masks exactly like a dense cache's unwritten tail. Writes
scatter through the table; invalid targets (exhausted slot, -1 entry) map to
index ``num_blocks`` — out of bounds, which JAX scatter drops.

The int8 ``kv_quant`` path is preserved: scale pools are paged alongside the
value pools with the same tables.

This module is wired into the model through ``ops/attention.py``'s cache
interface (``cache_positions_update`` / ``kv_cache_update``): a cache dict
carrying ``block_tables`` takes the paged path, anything else the dense one.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

# Marks invalid/pad cache slots: the causal check kv_pos <= q_pos then masks
# them with no separate validity plumbing. A plain int (NOT jnp.int32): a
# module-level device array would initialize the XLA backend at import time,
# breaking jax.distributed.initialize for multi-host trainer processes.
POS_SENTINEL = 2**30


class BlockAllocatorError(ValueError):
    """A ``free()`` that would corrupt the free list: out-of-range block id,
    double-free of an already-free block, or duplicate ids in one call.
    Raised BEFORE any mutation — a rejected free changes nothing — because
    the silent alternative is worse than a crash: a double-freed id gets
    handed out twice and two live slots then scatter into the same physical
    block."""


class BlockAllocator:
    """Host-side REFCOUNTED free-list over the physical block pool.

    The scheduler thread is the only allocator writer, but gauges
    (``/metrics``, gateway stats) read ``free_count`` from HTTP threads —
    hence the lock. Blocks are handed out lowest-id-first and returned to
    the head of the free list, so tests can assert deterministic reuse.

    Refcounts are the copy-on-write substrate: ``alloc`` hands blocks out
    at refcount 1, ``incref`` lets a second owner (another slot's block
    table, a prefix-cache entry) map the same physical block, and ``free``
    DECREMENTS — a block only returns to the free list when its last owner
    lets go. Every owner calls plain ``free`` on release, so the sharing is
    invisible to release paths. ``free()``/``incref()`` validate against
    the refcount table and raise BlockAllocatorError instead of admitting
    a corruption: a double-freed id would get handed out twice and two
    live slots would then scatter into the same physical block."""

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks))
        self._ref = [0] * num_blocks  # 0 = on the free list
        self._lock = threading.Lock()

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def refcount(self, block: int) -> int:
        """Owners of one block (0 = free) — tests and forensics."""
        with self._lock:
            return self._ref[int(block)]

    def alloc(self, n: int) -> Optional[List[int]]:
        """Reserve ``n`` blocks at refcount 1; None (and no change) when
        the pool can't cover the request — the caller keeps the request
        queued."""
        if n <= 0:
            return []
        with self._lock:
            if n > len(self._free):
                return None
            out, self._free = self._free[:n], self._free[n:]
            for b in out:
                self._ref[b] = 1
            return out

    def _validate(self, blocks: List[int], op: str) -> List[int]:
        ids = [int(b) for b in blocks]
        bad = [b for b in ids if not 0 <= b < self.num_blocks]
        if bad:
            raise BlockAllocatorError(
                f"{op} of out-of-range block id(s) {bad} "
                f"(pool has {self.num_blocks} blocks)")
        if len(set(ids)) != len(ids):
            dupes = sorted({b for b in ids if ids.count(b) > 1})
            raise BlockAllocatorError(
                f"{op} lists block id(s) {dupes} more than once")
        return ids

    def incref(self, blocks: List[int]):
        """Add one owner to each LIVE block (copy-on-write sharing: a new
        slot's table or a prefix-cache entry mapping blocks it did not
        allocate). Increffing a free block is the same corruption class as
        a double-free — rejected before any mutation."""
        if not blocks:
            return
        with self._lock:
            ids = self._validate(blocks, "incref()")
            dead = sorted(b for b in ids if self._ref[b] == 0)
            if dead:
                raise BlockAllocatorError(
                    f"incref() of free block id(s) {dead}: a shared "
                    "mapping must target live blocks")
            for b in ids:
                self._ref[b] += 1

    def free(self, blocks: List[int]):
        """Drop one owner per block; blocks whose last owner left return
        to the free list. Rejected (typed, pre-mutation) on out-of-range
        ids, duplicates in one call, and frees of already-free blocks."""
        if not blocks:
            return
        with self._lock:
            ids = self._validate(blocks, "free()")
            double = sorted(b for b in ids if self._ref[b] == 0)
            if double:
                raise BlockAllocatorError(
                    f"double-free of block id(s) {double}: already on the "
                    "free list")
            released = []
            for b in ids:
                self._ref[b] -= 1
                if self._ref[b] == 0:
                    released.append(b)
            if released:
                self._free = sorted(released) + self._free


def blocks_for_depth(depth: int, block_size: int, overshoot: int = 0,
                     cap_depth: Optional[int] = None) -> int:
    """Blocks a slot must reserve to hold ``depth`` tokens of KV plus
    ``overshoot`` scratch tokens — the admission reserve math.

    ``overshoot`` exists for speculative decoding: a verify-k forward writes
    up to ``k + 1`` tokens beyond the row's live cursor (the pending token
    plus k proposals), and while accepted tokens always land within the
    plain ``depth`` extent, reserving the overshoot keeps REJECTED-lane
    writes physical too — no verify distribution is ever computed over a
    dropped write, and the slot's blocks tell the whole story when
    debugging. ``cap_depth`` (normally ``max_seq_len``) bounds the reserve
    at the block-table width so overshoot can never demand more blocks than
    a table row can hold."""
    total = depth + max(0, overshoot)
    if cap_depth is not None:
        total = min(total, cap_depth)
    return -(-total // block_size)


def init_paged_cache(cfg, slots: int, num_blocks: int, block_size: int,
                     blocks_per_slot: int, dtype=jnp.bfloat16,
                     quantize: Optional[str] = None) -> Dict:
    """Block-pool KV cache. ``block_tables`` is ``[slots, blocks_per_slot]``
    int32 (-1 = unallocated); ``len`` is the per-slot linear write cursor;
    ``pos`` records each written token's rope position per (block, offset)."""
    L = cfg.num_layers
    shape = (L, num_blocks, block_size, cfg.num_kv_heads, cfg.head_dim)
    cache: Dict = {
        "len": jnp.zeros((slots,), jnp.int32),
        "pos": jnp.full((num_blocks, block_size), POS_SENTINEL, jnp.int32),
        "block_tables": jnp.full((slots, blocks_per_slot), -1, jnp.int32),
    }
    if quantize == "int8":
        cache["k"] = jnp.zeros(shape, jnp.int8)
        cache["v"] = jnp.zeros(shape, jnp.int8)
        cache["k_scale"] = jnp.zeros(shape[:-1], jnp.float32)
        cache["v_scale"] = jnp.zeros(shape[:-1], jnp.float32)
    elif quantize:
        raise ValueError(f"unsupported cache quantization {quantize!r}")
    else:
        cache["k"] = jnp.zeros(shape, dtype)
        cache["v"] = jnp.zeros(shape, dtype)
    return cache


def paged_view_width(cache: Dict) -> int:
    """Linear width of the gathered per-slot view (= dense-row equivalent)."""
    return cache["block_tables"].shape[1] * cache["k"].shape[2]


def _write_targets(tables: jnp.ndarray, lens: jnp.ndarray, T: int,
                   block_size: int, num_blocks: int):
    """Physical (block, offset) for the next ``T`` linear positions of each
    slot. Invalid targets (slot exhausted, table entry -1) get physical index
    ``num_blocks`` — out of bounds, so the scatter drops them."""
    idx = lens[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]  # [B, T]
    blk, off = idx // block_size, idx % block_size
    nbps = tables.shape[1]
    tbl = jnp.take_along_axis(tables, jnp.clip(blk, 0, nbps - 1), axis=1)
    phys = jnp.where((blk < nbps) & (tbl >= 0), tbl, num_blocks)
    return phys, off


def paged_linear_targets(tables: jnp.ndarray, lin: jnp.ndarray,
                         block_size: int, num_blocks: int,
                         valid: jnp.ndarray):
    """Physical (block, offset) for ARBITRARY linear positions ``lin``
    [B, N] — ``_write_targets`` generalized beyond a cursor-contiguous run
    (tree-verify window compaction moves non-contiguous window columns).
    Positions with ``valid`` False, past the table, or backed by no block
    get physical index ``num_blocks`` so scatters drop them."""
    blk, off = lin // block_size, lin % block_size
    nbps = tables.shape[1]
    tbl = jnp.take_along_axis(tables, jnp.clip(blk, 0, nbps - 1), axis=1)
    phys = jnp.where(valid & (blk >= 0) & (blk < nbps) & (tbl >= 0),
                     tbl, num_blocks)
    return phys, off


def _gather_tables(tables: jnp.ndarray) -> jnp.ndarray:
    """Table with -1 entries clamped to block 0 (gather must stay in
    bounds; the garbage it reads is masked via sentinel positions)."""
    return jnp.where(tables >= 0, tables, 0)


def paged_record_positions(cache: Dict, pos_update: jnp.ndarray,
                           gather: bool = True):
    """Scatter the new tokens' rope positions through the block tables and
    return ``(new_pos_pool, kv_positions [B, W])`` — the gathered linear
    position view attention's causal bias masks against. Lanes backed by no
    block read as POS_SENTINEL.

    ``gather=False`` (the Pallas kernel decode path) skips the gathered view
    entirely — the kernel masks against the pos POOL through the block table
    in place — and returns ``(new_pos_pool, None)``."""
    tables, lens, pool = cache["block_tables"], cache["len"], cache["pos"]
    num_blocks, block_size = pool.shape
    phys, off = _write_targets(tables, lens, pos_update.shape[1],
                               block_size, num_blocks)
    new_pool = pool.at[phys, off].set(pos_update)
    if not gather:
        return new_pool, None
    gathered = new_pool[_gather_tables(tables)]  # [B, nbps, bs]
    gathered = jnp.where((tables >= 0)[:, :, None], gathered, POS_SENTINEL)
    return new_pool, gathered.reshape(tables.shape[0], -1)


def paged_kv_write(ck, cv, cks, cvs, tables, lens, k_w, v_w, ks_w, vs_w):
    """Per-layer paged write WITHOUT the gathered read-back — the Pallas
    kernel decode path's half of ``paged_kv_update``: scatter the new
    tokens' K/V (and int8 scales) through the block tables and return the
    updated pools; attention then reads the blocks in place."""
    num_blocks, block_size = ck.shape[0], ck.shape[1]
    phys, off = _write_targets(tables, lens, k_w.shape[1],
                               block_size, num_blocks)
    ck = ck.at[phys, off].set(k_w)
    cv = cv.at[phys, off].set(v_w)
    if cks is not None:
        cks = cks.at[phys, off].set(ks_w)
        cvs = cvs.at[phys, off].set(vs_w)
    return ck, cv, cks, cvs


def paged_kv_update(ck, cv, cks, cvs, tables, lens, k_w, v_w, ks_w, vs_w):
    """Per-layer paged write + gathered read.

    ``ck``/``cv`` are one layer's pools ``[NB, bs, KV, d]`` (the layer scan
    peels the leading L axis); ``k_w``/``v_w`` the new tokens ``[B, T, KV,
    d]``. Returns updated pools plus the gathered ``[B, W, KV, d]`` views
    attention reads — element-identical to a dense row for every written
    lane, sentinel-masked elsewhere."""
    B = k_w.shape[0]
    ck, cv, cks, cvs = paged_kv_write(ck, cv, cks, cvs, tables, lens,
                                      k_w, v_w, ks_w, vs_w)
    tbl = _gather_tables(tables)
    k_all = ck[tbl].reshape(B, -1, ck.shape[-2], ck.shape[-1])
    v_all = cv[tbl].reshape(B, -1, cv.shape[-2], cv.shape[-1])
    ks_all = cks[tbl].reshape(B, -1, cks.shape[-1]) if cks is not None else None
    vs_all = cvs[tbl].reshape(B, -1, cvs.shape[-1]) if cvs is not None else None
    return ck, cv, cks, cvs, k_all, v_all, ks_all, vs_all


# --------------------------------------------------------- row import/export
def _row_targets(table_row: jnp.ndarray, width: int, block_size: int,
                 num_blocks: int):
    idx = jnp.arange(width, dtype=jnp.int32)
    blk, off = idx // block_size, idx % block_size
    nbps = table_row.shape[0]
    tbl = table_row[jnp.clip(blk, 0, nbps - 1)]
    phys = jnp.where((blk < nbps) & (tbl >= 0), tbl, num_blocks)
    return phys, off


def paged_insert_row(cache: Dict, slot, table_row: jnp.ndarray,
                     row_cache: Dict) -> Dict:
    """Scatter a dense single-row cache (a prefill/prefix-cache product,
    ``k [L, 1, W, KV, d]``) into the slot's blocks and install its table.
    Positions beyond the row's cursor are POS_SENTINEL in the row already,
    so writing the full width doubles as the block scrub. Linear positions
    past the slot's allocation are dropped (no block — nothing to strand)."""
    num_blocks, block_size = cache["pos"].shape
    W = row_cache["k"].shape[2]
    phys, off = _row_targets(table_row, W, block_size, num_blocks)
    out = dict(cache)
    out["block_tables"] = jax.lax.dynamic_update_slice(
        cache["block_tables"], table_row[None], (slot, 0))
    out["k"] = cache["k"].at[:, phys, off].set(row_cache["k"][:, 0])
    out["v"] = cache["v"].at[:, phys, off].set(row_cache["v"][:, 0])
    if "k_scale" in cache:
        out["k_scale"] = cache["k_scale"].at[:, phys, off].set(
            row_cache["k_scale"][:, 0])
        out["v_scale"] = cache["v_scale"].at[:, phys, off].set(
            row_cache["v_scale"][:, 0])
    out["pos"] = cache["pos"].at[phys, off].set(row_cache["pos"][0])
    return out


def row_trim(row: Dict, width: int) -> Dict:
    """Trim a dense single-row cache to its first ``width`` linear
    positions — the live prefix of a migrating session (serving/migration
    serializes only real KV, not the row's unwritten tail). Device-side
    slicing, so the host transfer that follows moves ``width`` columns
    instead of the full ``max_seq_len`` row. The inverse (sentinel-padding
    back to full width) lives in ``serving/migration.unpack_kv_row``."""
    width = min(width, row["k"].shape[2])
    out: Dict = {"len": row.get("len")}
    out["k"] = row["k"][:, :, :width]
    out["v"] = row["v"][:, :, :width]
    if "k_scale" in row:
        out["k_scale"] = row["k_scale"][:, :, :width]
        out["v_scale"] = row["v_scale"][:, :, :width]
    out["pos"] = row["pos"][:, :width]
    return out


def paged_copy_block(cache: Dict, src, dst, keep) -> Dict:
    """Copy one physical block (K/V pools, int8 scales, pos row) onto
    another — the copy-on-write primitive. Position lanes at offset >=
    ``keep`` are scrubbed to POS_SENTINEL in the destination, so copying a
    partially-written tail block never leaks the source's later tokens to
    the new owner's attention (decode only ever appends at the cursor, so
    this copy is the at-most-once COW event per shared tail block)."""
    out = dict(cache)
    block_size = cache["pos"].shape[1]
    for key in ("k", "v"):
        out[key] = cache[key].at[:, dst].set(cache[key][:, src])
    if "k_scale" in cache:
        out["k_scale"] = cache["k_scale"].at[:, dst].set(
            cache["k_scale"][:, src])
        out["v_scale"] = cache["v_scale"].at[:, dst].set(
            cache["v_scale"][:, src])
    row = jnp.where(jnp.arange(block_size, dtype=jnp.int32) < keep,
                    cache["pos"][src], POS_SENTINEL)
    out["pos"] = cache["pos"].at[dst].set(row)
    return out


def paged_extract_row(cache: Dict, slot, cursor, *,
                      width: Optional[int] = None) -> Dict:
    """Gather a slot's blocks back into a dense single-row cache (the
    prefix-cache / migration-wire storage format). The inverse of
    ``paged_insert_row``; ``cursor`` becomes the row's scalar write cursor
    so suffix extension picks up exactly where the prompt ended.

    ``width`` (static under jit) trims the gather to the first
    ``ceil(width / block_size)`` blocks — a short prefix then moves
    ``width`` columns of HBM instead of a full ``max_seq_len`` row, which
    is what the prefix-cache export and migration paths pay per session.
    Default None keeps the full-table gather (width = blocks_per_slot ×
    block_size = max_seq_len)."""
    nbps_total = cache["block_tables"].shape[1]
    block_size = cache["k"].shape[2]
    nbps = nbps_total if width is None else max(
        1, min(nbps_total, -(-int(width) // block_size)))
    table_row = jax.lax.dynamic_slice(
        cache["block_tables"], (slot, 0), (1, nbps))[0]
    tbl = _gather_tables(table_row)
    L = cache["k"].shape[0]
    kv, d = cache["k"].shape[-2], cache["k"].shape[-1]
    W = nbps * cache["k"].shape[2]
    row: Dict = {
        "k": cache["k"][:, tbl].reshape(L, 1, W, kv, d),
        "v": cache["v"][:, tbl].reshape(L, 1, W, kv, d),
        "len": jnp.asarray(cursor, jnp.int32),
    }
    if "k_scale" in cache:
        row["k_scale"] = cache["k_scale"][:, tbl].reshape(L, 1, W, kv)
        row["v_scale"] = cache["v_scale"][:, tbl].reshape(L, 1, W, kv)
    pos = cache["pos"][tbl]  # [nbps, bs]
    pos = jnp.where((table_row >= 0)[:, None], pos, POS_SENTINEL)
    row["pos"] = pos.reshape(1, W)
    return row
