"""Attention ops: XLA reference path + dispatch to Pallas flash / ring attention.

The reference delegates attention entirely to HF transformers CUDA kernels
(optionally flash-attn, reference cmd/tuning/parser.py:66-69). TPU-native design:
a plain einsum+softmax path that XLA fuses well (default), a Pallas flash kernel
for long sequences, and ring attention over a mesh axis for sequence parallelism
(SURVEY.md §5.7 stretch goal).

Shapes: q [B, T, H, d]; k, v [B, S, KV, d] with H = KV * G (GQA).
Bias is additive, broadcastable to [B, 1|H, T, S]; softmax runs in f32.

This module also owns the serving KV-cache interface the model writes and
reads through (``cache_positions_update`` / ``kv_cache_update``): a cache
dict with ``block_tables`` takes the paged block-pool path
(ops/paged_attention.py); otherwise the dense contiguous layouts
(scalar-cursor prefill rows, per-slot-cursor continuous batching). The int8
``kv_quant`` representation is shared by both.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from datatunerx_tpu.ops.paged_attention import (
    POS_SENTINEL,
    paged_kv_update,
    paged_kv_write,
    paged_linear_targets,
    paged_record_positions,
    paged_view_width,
)


def attention_allow(
    q_positions: jnp.ndarray,  # [B, T] absolute positions of queries
    kv_positions: jnp.ndarray,  # [B, S] absolute positions of keys
    kv_valid: jnp.ndarray | None = None,  # [B, S] bool — False for padding
    *,
    sliding_window: int | None = None,
    q_segment_ids: jnp.ndarray | None = None,  # [B, T] for packed sequences
    kv_segment_ids: jnp.ndarray | None = None,  # [B, S]
    window_mask: jnp.ndarray | None = None,  # [B, T, WN] bool — see below
    window_start: jnp.ndarray | None = None,  # [B] linear start of the window
) -> jnp.ndarray:
    """The boolean attendability tensor [B, T, S] behind the causal bias.

    ``window_mask``/``window_start`` carve a per-step WINDOW out of the KV
    lanes — the ``WN`` linear cache positions starting at ``window_start``
    (a multi-token verify/draft step's own writes). Inside the window a
    lane must pass the mask column AND the causal check (tree siblings
    share a rope position, so causality alone cannot separate branches —
    and the causal check still excludes unwritten sentinel lanes); outside
    it, plain causal masking applies unchanged. A lower-triangular mask
    reproduces the chain behavior exactly, so chain verify never sets one.

    Factored out of ``make_causal_bias`` so the Pallas multi-token kernel
    consumes the SAME boolean tensor the XLA oracle biases with — mask
    parity between the two paths holds by construction."""
    ok = kv_positions[:, None, :] <= q_positions[:, :, None]  # causal
    if sliding_window is not None:
        ok &= kv_positions[:, None, :] > q_positions[:, :, None] - sliding_window
    if kv_valid is not None:
        ok &= kv_valid[:, None, :]
    if q_segment_ids is not None and kv_segment_ids is not None:
        ok &= q_segment_ids[:, :, None] == kv_segment_ids[:, None, :]
    if window_mask is not None:
        B, T, WN = window_mask.shape
        S = kv_positions.shape[1]
        lane = jnp.arange(S, dtype=jnp.int32)[None, :]
        w = lane - window_start.astype(jnp.int32)[:, None]  # [B, S]
        inside = (w >= 0) & (w < WN)
        wc = jnp.clip(w, 0, WN - 1)
        allowed = jnp.take_along_axis(
            window_mask.astype(bool),
            jnp.broadcast_to(wc[:, None, :], (B, T, S)), axis=2)
        ok &= ~inside[:, None, :] | allowed
    return ok


def make_causal_bias(
    q_positions: jnp.ndarray,  # [B, T] absolute positions of queries
    kv_positions: jnp.ndarray,  # [B, S] absolute positions of keys
    kv_valid: jnp.ndarray | None = None,  # [B, S] bool — False for padding
    *,
    sliding_window: int | None = None,
    q_segment_ids: jnp.ndarray | None = None,  # [B, T] for packed sequences
    kv_segment_ids: jnp.ndarray | None = None,  # [B, S]
    window_mask: jnp.ndarray | None = None,  # [B, T, WN] branch/window mask
    window_start: jnp.ndarray | None = None,  # [B]
    dtype=jnp.float32,
) -> jnp.ndarray:
    """Additive bias [B, 1, T, S]: 0 where attendable, -inf-ish otherwise."""
    ok = attention_allow(
        q_positions, kv_positions, kv_valid,
        sliding_window=sliding_window, q_segment_ids=q_segment_ids,
        kv_segment_ids=kv_segment_ids, window_mask=window_mask,
        window_start=window_start)
    neg = jnp.asarray(jnp.finfo(dtype).min, dtype)
    return jnp.where(ok, jnp.zeros((), dtype), neg)[:, None, :, :]


def xla_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray,
) -> jnp.ndarray:
    """Reference attention: f32 softmax, GQA via reshape. Returns [B, T, H, d]."""
    B, T, H, d = q.shape
    S, KV = k.shape[1], k.shape[2]
    G = H // KV
    q = q.reshape(B, T, KV, G, d)
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = jnp.einsum("btkgd,bskd->bkgts", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    bias4 = bias.astype(jnp.float32)  # [B, 1|H, T, S]
    if bias4.shape[1] == 1:
        logits = logits + bias4[:, :, None, :, :]
    else:
        logits = logits + bias4.reshape(B, KV, G, T, S)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgts,bskd->btkgd", probs.astype(v.dtype), v)
    return out.reshape(B, T, H, d)


# ------------------------------------------------------- KV cache interface

def kv_quantize(x: jnp.ndarray):
    """[..., head_dim] → (int8 values, per-vector scale)."""
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / 127.0
    safe = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / safe[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def kv_dequantize(q: jnp.ndarray, scale: jnp.ndarray, dtype):
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def kv_cache_width(cache: dict) -> int:
    """Linear key width attention sees for one slot — the rope ``seq_len``
    (dynamic-NTK inflation keys off it, ops/rope.py)."""
    if "block_tables" in cache:
        return paged_view_width(cache)
    return cache["k"].shape[2]


def cache_positions_update(cache: dict, positions: jnp.ndarray,
                           attention_mask, gather: bool = True):
    """Record the new tokens' rope positions at each slot's write cursor.

    Returns ``(pos_state, kv_positions)``: the updated position state (dense
    [B, S] table, or the paged [NB, bs] pool) and the per-slot linear
    position view ``[B, W]`` the causal bias masks against. Pads
    (attention_mask 0) get POS_SENTINEL so they are masked everywhere.
    ``gather=False`` (paged kernel decode) skips the gathered view — the
    kernel masks against the pos pool in place — returning ``(pool, None)``."""
    pos_update = positions
    if attention_mask is not None:
        pos_update = jnp.where(attention_mask.astype(bool), positions,
                               POS_SENTINEL)
    if "block_tables" in cache:
        return paged_record_positions(cache, pos_update, gather=gather)
    B, T = positions.shape
    if cache["len"].ndim == 0:
        cache_pos = jax.lax.dynamic_update_slice(
            cache["pos"], pos_update, (0, cache["len"]))
    else:
        # per-slot cursors: scatter each row at its own depth (OOB writes
        # for exhausted slots are dropped by the default scatter mode)
        rows = jnp.arange(B, dtype=jnp.int32)[:, None]
        idx = cache["len"][:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        cache_pos = cache["pos"].at[rows, idx].set(pos_update)
    return cache_pos, cache_pos


def kv_cache_write_paged(cache: dict, ck, cv, cks, cvs, k, v):
    """Paged write WITHOUT the gathered read — the Pallas kernel decode
    path: quantize the new tokens exactly as ``kv_cache_update`` would,
    scatter them through the block tables, and return only the updated
    pool leaves; the kernel then reads the blocks in place."""
    if cks is not None:
        k_w, ks_w = kv_quantize(k)
        v_w, vs_w = kv_quantize(v)
    else:
        k_w, v_w = k.astype(ck.dtype), v.astype(cv.dtype)
        ks_w = vs_w = None
    return paged_kv_write(ck, cv, cks, cvs, cache["block_tables"],
                          cache["len"], k_w, v_w, ks_w, vs_w)


def kv_cache_update(cache: dict, ck, cv, cks, cvs, k, v):
    """One layer's cache write + full-width read.

    ``ck``/``cv`` (and int8 scale pools ``cks``/``cvs``) are the layer-peeled
    cache leaves the scan threads; ``k``/``v`` the new tokens' projections
    [B, T, KV, d]. Returns the updated leaves plus ``k_att``/``v_att`` — the
    [B, W, KV, d] views attention reads, dequantized when quantized."""
    if cks is not None:  # int8 cache: quantize new k/v on write
        k_w, ks_w = kv_quantize(k)
        v_w, vs_w = kv_quantize(v)
    else:
        k_w, v_w = k.astype(ck.dtype), v.astype(cv.dtype)
        ks_w = vs_w = None
    if "block_tables" in cache:
        ck, cv, cks, cvs, k_all, v_all, ks_all, vs_all = paged_kv_update(
            ck, cv, cks, cvs, cache["block_tables"], cache["len"],
            k_w, v_w, ks_w, vs_w)
        if cks is not None:
            return ck, cv, cks, cvs, \
                kv_dequantize(k_all, ks_all, k.dtype), \
                kv_dequantize(v_all, vs_all, v.dtype)
        return ck, cv, cks, cvs, k_all.astype(k.dtype), v_all.astype(v.dtype)
    B, T = k.shape[0], k.shape[1]
    start = cache["len"]
    if start.ndim == 0:
        ck = jax.lax.dynamic_update_slice(ck, k_w, (0, start, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v_w, (0, start, 0, 0))
        if cks is not None:
            cks = jax.lax.dynamic_update_slice(cks, ks_w, (0, start, 0))
            cvs = jax.lax.dynamic_update_slice(cvs, vs_w, (0, start, 0))
    else:
        rows = jnp.arange(B, dtype=jnp.int32)[:, None]
        idx = start[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        ck = ck.at[rows, idx].set(k_w)
        cv = cv.at[rows, idx].set(v_w)
        if cks is not None:
            cks = cks.at[rows, idx].set(ks_w)
            cvs = cvs.at[rows, idx].set(vs_w)
    if cks is not None:
        k_att = kv_dequantize(ck, cks, k.dtype)
        v_att = kv_dequantize(cv, cvs, v.dtype)
    else:
        k_att, v_att = ck.astype(k.dtype), cv.astype(v.dtype)
    return ck, cv, cks, cvs, k_att, v_att


def compact_window(cache: dict, participate: jnp.ndarray, len0: jnp.ndarray,
                   src_cols: jnp.ndarray, keep: jnp.ndarray,
                   pos0: jnp.ndarray, width: int) -> dict:
    """Collapse a tree-verify window back into chain-invariant lanes.

    A tree-verify forward writes ``width`` KV lanes per row starting at the
    pre-step cursor ``len0``: column 0 is the pending token, the rest the
    flattened tree nodes — SIBLINGS SHARING ROPE POSITIONS. After
    acceptance, only the chosen root-to-leaf path may survive: a stale
    sibling lane (rope pos ``p+1`` parked at linear lane ``len0+2``) would
    pass the plain causal check of any later read, which is exactly the
    corruption chain mode can never produce (its lane order == rope order).

    This moves the accepted path's K/V into the contiguous cursor lanes
    (``len0+1 … len0+keep``; lane ``len0`` already holds the pending token)
    and rewrites every window lane's position — ``pos0+i`` where kept,
    POS_SENTINEL otherwise — restoring the chain invariant the settle /
    export / migration paths assume. Works on both cache layouts.

    ``src_cols [B, D]`` is the window column of the path's depth-(i+1)
    node, ``keep [B]`` the accepted path length (≤ D), ``pos0 [B]`` the
    pending token's rope position. Rows with ``participate`` False are
    untouched (targets go out of bounds, the default scatter drop).
    ``len`` is NOT advanced here — the caller owns cursor math."""
    B, D = src_cols.shape
    depth_i = jnp.arange(1, D + 1, dtype=jnp.int32)[None, :]  # [1, D]
    move = participate[:, None] & (depth_i <= keep[:, None])
    src_lin = len0[:, None] + src_cols
    dst_lin = len0[:, None] + depth_i
    lane = jnp.arange(width, dtype=jnp.int32)[None, :]
    lane_lin = len0[:, None] + lane
    lane_valid = jnp.broadcast_to(participate[:, None], lane_lin.shape)
    vals = jnp.where(lane <= keep[:, None], pos0[:, None] + lane,
                     POS_SENTINEL)
    out = dict(cache)
    kv_keys = [k for k in ("k", "v", "k_scale", "v_scale") if k in cache]
    if "block_tables" in cache:
        tables = cache["block_tables"]
        num_blocks, block_size = cache["pos"].shape
        src_phys, src_off = paged_linear_targets(
            tables, src_lin, block_size, num_blocks, move)
        src_phys = jnp.minimum(src_phys, num_blocks - 1)  # gather in bounds
        dst_phys, dst_off = paged_linear_targets(
            tables, dst_lin, block_size, num_blocks, move)
        for key in kv_keys:
            leaf = cache[key]
            out[key] = leaf.at[:, dst_phys, dst_off].set(
                leaf[:, src_phys, src_off])
        lane_phys, lane_off = paged_linear_targets(
            tables, lane_lin, block_size, num_blocks, lane_valid)
        out["pos"] = cache["pos"].at[lane_phys, lane_off].set(vals)
        return out
    W = cache["pos"].shape[1]
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    src_idx = jnp.clip(src_lin, 0, W - 1)
    dst_idx = jnp.where(move, dst_lin, W)  # OOB = dropped
    for key in kv_keys:
        leaf = cache[key]
        out[key] = leaf.at[:, rows, dst_idx].set(leaf[:, rows, src_idx])
    lane_idx = jnp.where(lane_valid, lane_lin, W)
    out["pos"] = cache["pos"].at[rows, lane_idx].set(vals)
    return out


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray,
    *,
    impl: str = "xla",
    segment_ids: jnp.ndarray | None = None,
) -> jnp.ndarray:
    if impl == "xla":
        return xla_attention(q, k, v, bias)
    if impl == "flash":
        from datatunerx_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, bias, segment_ids=segment_ids)
    if impl == "ring":
        from datatunerx_tpu.ops.ring_attention import (
            get_ring_context,
            ring_attention_sharded,
        )

        mesh, axis, batch_axes = get_ring_context()
        if mesh is None or mesh.shape.get(axis, 1) == 1:
            # no sequence-parallel axis active — plain attention is exact
            return xla_attention(q, k, v, bias)
        return ring_attention_sharded(q, k, v, mesh, axis_name=axis,
                                      batch_axes=batch_axes)
    raise ValueError(f"unknown attention impl {impl!r}")
