"""Attention ops: XLA reference path + dispatch to Pallas flash / ring attention.

The reference delegates attention entirely to HF transformers CUDA kernels
(optionally flash-attn, reference cmd/tuning/parser.py:66-69). TPU-native design:
a plain einsum+softmax path that XLA fuses well (default), a Pallas flash kernel
for long sequences, and ring attention over a mesh axis for sequence parallelism
(SURVEY.md §5.7 stretch goal).

Shapes: q [B, T, H, d]; k, v [B, S, KV, d] with H = KV * G (GQA).
Bias is additive, broadcastable to [B, 1|H, T, S]; softmax runs in f32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def make_causal_bias(
    q_positions: jnp.ndarray,  # [B, T] absolute positions of queries
    kv_positions: jnp.ndarray,  # [B, S] absolute positions of keys
    kv_valid: jnp.ndarray | None = None,  # [B, S] bool — False for padding
    *,
    sliding_window: int | None = None,
    q_segment_ids: jnp.ndarray | None = None,  # [B, T] for packed sequences
    kv_segment_ids: jnp.ndarray | None = None,  # [B, S]
    dtype=jnp.float32,
) -> jnp.ndarray:
    """Additive bias [B, 1, T, S]: 0 where attendable, -inf-ish otherwise."""
    ok = kv_positions[:, None, :] <= q_positions[:, :, None]  # causal
    if sliding_window is not None:
        ok &= kv_positions[:, None, :] > q_positions[:, :, None] - sliding_window
    if kv_valid is not None:
        ok &= kv_valid[:, None, :]
    if q_segment_ids is not None and kv_segment_ids is not None:
        ok &= q_segment_ids[:, :, None] == kv_segment_ids[:, None, :]
    neg = jnp.asarray(jnp.finfo(dtype).min, dtype)
    return jnp.where(ok, jnp.zeros((), dtype), neg)[:, None, :, :]


def xla_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray,
) -> jnp.ndarray:
    """Reference attention: f32 softmax, GQA via reshape. Returns [B, T, H, d]."""
    B, T, H, d = q.shape
    S, KV = k.shape[1], k.shape[2]
    G = H // KV
    q = q.reshape(B, T, KV, G, d)
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = jnp.einsum("btkgd,bskd->bkgts", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    bias4 = bias.astype(jnp.float32)  # [B, 1|H, T, S]
    if bias4.shape[1] == 1:
        logits = logits + bias4[:, :, None, :, :]
    else:
        logits = logits + bias4.reshape(B, KV, G, T, S)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgts,bskd->btkgd", probs.astype(v.dtype), v)
    return out.reshape(B, T, H, d)


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray,
    *,
    impl: str = "xla",
    segment_ids: jnp.ndarray | None = None,
) -> jnp.ndarray:
    if impl == "xla":
        return xla_attention(q, k, v, bias)
    if impl == "flash":
        from datatunerx_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, bias, segment_ids=segment_ids)
    if impl == "ring":
        from datatunerx_tpu.ops.ring_attention import (
            get_ring_context,
            ring_attention_sharded,
        )

        mesh, axis, batch_axes = get_ring_context()
        if mesh is None or mesh.shape.get(axis, 1) == 1:
            # no sequence-parallel axis active — plain attention is exact
            return xla_attention(q, k, v, bias)
        return ring_attention_sharded(q, k, v, mesh, axis_name=axis,
                                      batch_axes=batch_axes)
    raise ValueError(f"unknown attention impl {impl!r}")
