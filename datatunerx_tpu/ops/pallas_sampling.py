"""Fused on-chip sampling epilogue for the decode fast path.

Every decoded token used to pay a full-vocab sampling round-trip after
unembed: ``_sample_jit`` argsorts the whole ``[S, vocab]`` logits row,
softmaxes, cumsums, and draws with ``jax.random.categorical`` — even for
greedy rows, and even though only ONE token id per row leaves the step.
This module consumes the unembed output where it lives and emits just the
``[S]`` token ids, as three static per-batch modes so mixed batches never
materialize the ``[S, vocab]`` distribution on host:

  greedy — plain argmax (one max pass, no exp/sort/cumsum at all).
  simple — temperature sampling, ``top_p == 1`` for every sampled row:
           inverse-CDF over ``softmax(logits / max(t, 1e-6))`` via an
           online max pass + normalizer pass + CDF-crossing pass. Exactly
           the distribution ``sampling_probs(..., top_p=1)`` describes, so
           the speculative rejection rule's exactness is untouched.
  topp   — the ``exact_topp`` nucleus path. Needs a full-vocab sort, which
           Mosaic has no primitive for, so this mode always runs the XLA
           path below (sorted-space inverse-CDF) — still avoiding the
           host round-trip, but not the sort.

Two implementations share one tile walk:

  impl="kernel" — a Pallas kernel (grid ``(S, phases, vocab-tiles)``,
      per-row SMEM carries) for greedy/simple. Engaged on real TPU
      backends; interpret mode emulates it for CPU tests.
  impl="xla"    — a blocked XLA twin that mirrors the kernel's tile walk
      op-for-op (same tile width, same sequential carry adds, same
      first-max-wins / first-crossing tie rules). It is the PARITY ORACLE
      (PR 13 pattern): greedy tokens agree with the kernel bitwise by
      construction (max/compare are order-exact), and sampled tokens agree
      under a fixed seed because both sides consume the same precomputed
      per-row uniforms over the identical tile schedule — asserted by
      tests/test_pallas_sampling.py. It is also a genuine CPU win over
      ``_sample_jit``: no full-vocab argsort per decoded token.

The residual/acceptance math in ``serving/speculative.py`` keeps its full
device-resident ``q = sampling_probs(...)`` distributions (a top-k
approximation would break the exactness guarantee); what this module
removes is the per-token sort + host-visible ``[S, vocab]`` epilogue.

``DTX_SAMPLING_EPILOGUE_KERNEL=1`` forces impl="kernel" (interpret off
TPU), ``=0`` forces impl="xla"; unset defers to the backend — the same
contract ``DTX_PALLAS_INTERPRET`` gives the attention kernels.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from datatunerx_tpu.ops._pallas import interpret_default, pick_block_n

NEG_INF = -1e30
_BLOCK_CAP = 512

MODES = ("greedy", "simple", "topp")


def _interpret() -> bool:
    return interpret_default()


def default_impl() -> str:
    """Resolve the kernel/XLA split for this process: the Pallas kernel on
    real TPU backends, the blocked-XLA twin elsewhere.
    ``DTX_SAMPLING_EPILOGUE_KERNEL`` overrides (1 → kernel, 0 → xla) so
    tests can pin either side."""
    env = (os.environ.get("DTX_SAMPLING_EPILOGUE_KERNEL") or "").strip()
    if env:
        return "xla" if env.lower() in ("0", "false", "no") else "kernel"
    return "kernel" if jax.default_backend() == "tpu" else "xla"


def _prep(logits, temps, *, mode):
    """Shared pre-scale + lane-pad: both impls consume the SAME padded
    array, so scaling can never diverge between them. Padding is NEG_INF
    *after* scaling — dead lanes lose every argmax and contribute
    ``exp(NEG_INF - m) == 0`` to the normalizer and CDF."""
    x = logits.astype(jnp.float32)
    if mode != "greedy":
        x = x / jnp.maximum(temps, 1e-6).astype(jnp.float32)[:, None]
    v = x.shape[-1]
    vp = -(-v // 128) * 128
    if vp != v:
        x = jnp.pad(x, ((0, 0), (0, vp - v)), constant_values=NEG_INF)
    return x, pick_block_n(vp, _BLOCK_CAP)


# --------------------------------------------------------------- kernel

def _sample_kernel(temps_ref, us_ref, x_ref, tok_ref, fbuf, ibuf, *,
                   bn, nt, greedy):
    """One (row, phase, tile) step. SMEM carries per row:
    fbuf = [running max m, normalizer Z, CDF cursor c]
    ibuf = [argmax, sampled token, crossing-found flag]
    Phase 0 finds m/argmax; phase 1 accumulates Z = sum exp(x - m);
    phase 2 finds the first index whose running cumsum crosses u·Z.
    Greedy mode runs phase 0 only (the wrapper shrinks the grid)."""
    i = pl.program_id(0)
    p = pl.program_id(1)
    t = pl.program_id(2)
    tile = x_ref[...]  # (1, bn) f32
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)

    @pl.when((p == 0) & (t == 0))
    def _init_max():
        fbuf[0] = NEG_INF
        ibuf[0] = 0

    @pl.when(p == 0)
    def _phase_max():
        tmax = jnp.max(tile)
        # first-max-wins inside the tile (min index among maxima) plus a
        # strict > across tiles == jnp.argmax's first-occurrence rule
        targ = jnp.min(jnp.where(tile == tmax, lane, bn))
        better = tmax > fbuf[0]

        @pl.when(better)
        def _():
            fbuf[0] = tmax
            ibuf[0] = t * bn + targ

    if greedy:
        @pl.when((p == 0) & (t == nt - 1))
        def _emit_greedy():
            tok_ref[0, 0] = ibuf[0]
        return

    @pl.when((p == 1) & (t == 0))
    def _init_z():
        fbuf[1] = 0.0

    @pl.when(p == 1)
    def _phase_z():
        fbuf[1] = fbuf[1] + jnp.sum(jnp.exp(tile - fbuf[0]))

    @pl.when((p == 2) & (t == 0))
    def _init_cdf():
        fbuf[2] = 0.0
        ibuf[1] = 0
        ibuf[2] = 0

    @pl.when(p == 2)
    def _phase_cdf():
        e = jnp.exp(tile - fbuf[0])
        cum = fbuf[2] + jnp.cumsum(e, axis=1)
        thresh = us_ref[i] * fbuf[1]
        hit = cum > thresh
        first = jnp.min(jnp.where(hit, lane, bn))
        take = (first < bn) & (ibuf[2] == 0)

        @pl.when(take)
        def _():
            ibuf[1] = t * bn + first
            ibuf[2] = 1
        fbuf[2] = fbuf[2] + jnp.sum(e)

        @pl.when(t == nt - 1)
        def _emit():
            # no crossing (u·Z at/after the float tail) falls back to the
            # argmax; rows with temp <= 0 are greedy regardless of draw
            sampled = jnp.where(ibuf[2] == 1, ibuf[1], ibuf[0])
            tok_ref[0, 0] = jnp.where(temps_ref[i] <= 0.0, ibuf[0], sampled)


def _kernel_sample(x, temps, us, *, bn, greedy, interpret):
    s, vp = x.shape
    nt = vp // bn
    phases = 1 if greedy else 3
    out = pl.pallas_call(
        functools.partial(_sample_kernel, bn=bn, nt=nt, greedy=greedy),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(s, phases, nt),
            in_specs=[pl.BlockSpec((1, bn), lambda i, p, t, *_: (i, t))],
            out_specs=pl.BlockSpec(
                (1, 1), lambda i, p, t, *_: (i, 0),
                memory_space=pltpu.SMEM),
            scratch_shapes=[
                pltpu.SMEM((4,), jnp.float32),
                pltpu.SMEM((4,), jnp.int32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((s, 1), jnp.int32),
        interpret=_interpret() if interpret is None else interpret,
    )(temps.astype(jnp.float32), us.astype(jnp.float32), x)
    return out[:, 0]


# ----------------------------------------------------------- XLA oracle

def _xla_sample(x, temps, us, *, bn, greedy):
    """Blocked XLA twin: the kernel's tile walk verbatim (python loop over
    the same bn-wide tiles, sequential carry adds, identical tie rules) —
    the parity oracle AND the CPU fast path."""
    s, vp = x.shape
    nt = vp // bn
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)
    m = jnp.full((s,), NEG_INF, jnp.float32)
    idx = jnp.zeros((s,), jnp.int32)
    for t in range(nt):
        tile = x[:, t * bn:(t + 1) * bn]
        tmax = jnp.max(tile, axis=1)
        targ = jnp.min(jnp.where(tile == tmax[:, None], lane, bn), axis=1)
        better = tmax > m
        idx = jnp.where(better, t * bn + targ, idx)
        m = jnp.where(better, tmax, m)
    if greedy:
        return idx
    z = jnp.zeros((s,), jnp.float32)
    for t in range(nt):
        tile = x[:, t * bn:(t + 1) * bn]
        z = z + jnp.sum(jnp.exp(tile - m[:, None]), axis=1)
    thresh = us.astype(jnp.float32) * z
    c = jnp.zeros((s,), jnp.float32)
    token = jnp.zeros((s,), jnp.int32)
    found = jnp.zeros((s,), bool)
    for t in range(nt):
        tile = x[:, t * bn:(t + 1) * bn]
        e = jnp.exp(tile - m[:, None])
        cum = c[:, None] + jnp.cumsum(e, axis=1)
        hit = cum > thresh[:, None]
        first = jnp.min(jnp.where(hit, lane, bn), axis=1)
        got = first < bn
        take = got & ~found
        token = jnp.where(take, t * bn + first, token)
        found = found | got
        c = c + jnp.sum(e, axis=1)
    sampled = jnp.where(found, token, idx)
    return jnp.where(temps.astype(jnp.float32) <= 0.0, idx, sampled)


def _topp_sample(logits, temps, top_ps, us):
    """The exact_topp nucleus path (speculative.sampling_probs semantics):
    sorted-space inverse-CDF over the truncated distribution. XLA-only —
    there is no Mosaic full-vocab sort — but still epilogue-shaped: one
    token id per row leaves, never the [S, vocab] probs."""
    temps = temps.astype(jnp.float32)
    scaled = logits.astype(jnp.float32) / jnp.maximum(temps, 1e-6)[:, None]
    order = jnp.argsort(scaled, axis=-1)[:, ::-1]
    svals = jnp.take_along_axis(scaled, order, axis=-1)
    probs = jax.nn.softmax(svals, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cut = (cum - probs > top_ps.astype(jnp.float32)[:, None]) \
        & (top_ps.astype(jnp.float32)[:, None] < 1.0)
    probs = jnp.where(cut, 0.0, probs)
    total = jnp.sum(probs, axis=-1)
    cdf = jnp.cumsum(probs, axis=-1)
    hit = cdf > (us.astype(jnp.float32) * total)[:, None]
    # all-False can only mean the float tail; argmax(False row) = 0 falls
    # back to the sorted-top token, which is always in the nucleus
    first = jnp.argmax(hit, axis=-1)
    tok = jnp.take_along_axis(order, first[:, None], axis=-1)[:, 0]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy, tok.astype(jnp.int32))


# ------------------------------------------------------------------ API

def fused_sample(logits, temps, top_ps, keys, *, mode, impl="xla",
                 interpret=None):
    """Sample one token per row from ``logits [S, V]``. ``mode`` is the
    static per-batch mode ("greedy" | "simple" | "topp"); ``keys`` are
    per-row PRNG keys ``[S, 2]`` (ignored — may be None — for greedy).
    Returns token ids ``[S] int32``. ``impl`` picks kernel vs the blocked
    XLA twin for greedy/simple; topp always takes the XLA nucleus path."""
    if mode not in MODES:
        raise ValueError(f"unknown sampling mode {mode!r} (want {MODES})")
    temps = jnp.asarray(temps)
    if mode == "greedy":
        x, bn = _prep(logits, temps, mode=mode)
        if impl == "kernel":
            us = jnp.zeros((logits.shape[0],), jnp.float32)
            return _kernel_sample(x, temps, us, bn=bn, greedy=True,
                                  interpret=interpret)
        return _xla_sample(x, temps, None, bn=bn, greedy=True)
    us = jax.vmap(lambda k: jax.random.uniform(k, ()))(keys)
    if mode == "topp":
        return _topp_sample(logits, temps, jnp.asarray(top_ps), us)
    x, bn = _prep(logits, temps, mode=mode)
    if impl == "kernel":
        return _kernel_sample(x, temps, us, bn=bn, greedy=False,
                              interpret=interpret)
    return _xla_sample(x, temps, us, bn=bn, greedy=False)


def sample_rows(logits, temps, top_ps, rng, *, mode, impl="xla",
                interpret=None):
    """Drop-in for the ``vmap(split) + vmap(_sample_jit)`` pair: splits
    each row's key exactly like the legacy path (slot 0 kept, slot 1
    consumed) so the per-slot PRNG stream — the one the KV-migration
    payload carries — evolves identically, then samples via the epilogue.
    Returns ``(tokens [S] int32, new_rng [S, 2])``."""
    split = jax.vmap(jax.random.split)(rng)
    new_rng, sub = split[:, 0], split[:, 1]
    toks = fused_sample(logits, temps, top_ps, sub, mode=mode, impl=impl,
                        interpret=interpret)
    return toks, new_rng
