"""Pallas TPU kernels: fused quantized matmuls (int8 w8a16, nf4 QLoRA).

The bitsandbytes replacement's hot path (SURVEY.md §2.4, §7.4#2): the XLA
reference implementations live in ops/quant.py; these kernels fuse
unpack → codebook → scale → MXU dot per tile, so the dequantized weights never
round-trip through HBM. Correctness is pinned to the XLA path in
tests/test_quant.py (interpret mode on CPU; compiled on TPU).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from datatunerx_tpu.ops.quant import NF4_CODE


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad_rows(x2d: jnp.ndarray, bm: int) -> Tuple[jnp.ndarray, int]:
    m = x2d.shape[0]
    pad = (-m) % bm
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    return x2d, m


# ----------------------------------------------------------------- int8

def _int8_kernel(x_ref, q_ref, s_ref, o_ref):
    acc = jnp.dot(
        x_ref[:], q_ref[:].astype(x_ref.dtype),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = (acc * s_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def pallas_matmul_int8(
    x: jnp.ndarray, q: jnp.ndarray, scale: jnp.ndarray,
    block_m: int = 256, block_n: int = 256,
) -> jnp.ndarray:
    """x: [..., K] @ q: int8 [K, N] * scale [N] → [..., N]."""
    *lead, K = x.shape
    N = q.shape[1]
    x2d = x.reshape(-1, K)
    x2d, m_real = _pad_rows(x2d, block_m)
    M = x2d.shape[0]
    bn = min(block_n, N)
    assert N % bn == 0, (N, bn)

    out = pl.pallas_call(
        _int8_kernel,
        grid=(M // block_m, N // bn),
        in_specs=[
            pl.BlockSpec((block_m, K), lambda i, j: (i, 0)),
            pl.BlockSpec((K, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        interpret=_interpret(),
    )(x2d, q, scale.reshape(1, N))
    return out[:m_real].reshape(*lead, N)


# ------------------------------------------------------------------ nf4

def _nf4_kernel(x_ref, packed_ref, scales_ref, code_ref, o_ref, *, block_size: int):
    # packed_ref: [bn, K // block, block // 2] uint8 (channel-major blocks)
    # scales_ref: [bn, K // block] f32; code_ref: [1, 16] nf4 codebook
    packed = packed_ref[:]
    bn, nb, half = packed.shape
    lo = (packed & 0x0F).astype(jnp.int32)
    hi = (packed >> 4).astype(jnp.int32)
    idx = jnp.stack([lo, hi], axis=-1).reshape(bn, nb, block_size)
    code = code_ref[0]
    w = code[idx] * scales_ref[:][..., None]  # [bn, nb, block]
    w = w.reshape(bn, nb * block_size)  # [bn, K]
    acc = jax.lax.dot_general(
        x_ref[:], w.astype(x_ref.dtype),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = acc.astype(o_ref.dtype)


def pallas_matmul_nf4(
    x: jnp.ndarray, qw: Dict[str, jnp.ndarray], shape: Tuple[int, int],
    block_m: int = 256, block_n: int = 256, block_size: int = 64,
) -> jnp.ndarray:
    """x: [..., K] @ nf4-packed weights (ops/quant.py layout) → [..., N]."""
    K, N = shape
    *lead, K2 = x.shape
    assert K2 == K, (K2, K)
    nb_per_channel = K // block_size
    packed = qw["packed"].reshape(N, nb_per_channel, block_size // 2)
    scales = (qw["scale_q"].astype(jnp.float32) * qw["meta"][0]).reshape(
        N, nb_per_channel
    )

    x2d = x.reshape(-1, K)
    x2d, m_real = _pad_rows(x2d, block_m)
    M = x2d.shape[0]
    bn = min(block_n, N)
    assert N % bn == 0, (N, bn)

    out = pl.pallas_call(
        functools.partial(_nf4_kernel, block_size=block_size),
        grid=(M // block_m, N // bn),
        in_specs=[
            pl.BlockSpec((block_m, K), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, nb_per_channel, block_size // 2),
                         lambda i, j: (j, 0, 0)),
            pl.BlockSpec((bn, nb_per_channel), lambda i, j: (j, 0)),
            pl.BlockSpec((1, 16), lambda i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_m, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        interpret=_interpret(),
    )(x2d, packed, scales, jnp.asarray(NF4_CODE).reshape(1, 16))
    return out[:m_real].reshape(*lead, N)
