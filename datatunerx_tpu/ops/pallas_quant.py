"""Pallas TPU kernels: fused quantized matmuls (int8 w8a16, nf4 QLoRA).

The bitsandbytes replacement's hot path (SURVEY.md §2.4, §7.4#2): the XLA
reference implementations live in ops/quant.py; these kernels fuse
unpack → codebook → scale → MXU dot per tile, so the dequantized weights never
round-trip through HBM. Correctness is pinned to the XLA path in
tests/test_quant.py (interpret mode on CPU; compiled on TPU).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from datatunerx_tpu.ops.quant import NF4_CODE


def _interpret() -> bool:
    from datatunerx_tpu.ops._pallas import interpret_default

    return interpret_default()


def _pad_rows(x2d: jnp.ndarray, bm: int) -> Tuple[jnp.ndarray, int]:
    m = x2d.shape[0]
    pad = (-m) % bm
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    return x2d, m


# ----------------------------------------------------------------- int8

def _int8_kernel(x_ref, q_ref, s_ref, o_ref):
    acc = jnp.dot(
        x_ref[:], q_ref[:].astype(x_ref.dtype),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = (acc * s_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def pallas_matmul_int8(x: jnp.ndarray, q: jnp.ndarray, scale: jnp.ndarray,
                       block_m: int = 256, block_n: int = 256) -> jnp.ndarray:
    """Differentiable wrapper: forward rides the fused kernel; backward is
    dx = (g·scale) @ qᵀ through XLA (q/scale are a frozen quantized base —
    QLoRA never needs their gradients; pallas_call has no jvp rule, so
    without this the TRAINING path couldn't use the kernel at all)."""
    return _int8_mm((block_m, block_n), x, q, scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _int8_mm(blocks, x, q, scale):
    return _pallas_matmul_int8_impl(x, q, scale, *blocks)


def _int8_fwd(blocks, x, q, scale):
    return _pallas_matmul_int8_impl(x, q, scale, *blocks), (q, scale)


def _int8_bwd(blocks, res, g):
    q, scale = res
    gs = g.astype(jnp.float32) * scale.astype(jnp.float32)  # [..., N] * [N]
    dx = jnp.einsum("...n,kn->...k", gs.astype(g.dtype),
                    q.astype(g.dtype),
                    preferred_element_type=jnp.float32).astype(g.dtype)
    return (dx, np.zeros(q.shape, jax.dtypes.float0), jnp.zeros_like(scale))


_int8_mm.defvjp(_int8_fwd, _int8_bwd)


def _pallas_matmul_int8_impl(
    x: jnp.ndarray, q: jnp.ndarray, scale: jnp.ndarray,
    block_m: int = 256, block_n: int = 256,
) -> jnp.ndarray:
    """x: [..., K] @ q: int8 [K, N] * scale [N] → [..., N]."""
    *lead, K = x.shape
    N = q.shape[1]
    x2d = x.reshape(-1, K)
    x2d, m_real = _pad_rows(x2d, block_m)
    M = x2d.shape[0]
    from datatunerx_tpu.ops._pallas import pick_block_n

    bn = pick_block_n(N, block_n)

    out = pl.pallas_call(
        _int8_kernel,
        grid=(M // block_m, N // bn),
        in_specs=[
            pl.BlockSpec((block_m, K), lambda i, j: (i, 0)),
            pl.BlockSpec((K, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        interpret=_interpret(),
    )(x2d, q, scale.reshape(1, N))
    return out[:m_real].reshape(*lead, N)


# ------------------------------------------------------------------ nf4

def _nf4_kernel(x_ref, packed_ref, scales_ref, o_ref, w_vmem, acc_ref,
                *, block_size: int, nk: int):
    # One K-chunk of ck = nb·block weights per grid step (chunk-major inputs:
    # x_ref [1, bm, ck], packed_ref [1, bn, nb, block/2] planar nibbles,
    # scales_ref [1, bn, nb]).
    #
    # Mosaic has no >2D gather and no sublane→lane shape casts, so the unpack
    # never materializes [bn, nb, block]: each block is dequantized in 2D
    # ([bn, block/2] per nibble plane, 16-term select-sum codebook) and stored
    # into its static 64-lane slice of a [bn, ck] VMEM scratch; the MXU then
    # runs one full-depth dot per chunk, accumulating across the K grid dim.
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    packed = packed_ref[0]
    bn, nb, half = packed.shape
    code = np.asarray(NF4_CODE, np.float32)
    for b in range(nb):
        # widen before the shift: Mosaic can't legalize shrui on i8 vectors
        pb = packed[:, b, :].astype(jnp.int32)            # [bn, block/2]
        lo = pb & 0x0F
        hi = (pb >> 4) & 0x0F
        idx = jnp.concatenate([lo, hi], axis=-1)          # [bn, block] planar
        w = jnp.zeros(idx.shape, jnp.float32)
        for c, val in enumerate(code):
            w = jnp.where(idx == c, jnp.float32(val), w)
        w_vmem[:, b * block_size:(b + 1) * block_size] = (
            w * scales_ref[0][:, b:b + 1])

    acc_ref[:] += jax.lax.dot_general(
        x_ref[0], w_vmem[:].astype(x_ref.dtype),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(kk == nk - 1)
    def _finish():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _pick_chunk(nb_total: int, block_size: int, cap_nb: int = 16) -> int:
    """Largest divisor of nb_total ≤ cap_nb (chunk = that many nf4 blocks).

    Any divisor is Mosaic-legal: the chunk axis is hoisted to a leading array
    dim on the host, so every BlockSpec's last-two dims EQUAL their array
    dims regardless of nb (no 8/128-multiple requirement to satisfy)."""
    best = 1
    for d in range(1, cap_nb + 1):
        if nb_total % d == 0:
            best = d
    return best * block_size


def pallas_matmul_nf4(x: jnp.ndarray, qw: Dict[str, jnp.ndarray],
                      shape: Tuple[int, int], block_m: int = 256,
                      block_n: int = 256) -> jnp.ndarray:
    """Differentiable wrapper (see pallas_matmul_int8): forward = fused
    kernel, backward = dx = g @ Wᵀ with W dequantized by the XLA reference
    path (frozen base ⇒ no weight grads)."""
    return _nf4_mm((shape, block_m, block_n), x,
                   qw["packed"], qw["scale_q"], qw["meta"])


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _nf4_mm(static, x, packed, scale_q, meta):
    shape, block_m, block_n = static
    return _pallas_matmul_nf4_impl(
        x, {"packed": packed, "scale_q": scale_q, "meta": meta}, shape,
        block_m=block_m, block_n=block_n)


def _nf4_mm_fwd(static, x, packed, scale_q, meta):
    shape, block_m, block_n = static
    out = _pallas_matmul_nf4_impl(
        x, {"packed": packed, "scale_q": scale_q, "meta": meta}, shape,
        block_m=block_m, block_n=block_n)
    return out, (packed, scale_q, meta)


def _nf4_mm_bwd(static, res, g):
    packed, scale_q, meta = res
    shape, _, _ = static
    # dx = g @ Wᵀ through the fused transposed kernel: the weights stay
    # packed in HBM (0.5 byte/weight read, dequant per-tile in VMEM). The
    # round-2 XLA fallback here materialized the full [K, N] bf16 dequant
    # per matmul per step — at 7B with remat that is ~3 × 13.5 GB of HBM
    # writes per step and the reason the nf4 path sat at 14.6% MFU.
    dx = _pallas_matmul_nf4_t_impl(
        g, {"packed": packed, "scale_q": scale_q, "meta": meta}, shape)
    return (dx,
            np.zeros(packed.shape, jax.dtypes.float0),
            np.zeros(scale_q.shape, jax.dtypes.float0),
            jnp.zeros_like(meta))


_nf4_mm.defvjp(_nf4_mm_fwd, _nf4_mm_bwd)


def _nf4_t_kernel(g_ref, packed_ref, scales_ref, o_ref, w_vmem, acc_ref,
                  *, block_size: int, nn: int):
    # Transposed product dx[M, K] = g[M, N] @ V[N, K] (V = Wᵀ): contraction
    # runs over the N grid dim; each step dequantizes an [bn, ck] weight tile
    # (bn output channels × ck of their K-contiguous weights — the SAME
    # per-block 2D unpack as the forward kernel) and feeds the MXU with
    # g_tile[bm, bn] @ w[bn, ck], accumulating over nj.
    nj = pl.program_id(2)

    @pl.when(nj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    packed = packed_ref[0]
    bn, nb, half = packed.shape
    code = np.asarray(NF4_CODE, np.float32)
    for b in range(nb):
        pb = packed[:, b, :].astype(jnp.int32)            # [bn, block/2]
        lo = pb & 0x0F
        hi = (pb >> 4) & 0x0F
        idx = jnp.concatenate([lo, hi], axis=-1)          # [bn, block] planar
        w = jnp.zeros(idx.shape, jnp.float32)
        for c, val in enumerate(code):
            w = jnp.where(idx == c, jnp.float32(val), w)
        w_vmem[:, b * block_size:(b + 1) * block_size] = (
            w * scales_ref[0][:, b:b + 1])

    acc_ref[:] += jax.lax.dot_general(
        g_ref[0], w_vmem[:].astype(g_ref.dtype),
        (((1,), (0,)), ((), ())),                         # contract bn
        preferred_element_type=jnp.float32,
    )

    @pl.when(nj == nn - 1)
    def _finish():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _pallas_matmul_nf4_t_impl(
    g: jnp.ndarray, qw: Dict[str, jnp.ndarray], shape: Tuple[int, int],
    block_m: int = 256, block_n: int = 256, block_size: int = 64,
) -> jnp.ndarray:
    """g: [..., N] @ dequant(packed)ᵀ → [..., K] (the QLoRA dx product).

    Reuses the forward layout as-is: packed rows are output channels n with
    their K weights contiguous, which for the transposed product is exactly
    V[N, K] row-major — so the only difference from the forward kernel is
    which operand axis the grid contracts over."""
    K, N = shape
    *lead, N2 = g.shape
    assert N2 == N, (N2, N)
    nb_per_channel = K // block_size
    ck = _pick_chunk(nb_per_channel, block_size)
    nb_chunk = ck // block_size
    nk = K // ck
    half = block_size // 2

    g2d = g.reshape(-1, N)
    g2d, m_real = _pad_rows(g2d, block_m)
    M = g2d.shape[0]
    from datatunerx_tpu.ops._pallas import pick_block_n

    bn = pick_block_n(N, block_n)
    nn = N // bn

    packedk = qw["packed"].reshape(N, nk, nb_chunk, half)
    scales = (qw["scale_q"].astype(jnp.float32) * qw["meta"][0]).reshape(
        N, nk, nb_chunk
    )

    out = pl.pallas_call(
        functools.partial(_nf4_t_kernel, block_size=block_size, nn=nn),
        grid=(M // block_m, nk, nn),
        in_specs=[
            pl.BlockSpec((1, block_m, bn), lambda i, kk, nj: (nj, i, 0)),
            pl.BlockSpec((1, bn, nb_chunk, half),
                         lambda i, kk, nj: (kk, nj, 0, 0)),
            pl.BlockSpec((1, bn, nb_chunk), lambda i, kk, nj: (kk, nj, 0)),
        ],
        out_specs=pl.BlockSpec((block_m, ck), lambda i, kk, nj: (i, kk)),
        out_shape=jax.ShapeDtypeStruct((M, K), g.dtype),
        scratch_shapes=[
            pltpu.VMEM((bn, ck), jnp.float32),
            pltpu.VMEM((block_m, ck), jnp.float32),
        ],
        interpret=_interpret(),
    )(
        g2d.reshape(M, nn, bn).transpose(1, 0, 2),        # [nn, M, bn]
        packedk.transpose(1, 0, 2, 3),                    # [nk, N, nbc, half]
        scales.transpose(1, 0, 2),                        # [nk, N, nb_chunk]
    )
    return out[:m_real].reshape(*lead, K)


def _pallas_matmul_nf4_impl(
    x: jnp.ndarray, qw: Dict[str, jnp.ndarray], shape: Tuple[int, int],
    block_m: int = 256, block_n: int = 256, block_size: int = 64,
) -> jnp.ndarray:
    """x: [..., K] @ nf4-packed weights (ops/quant.py layout) → [..., N].

    Inputs are rearranged chunk-major on the host ([nk, …, ck-sized tail]) so
    the K-grid BlockSpecs index a leading dim and keep lane/sublane block
    dims equal to the array dims — the only tiling that is legal for EVERY
    real-model K (5632, 11008, … are not 128·64-multiples)."""
    K, N = shape
    *lead, K2 = x.shape
    assert K2 == K, (K2, K)
    nb_per_channel = K // block_size
    ck = _pick_chunk(nb_per_channel, block_size)
    nb_chunk = ck // block_size
    nk = K // ck
    half = block_size // 2

    x2d = x.reshape(-1, K)
    x2d, m_real = _pad_rows(x2d, block_m)
    M = x2d.shape[0]
    from datatunerx_tpu.ops._pallas import pick_block_n

    bn = pick_block_n(N, block_n)

    xk = x2d.reshape(M, nk, ck).transpose(1, 0, 2)  # [nk, M, ck]
    packedk = qw["packed"].reshape(N, nk, nb_chunk, half).transpose(1, 0, 2, 3)
    scales = (qw["scale_q"].astype(jnp.float32) * qw["meta"][0]).reshape(
        N, nk, nb_chunk
    )
    scalesk = scales.transpose(1, 0, 2)  # [nk, N, nb_chunk]

    out = pl.pallas_call(
        functools.partial(_nf4_kernel, block_size=block_size, nk=nk),
        grid=(M // block_m, N // bn, nk),
        in_specs=[
            pl.BlockSpec((1, block_m, ck), lambda i, j, kk: (kk, i, 0)),
            pl.BlockSpec((1, bn, nb_chunk, half),
                         lambda i, j, kk: (kk, j, 0, 0)),
            pl.BlockSpec((1, bn, nb_chunk), lambda i, j, kk: (kk, j, 0)),
        ],
        out_specs=pl.BlockSpec((block_m, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((bn, ck), jnp.float32),
            pltpu.VMEM((block_m, bn), jnp.float32),
        ],
        interpret=_interpret(),
    )(xk, packedk, scalesk)
    return out[:m_real].reshape(*lead, N)
