"""Pallas in-place paged-attention decode kernel (vLLM PagedAttention done
natively — PAPERS.md; the ROADMAP "Decode fast path" arc).

The XLA gather path (ops/paged_attention.py ``paged_kv_update``) is
token-exact but materializes a dense-equivalent ``[B, W, KV, d]`` linear
view of every slot's blocks per layer per decode step: the block pool saves
HBM *capacity* while decode still pays dense HBM *bandwidth* — a full-width
gather write plus a full-width attention read, padding included. This
kernel walks the per-slot block table with scalar prefetch and reads the
K/V blocks IN PLACE: per decode token it streams only the slot's LIVE
blocks through VMEM (K twice, V once — see below), so HBM traffic scales
with ``len(session)`` instead of ``blocks_per_slot × block_size``, and the
gathered view never exists.

Correctness contract — the gather path stays alive as the parity ORACLE,
and the PR 5 bit-parity suite asserts kernel-vs-gather token-exactness.
That drives the kernel's two-phase shape:

- **Phase 0 (stats)**: flash-style online-softmax accumulator over the
  table's blocks — running row max ``m`` and rescaled normalizer ``l`` in
  f32 VMEM scratch, exactly flash_attention.py's scheme.
- **Phase 1 (weighted sum)**: with the row's ``m``/``l`` known, each
  block's probabilities are the oracle's own ``exp(s - m) / l`` quantized
  to the compute dtype BEFORE the PV product — replicating
  ``xla_attention``'s ``probs.astype(v.dtype)`` rounding, which a
  single-pass accumulator cannot (it would normalize after the cast).
  Differences vs the oracle reduce to f32 summation order (~1e-7
  relative), which greedy/sampled token streams don't see.

Masking needs no bias tensor: a table entry < 0 skips its block outright
(``pl.when``), and within a block the pos pool — POS_SENTINEL on every
unwritten/pad lane — is compared against the query's rope position, the
same ``kv_pos <= q_pos`` check the oracle's causal bias encodes. GQA maps
each query-head group onto its KV head with a static in-kernel loop (no
``jnp.repeat``); int8 ``kv_quant`` pools dequantize per block inside the
kernel by the paged scale pools (pallas_quant.py's fuse-the-dequant idiom),
rounding through the compute dtype exactly as ``kv_dequantize`` does.

Testable under ``JAX_PLATFORMS=cpu`` via the shared interpret-mode gate
(ops/_pallas.py); ``DTX_PALLAS_INTERPRET=0`` forces real Mosaic lowering
for AOT certification.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # finite (flash_attention.py): -inf - -inf would NaN
_LANES = 128  # stats scratch padded to the TPU lane width


def _interpret() -> bool:
    from datatunerx_tpu.ops._pallas import interpret_default

    return interpret_default()


def _decode_kernel(tables_ref, qpos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                   pos_ref, o_ref, acc_ref, m_ref, l_ref,
                   *, nbps: int, kv_heads: int, group: int, scale: float,
                   quant: bool):
    """One (slot, table-entry, phase) grid step.

    Grid is ``(B, 2 * nbps)``: the trailing dim walks the slot's table twice
    — ``j < nbps`` is the stats phase, ``j >= nbps`` the weighted-sum phase.
    Block j's K/V/pos land in VMEM via the scalar-prefetched table (invalid
    entries clamp to physical block 0 and are skipped by ``pl.when``)."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    jj = j - (j // nbps) * nbps  # table column this step covers
    stats_phase = j < nbps
    entry = tables_ref[b, jj]
    q_pos = qpos_ref[b]
    d = o_ref.shape[-1]

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _heads(ref, scale_ref):
        """The block's per-head [bs, d] tiles, dequantized when quantized.

        Pools arrive with the (KV, d) trailing dims MERGED ([1, bs, KV·d]
        blocks): Mosaic cannot slice the middle dim of an int8 tile (and
        per-head (…, 1, d) trailing block dims are illegal tilings), so the
        whole tile is loaded/converted 2D and each head is a static
        lane-dim slice — the nf4 kernel's planar-unpack idiom."""
        full = ref[0]  # [bs, KV·d]
        if quant:
            full = full.astype(jnp.float32)
        out = []
        for kv in range(kv_heads):
            h = full[:, kv * d:(kv + 1) * d]
            if quant:
                # match kv_dequantize: f32 product rounded through the
                # compute dtype before the f32 MXU pass
                h = (h * scale_ref[0][:, kv:kv + 1]).astype(o_ref.dtype)
            out.append(h.astype(jnp.float32))
        return out

    def _masked_scores(k_heads):
        """Masked f32 score rows, one [group, bs] per KV head."""
        # pos block is [1, 1, bs] (the unit middle dim keeps the trailing
        # block dims equal to the array dims — Mosaic's tiling rule)
        mask = pos_ref[0, 0:1, :] <= q_pos  # sentinel + causal in one
        out = []
        for kv in range(kv_heads):
            qg = q_ref[0, kv * group:(kv + 1) * group, :].astype(jnp.float32)
            s = jax.lax.dot_general(
                qg, k_heads[kv], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            out.append(jnp.where(mask, s, NEG_INF))
        return out

    @pl.when((entry >= 0) & stats_phase)
    def _stats():
        for kv, s in enumerate(_masked_scores(_heads(k_ref, ks_ref))):
            rows = slice(kv * group, (kv + 1) * group)
            m_prev = m_ref[rows, 0:1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            corr = jnp.exp(m_prev - m_new)
            l_ref[rows, :] = (l_ref[rows, :] * corr
                              + jnp.sum(jnp.exp(s - m_new), axis=1,
                                        keepdims=True))
            m_ref[rows, :] = jnp.broadcast_to(m_new,
                                              (group, m_ref.shape[1]))

    @pl.when((entry >= 0) & ~stats_phase)
    def _weighted_sum():
        v_heads = _heads(v_ref, vs_ref)
        for kv, s in enumerate(_masked_scores(_heads(k_ref, ks_ref))):
            rows = slice(kv * group, (kv + 1) * group)
            l_row = jnp.maximum(l_ref[rows, 0:1], 1e-30)
            # the oracle's probs: normalized THEN quantized to the compute
            # dtype before the PV product (xla_attention rounds the same way)
            p = (jnp.exp(s - m_ref[rows, 0:1]) / l_row).astype(o_ref.dtype)
            acc_ref[rows, :] += jax.lax.dot_general(
                p.astype(jnp.float32), v_heads[kv],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    @pl.when(j == 2 * nbps - 1)
    def _finish():
        o_ref[0] = acc_ref[:].astype(o_ref.dtype)


def paged_decode_attention(
    q: jnp.ndarray,          # [B, H, d] — the decode step's single token
    k_pool: jnp.ndarray,     # [NB, bs, KV, d] one layer's block pool
    v_pool: jnp.ndarray,
    k_scale: Optional[jnp.ndarray],  # [NB, bs, KV] f32 (int8 pools) | None
    v_scale: Optional[jnp.ndarray],
    tables: jnp.ndarray,     # [B, nbps] int32, -1 = unallocated
    pos_pool: jnp.ndarray,   # [NB, bs] int32 — POST-write (this token's rope
                             # position already scattered in)
    q_positions: jnp.ndarray,  # [B] int32 rope position of the query token
    *,
    interpret=None,
) -> jnp.ndarray:
    """In-place paged decode attention over the block pool: out [B, H, d].

    Slots whose tables hold no valid block (released / never admitted)
    produce zeros — the engine's emit mask already discards their tokens,
    mirroring the garbage the oracle's sentinel-masked uniform softmax
    yields for such rows."""
    B, H, d = q.shape
    NB, bs, KV, _ = k_pool.shape
    nbps = tables.shape[1]
    G = H // KV
    quant = k_scale is not None

    # the ORACLE's scale arithmetic, exactly: xla_attention computes
    # 1/sqrt(f32(d)) in f32 — a python 1/d**0.5 double differs by 1 ulp for
    # head dims like 96/112, enough to flip a bf16-rounded probability and
    # break the token-parity contract on those models
    scale = float(np.float32(1.0) / np.sqrt(np.float32(d)))  # dtxlint: disable=DTX001 — host numpy scalar (d is a static shape), no device sync
    kernel = functools.partial(
        _decode_kernel, nbps=nbps, kv_heads=KV, group=G,
        scale=scale, quant=quant)

    def kv_index(b, j, tables_ref, qpos_ref):
        # clamp -1 → block 0: the DMA must stay in bounds; pl.when skips
        # the compute, so the fetched garbage is never read
        return (jnp.maximum(tables_ref[b, j - (j // nbps) * nbps], 0), 0, 0)

    pos_index = scale_index = kv_index

    def v_index(b, j, tables_ref, qpos_ref):
        # V is consumed in phase 1 only; parking the index on block 0
        # during phase 0 keeps Mosaic's same-block revisit from re-DMAing
        # anything useless (interpret mode is indifferent)
        jj = j - (j // nbps) * nbps
        return (jnp.maximum(tables_ref[b, jj], 0) * (j >= nbps), 0, 0)

    # pools enter the kernel with (KV, d) merged — [NB, bs, KV·d] — a free
    # trailing-dims reshape that makes every per-head extraction a static
    # LANE slice (Mosaic cannot slice the middle dim of an int8 tile)
    in_specs = [
        pl.BlockSpec((1, H, d), lambda b, j, t, p: (b, 0, 0)),
        pl.BlockSpec((1, bs, KV * d), kv_index),
        pl.BlockSpec((1, bs, KV * d), v_index),
    ]
    args = [q, k_pool.reshape(NB, bs, KV * d),
            v_pool.reshape(NB, bs, KV * d)]
    if quant:
        in_specs += [pl.BlockSpec((1, bs, KV), scale_index),
                     pl.BlockSpec((1, bs, KV), scale_index)]
        args += [k_scale, v_scale]
    in_specs.append(pl.BlockSpec((1, 1, bs), pos_index))
    args.append(pos_pool[:, None])  # [NB, 1, bs]: Mosaic-legal tiling

    kernel_args = kernel if quant else functools.partial(
        _no_scale_kernel, kernel)
    out = pl.pallas_call(
        kernel_args,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, 2 * nbps),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, H, d), lambda b, j, t, p: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((H, d), jnp.float32),
                pltpu.VMEM((H, _LANES), jnp.float32),
                pltpu.VMEM((H, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, d), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(tables.astype(jnp.int32), q_positions.astype(jnp.int32), *args)
    return out


def _no_scale_kernel(kernel, tables_ref, qpos_ref, q_ref, k_ref, v_ref,
                     pos_ref, o_ref, acc_ref, m_ref, l_ref):
    """Arity shim for the unquantized pools: no scale refs in the call."""
    kernel(tables_ref, qpos_ref, q_ref, k_ref, v_ref, None, None,
           pos_ref, o_ref, acc_ref, m_ref, l_ref)


def paged_attention_decode_step(q, ck, cv, cks, cvs, cache: dict,
                                pos_pool, positions, *, interpret=None):
    """Model-facing wrapper: q ``[B, 1, H, d]`` (one decode token), the
    layer-peeled pools, the live cache dict (block tables), the POST-write
    pos pool, and the step's ``positions [B, 1]``. Returns ``[B, 1, H, d]``
    in q.dtype — drop-in for the gather + ``xla_attention`` pair."""
    B, T, H, d = q.shape
    assert T == 1, f"paged decode kernel is single-token (T=1), got T={T}"
    out = paged_decode_attention(
        q[:, 0], ck, cv, cks, cvs, cache["block_tables"], pos_pool,
        positions[:, 0], interpret=interpret)
    return out[:, None]


# ---------------------------------------------------------------------------
# Multi-token q (chunked prefill / verify-k / tree-verify columns)
# ---------------------------------------------------------------------------
#
# Same block-table walk and two-phase online softmax as the decode kernel,
# for a bucketed q_len > 1. Masking changes shape, not mechanism: instead of
# the in-kernel ``kv_pos <= q_pos`` compare (one scalar per slot), the host
# precomputes the full boolean attendability tensor ``allow [B, T, W]`` with
# ``ops.attention.attention_allow`` — the SAME tensor the XLA oracle turns
# into its additive bias — and the kernel streams the block's [T, bs] tile
# of it alongside K/V. That one operand encodes per-row causal offsets,
# POS_SENTINEL lanes, ragged lens, sliding windows, and tree-branch
# ancestry masks uniformly, so kernel/oracle mask parity holds by
# construction (an int32 tile costs W·T·4 bytes per slot vs the KV blocks'
# 2·W·KV·d·itemsize — noise). Invalid table entries are still skipped
# outright by ``pl.when``.
#
# q enters kv-major as ``[B, H·T, d]`` (row (kv·G + g)·T + t): per-(kv, g)
# extraction stays a static sublane slice and each score tile is one
# [T, d] × [d, bs] MXU pass, reusing the decode kernel's merged-trailing-dim
# pool layout unchanged.
#
# Garbage contract: a fully-masked query row (inactive slot in a verify
# batch) normalizes over NEG_INF scores — finite uniform-ish junk, like the
# oracle's sentinel-masked softmax but not bit-equal to it. Such rows only
# exist where the engine's emit mask discards them; parity is asserted on
# rows with at least one attendable lane.


def _multitoken_kernel(tables_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                       allow_ref, o_ref, acc_ref, m_ref, l_ref,
                       *, nbps: int, kv_heads: int, group: int, q_len: int,
                       scale: float, quant: bool):
    """One (slot, table-entry, phase) grid step for q_len query rows."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    jj = j - (j // nbps) * nbps
    stats_phase = j < nbps
    entry = tables_ref[b, jj]
    d = o_ref.shape[-1]

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _heads(ref, scale_ref):
        """The block's per-head [bs, d] tiles (see the decode kernel)."""
        full = ref[0]  # [bs, KV·d]
        if quant:
            full = full.astype(jnp.float32)
        out = []
        for kv in range(kv_heads):
            h = full[:, kv * d:(kv + 1) * d]
            if quant:
                h = (h * scale_ref[0][:, kv:kv + 1]).astype(o_ref.dtype)
            out.append(h.astype(jnp.float32))
        return out

    def _masked_scores(k_heads):
        """Masked f32 score tiles: one ([q_len, bs], row slice) per head."""
        mask = allow_ref[0] != 0  # [q_len, bs] — the oracle's bias == 0
        out = []
        for kv in range(kv_heads):
            for g in range(group):
                rows = slice((kv * group + g) * q_len,
                             (kv * group + g + 1) * q_len)
                qg = q_ref[0, rows, :].astype(jnp.float32)
                s = jax.lax.dot_general(
                    qg, k_heads[kv], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * scale
                out.append((rows, kv, jnp.where(mask, s, NEG_INF)))
        return out

    @pl.when((entry >= 0) & stats_phase)
    def _stats():
        for rows, _, s in _masked_scores(_heads(k_ref, ks_ref)):
            m_prev = m_ref[rows, 0:1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            corr = jnp.exp(m_prev - m_new)
            l_ref[rows, :] = (l_ref[rows, :] * corr
                              + jnp.sum(jnp.exp(s - m_new), axis=1,
                                        keepdims=True))
            m_ref[rows, :] = jnp.broadcast_to(m_new,
                                              (q_len, m_ref.shape[1]))

    @pl.when((entry >= 0) & ~stats_phase)
    def _weighted_sum():
        v_heads = _heads(v_ref, vs_ref)
        for rows, kv, s in _masked_scores(_heads(k_ref, ks_ref)):
            l_row = jnp.maximum(l_ref[rows, 0:1], 1e-30)
            # oracle rounding: normalize THEN cast before the PV product
            p = (jnp.exp(s - m_ref[rows, 0:1]) / l_row).astype(o_ref.dtype)
            acc_ref[rows, :] += jax.lax.dot_general(
                p.astype(jnp.float32), v_heads[kv],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    @pl.when(j == 2 * nbps - 1)
    def _finish():
        o_ref[0] = acc_ref[:].astype(o_ref.dtype)


def paged_multitoken_attention(
    q: jnp.ndarray,          # [B, T, H, d] — the step's query columns
    k_pool: jnp.ndarray,     # [NB, bs, KV, d] one layer's block pool
    v_pool: jnp.ndarray,
    k_scale: Optional[jnp.ndarray],  # [NB, bs, KV] f32 (int8 pools) | None
    v_scale: Optional[jnp.ndarray],
    tables: jnp.ndarray,     # [B, nbps] int32, -1 = unallocated
    allow: jnp.ndarray,      # [B, T, nbps·bs] bool/int — attendability per
                             # (query row, linear cache lane), POST-write
    *,
    interpret=None,
) -> jnp.ndarray:
    """In-place paged attention for q_len > 1: out ``[B, T, H, d]``.

    ``allow`` must be ``attention_allow(...)`` over the POST-write gathered
    kv positions — the one tensor the gather oracle biases with."""
    B, T, H, d = q.shape
    NB, bs, KV, _ = k_pool.shape
    nbps = tables.shape[1]
    G = H // KV
    quant = k_scale is not None
    assert allow.shape == (B, T, nbps * bs), (
        f"allow {allow.shape} != {(B, T, nbps * bs)}")

    scale = float(np.float32(1.0) / np.sqrt(np.float32(d)))  # dtxlint: disable=DTX001 — host numpy scalar (d is a static shape), no device sync
    kernel = functools.partial(
        _multitoken_kernel, nbps=nbps, kv_heads=KV, group=G, q_len=T,
        scale=scale, quant=quant)

    def kv_index(b, j, tables_ref):
        return (jnp.maximum(tables_ref[b, j - (j // nbps) * nbps], 0), 0, 0)

    scale_index = kv_index

    def v_index(b, j, tables_ref):
        jj = j - (j // nbps) * nbps
        return (jnp.maximum(tables_ref[b, jj], 0) * (j >= nbps), 0, 0)

    def allow_index(b, j, tables_ref):
        # the allow tensor is laid out linearly — column block jj of slot b
        return (b, 0, j - (j // nbps) * nbps)

    # q kv-major [B, H·T, d]: row (kv·G + g)·T + t, so per-(kv, g) rows are
    # a static sublane slice; pools keep the merged (KV, d) trailing dims
    q_km = q.transpose(0, 2, 1, 3).reshape(B, H * T, d)
    in_specs = [
        pl.BlockSpec((1, H * T, d), lambda b, j, t: (b, 0, 0)),
        pl.BlockSpec((1, bs, KV * d), kv_index),
        pl.BlockSpec((1, bs, KV * d), v_index),
    ]
    args = [q_km, k_pool.reshape(NB, bs, KV * d),
            v_pool.reshape(NB, bs, KV * d)]
    if quant:
        in_specs += [pl.BlockSpec((1, bs, KV), scale_index),
                     pl.BlockSpec((1, bs, KV), scale_index)]
        args += [k_scale, v_scale]
    in_specs.append(pl.BlockSpec((1, T, bs), allow_index))
    args.append(allow.astype(jnp.int32))

    kernel_args = kernel if quant else functools.partial(
        _no_scale_mt_kernel, kernel)
    out = pl.pallas_call(
        kernel_args,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, 2 * nbps),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, H * T, d), lambda b, j, t: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((H * T, d), jnp.float32),
                pltpu.VMEM((H * T, _LANES), jnp.float32),
                pltpu.VMEM((H * T, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H * T, d), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(tables.astype(jnp.int32), *args)
    return out.reshape(B, H, T, d).transpose(0, 2, 1, 3)


def _no_scale_mt_kernel(kernel, tables_ref, q_ref, k_ref, v_ref, allow_ref,
                        o_ref, acc_ref, m_ref, l_ref):
    """Arity shim for the unquantized pools: no scale refs in the call."""
    kernel(tables_ref, q_ref, k_ref, v_ref, None, None, allow_ref,
           o_ref, acc_ref, m_ref, l_ref)


def paged_attention_multitoken_step(q, ck, cv, cks, cvs, cache: dict,
                                    allow, *, interpret=None):
    """Model-facing wrapper: q ``[B, T, H, d]`` (chunk / verify columns),
    the layer-peeled pools, the live cache dict, and the POST-write
    ``allow [B, T, S]`` attendability tensor. Returns ``[B, T, H, d]`` in
    q.dtype — drop-in for the gather + ``xla_attention`` pair."""
    return paged_multitoken_attention(
        q, ck, cv, cks, cvs, cache["block_tables"], allow,
        interpret=interpret)
