"""Pallas flash attention (causal, GQA) with a flash backward pass.

TPU-first replacement for the reference's flash-attn CUDA toggle (reference
cmd/tuning/parser.py:66-69): O(T) memory — the [T, S] score matrix never
materializes in either direction. Forward stores only the per-row logsumexp;
backward recomputes probabilities tile-by-tile (standard FlashAttention-2
scheme: one kernel accumulates dQ over K tiles, one accumulates dK/dV over Q
tiles, with D = rowsum(dO ∘ O) precomputed).

Masking is handled in-kernel: causal by row index, plus packed-segment
isolation via per-row segment ids (all-equal ids degenerate to plain causal,
so unpacked right-padded batches are exact — pads sit at the tail where no
valid query can attend them). Sliding window and cache decode fall back to the
biased XLA path (models/llama.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128  # stats tiles padded to the TPU lane width
_SUBLANES = 8  # segment-id tiles padded to the TPU sublane width

# Mosaic requires the last two block dims to be (8k, 128k) or match the array,
# so [B, T] segment ids can't block as (1, block_q). Broadcast them instead:
# q ids ride the lane dim ([B, T, 128]), kv ids the sublane dim ([B, 8, S]) —
# inside the kernel a (bq, 1) column of the former against a (1, bk) row of
# the latter recovers the [bq, bk] pairwise mask.


def _seg3d(q_seg: jnp.ndarray, kv_seg: jnp.ndarray):
    B, T = q_seg.shape
    S = kv_seg.shape[1]
    q3 = jnp.broadcast_to(q_seg[:, :, None], (B, T, _LANES))
    kv3 = jnp.broadcast_to(kv_seg[:, None, :], (B, _SUBLANES, S))
    return q3, kv3


def _interpret() -> bool:
    from datatunerx_tpu.ops._pallas import interpret_default

    return interpret_default()


# Mosaic kernels cannot be auto-partitioned by GSPMD ("wrap the call in a
# shard_map" — raised by the REAL TPU lowering, invisible in interpret mode;
# caught by AOT certification of the dp4×fsdp4 train step, r5). The Trainer
# sets this context when a mesh is active so the flash call runs under
# shard_map: each device executes the kernel on its local (batch, head)
# shard. Sequence stays unsharded here — sp-parallel attention is ring's job.
_FLASH: dict = {"mesh": None, "batch_axes": ("dp", "fsdp"), "tp_axis": "tp"}


def set_flash_context(mesh, batch_axes=("dp", "fsdp"),
                      tp_axis: str = "tp") -> None:
    _FLASH.update(mesh=mesh, batch_axes=batch_axes, tp_axis=tp_axis)


def _flash_shard_mesh():
    """The active mesh if any sharded axis is >1 (else None: plain call)."""
    mesh = _FLASH["mesh"]
    if mesh is None:
        return None, None, None
    batch_axes = tuple(a for a in _FLASH["batch_axes"]
                       if a in mesh.shape)
    tp = _FLASH["tp_axis"] if _FLASH["tp_axis"] in mesh.shape else None
    sharded = 1
    for a in batch_axes:
        sharded *= mesh.shape[a]
    if tp:
        sharded *= mesh.shape[tp]
    if sharded == 1:
        return None, None, None
    return mesh, batch_axes, tp


# ------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, qseg_ref, kseg_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref,
                *, block_q: int, block_k: int, scale: float,
                causal: bool = True):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal=False (ring-of-flash past chunks): every block contributes and
    # no triangular mask applies — the in/visible split is decided OUTSIDE
    # the kernel per ring step (full vs none), so the kernel stays static
    run = (j * block_k <= i * block_q + block_q - 1) if causal else (j >= 0)

    @pl.when(run)  # causal: skip fully-future blocks
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale

        q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = (k_pos <= q_pos) if causal else (k_pos >= 0)
        # packed-segment isolation (all-equal ids = plain causal)
        mask &= qseg_ref[0][:, 0:1] == kseg_ref[0][0:1, :]
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)

        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == nk - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse = m_ref[:, 0:1] + jnp.log(l)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _kv_index(H: int, G: int):
    """Map the folded (batch·q-head) grid index to the (batch·kv-head) row of
    the un-expanded K/V arrays — GQA without materializing jnp.repeat."""
    KV = H // G

    def index(b, i, j):
        return ((b // H) * KV + (b % H) // G, j, 0)

    return index


def _fwd(q, k, v, q_seg, kv_seg, *, block_q, block_k, interpret, H, G,
         causal: bool = True):
    BH, T, d = q.shape
    S = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, scale=scale,
        causal=causal,
    )
    kv_idx = _kv_index(H, G)
    q_seg3, kv_seg3 = _seg3d(q_seg, kv_seg)
    out, lse = pl.pallas_call(
        kernel,
        grid=(BH, T // block_q, S // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b // H, i, 0)),
            pl.BlockSpec((1, _SUBLANES, block_k), lambda b, i, j: (b // H, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, d), q.dtype),
            jax.ShapeDtypeStruct((BH, T, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, q_seg3, kv_seg3)
    return out, lse[:, :, 0]


# ------------------------------------------------------------- backward

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
                   qseg_ref, kseg_ref, dq_ref,
                   acc_ref, *, block_q: int, block_k: int, scale: float,
                   causal: bool = True):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = (j * block_k <= i * block_q + block_q - 1) if causal else (j >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = ((k_pos <= q_pos) if causal else (k_pos >= 0)) \
            & (qseg_ref[0][:, 0:1] == kseg_ref[0][0:1, :])
        p = jnp.where(mask, jnp.exp(s - lse_ref[0][:, 0:1]), 0.0)

        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dsum_ref[0][:, 0:1]) * scale
        acc_ref[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
                    qseg_ref, kseg_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, block_q: int, block_k: int, scale: float,
                    causal: bool = True):
    j = pl.program_id(1)  # k tile
    i = pl.program_id(2)  # q tile (sequential)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (i * block_q + block_q - 1 >= j * block_k) if causal else (i >= 0)

    @pl.when(run)  # causal: skip q tiles fully in the past
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = ((k_pos <= q_pos) if causal else (k_pos >= 0)) \
            & (qseg_ref[0][:, 0:1] == kseg_ref[0][0:1, :])
        p = jnp.where(mask, jnp.exp(s - lse_ref[0][:, 0:1]), 0.0)  # [bq, bk]

        do = do_ref[0].astype(jnp.float32)  # [bq, d]
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bk, d]
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        ds = p * (dp - dsum_ref[0][:, 0:1]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bk, d]

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(block_q, block_k, interpret, G, res, do, causal: bool = True):
    """K/V arrive un-expanded [B*KV, S, d]; expand here (backward only) and
    group-sum dk/dv at the end — forward never materializes the repeat."""
    q, k, v, q_seg, kv_seg, out, lse = res
    BH, T, d = q.shape
    if G > 1:
        BKV = k.shape[0]
        k = jnp.repeat(k, G, axis=0)
        v = jnp.repeat(v, G, axis=0)
    S = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _interpret()

    H_ = BH // q_seg.shape[0]  # q heads per batch row (segment index maps)
    dsum = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    lse_b = jnp.broadcast_to(lse[:, :, None], (BH, T, _LANES))
    dsum_b = jnp.broadcast_to(dsum[:, :, None], (BH, T, _LANES))
    q_seg3, kv_seg3 = _seg3d(q_seg, kv_seg)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=block_q, block_k=block_k,
                          scale=scale, causal=causal),
        grid=(BH, T // block_q, S // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b // H_, i, 0)),
            pl.BlockSpec((1, _SUBLANES, block_k), lambda b, i, j: (b // H_, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse_b, dsum_b, q_seg3, kv_seg3)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
                          scale=scale, causal=causal),
        grid=(BH, S // block_k, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, j, i: (b // H_, i, 0)),
            pl.BlockSpec((1, _SUBLANES, block_k), lambda b, j, i: (b // H_, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, d), k.dtype),
            jax.ShapeDtypeStruct((BH, S, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse_b, dsum_b, q_seg3, kv_seg3)
    if G > 1:
        dk = dk.reshape(BKV, G, S, d).sum(axis=1)
        dv = dv.reshape(BKV, G, S, d).sum(axis=1)
    return dq, dk, dv


# --------------------------------------------------------------- public

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def flash_attention_causal(q, k, v, q_seg, kv_seg, block_q: int = 512,
                           block_k: int = 512, interpret=None, H: int = 1,
                           G: int = 1):
    """q: [B*H, T, d]; k, v: [B*KV, S, d] (un-expanded GQA);
    q_seg/kv_seg: [B, T]/[B, S] int32 segment ids (all-equal = plain causal)."""
    out, _ = _fwd(q, k, v, q_seg, kv_seg, block_q=block_q, block_k=block_k,
                  interpret=_interpret() if interpret is None else interpret,
                  H=H, G=G)
    return out


def _vjp_fwd(q, k, v, q_seg, kv_seg, block_q, block_k, interpret, H, G):
    out, lse = _fwd(q, k, v, q_seg, kv_seg, block_q=block_q, block_k=block_k,
                    interpret=_interpret() if interpret is None else interpret,
                    H=H, G=G)
    return out, (q, k, v, q_seg, kv_seg, out, lse)


def _vjp_bwd(block_q, block_k, interpret, H, G, res, do):
    dq, dk, dv = _bwd(block_q, block_k, interpret, G, res, do)
    return dq, dk, dv, None, None


flash_attention_causal.defvjp(_vjp_fwd, _vjp_bwd)


def _pick_block(n: int, cap: int = 512) -> int:
    """Largest power-of-two divisor of n, capped (TPU-friendly tile sizes)."""
    b = 1
    while b < cap and n % (b * 2) == 0:
        b *= 2
    return min(b, cap)


def flash_attention(
    q: jnp.ndarray,  # [B, T, H, d]
    k: jnp.ndarray,  # [B, S, KV, d]
    v: jnp.ndarray,
    bias=None,  # accepted for dispatch parity; causal handled in-kernel
    *,
    segment_ids: jnp.ndarray | None = None,  # [B, T] packed-segment ids
    block_q: int = 512,
    block_k: int = 512,
    interpret=None,
) -> jnp.ndarray:
    """GQA wrapper: fold (B, H) into the grid dim; KV stays un-expanded and the
    kernel's index_map routes each q head to its KV group. With segment_ids,
    attention is additionally confined within packed segments (self-attention:
    T == S, ids shared between q and kv).

    Under an active mesh (set_flash_context) the call is wrapped in
    shard_map over the batch (+tp head) axes — Mosaic custom calls cannot
    be auto-partitioned by GSPMD, so without this the multi-chip train step
    fails to lower on real TPU toolchains."""
    mesh, batch_axes, tp = _flash_shard_mesh()
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        if tp is not None:
            H_, KV_ = q.shape[2], k.shape[2]
            if H_ % mesh.shape[tp] or KV_ % mesh.shape[tp]:
                # GQA head counts that don't divide tp: keep heads whole in
                # the wrap (GSPMD gathers them); batch still shards
                tp = None
        qkv_spec = P(batch_axes, None, tp, None)
        seg_spec = P(batch_axes, None)

        if segment_ids is None:
            def local3(q, k, v):
                return _flash_local(q, k, v, None, block_q, block_k,
                                    interpret)

            from datatunerx_tpu.parallel.sharding import compat_shard_map

            return compat_shard_map(local3, mesh=mesh, in_specs=(qkv_spec,) * 3,
                                    out_specs=qkv_spec, check=False)(q, k, v)

        def local(q, k, v, seg):
            return _flash_local(q, k, v, seg, block_q, block_k, interpret)

        from datatunerx_tpu.parallel.sharding import compat_shard_map

        return compat_shard_map(local, mesh=mesh,
                                in_specs=(qkv_spec, qkv_spec, qkv_spec,
                                          seg_spec),
                                out_specs=qkv_spec, check=False)(
            q, k, v, segment_ids)
    return _flash_local(q, k, v, segment_ids, block_q, block_k, interpret)


def _flash_local(q, k, v, segment_ids, block_q, block_k, interpret):
    B, T, H, d = q.shape
    S, KV = k.shape[1], k.shape[2]
    G = H // KV
    block_q = min(block_q, _pick_block(T))
    block_k = min(block_k, _pick_block(S))
    if segment_ids is None:
        q_seg = jnp.ones((B, T), jnp.int32)
        kv_seg = jnp.ones((B, S), jnp.int32)
    else:
        assert T == S, (
            f"segment_ids requires self-attention (T == S), got T={T} S={S}")
        q_seg = segment_ids.astype(jnp.int32)
        kv_seg = q_seg  # self-attention
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, T, d)
    kf = k.transpose(0, 2, 1, 3).reshape(B * KV, S, d)
    vf = v.transpose(0, 2, 1, 3).reshape(B * KV, S, d)
    out = flash_attention_causal(qf, kf, vf, q_seg, kv_seg, block_q, block_k,
                                 interpret, H, G)
    return out.reshape(B, H, T, d).transpose(0, 2, 1, 3)
