"""Routing policies: which replica gets the next request.

Selection pipeline (every policy):

  1. candidates = healthy ∩ not-draining ∩ circuit-allows
  2. adapter RESIDENCY preference (cache-locality, not a hard filter):
     replicas whose adapter pool already holds the requested LoRA adapter
     win (no load latency); otherwise any replica that KNOWS the adapter
     (static stack or registered-for-load-on-miss) — routing there makes
     the engine load it at admission, and the replica becomes the
     preferred target for the adapter's next requests; if nothing reports
     the adapter (or stats are unknown), fall back to all candidates —
     the engine loads on demand / 400s an unknown name.
  3. session affinity: a request carrying a session key sticks to the
     replica that served the session before (its prefix cache holds the
     conversation's KV rows, so re-prefill becomes a suffix extension) —
     as long as that replica is still a candidate.
  4. traffic weights: replicas carry a ``weight`` (canary promotion,
     gateway/server.py /admin/promote). Weight 0 = no new requests (a
     rolled-back canary). When the candidate set's weights are
     NON-uniform, selection is smooth weighted round-robin — a
     deterministic rotation whose long-run shares equal the weights
     exactly (nginx's algorithm), so a 5% canary weight means 1 request
     in 20, observably. Uniform weights (the default 1.0 everywhere)
     fall through to the policy pick, preserving pre-weight behavior.
  5. policy pick: ``least_busy`` (lowest slot occupancy, gateway in-flight
     count as tiebreak/fallback) or ``round_robin``.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import List, Optional

from datatunerx_tpu.gateway.replica_pool import (
    NoReplicaAvailable,
    Replica,
    ReplicaPool,
)

POLICIES = ("least_busy", "round_robin")


def session_key(messages: List[dict], explicit: Optional[str] = None) -> str:
    """Affinity key for a conversation. An explicit session id (body
    ``session_id`` / ``user`` field, X-DTX-Session-Id header) wins; else the
    first message anchors the conversation — every later turn of the same
    chat shares it, so turns land where the prefix cache is warm."""
    if explicit:
        return str(explicit)
    if not messages:
        return ""
    first = messages[0]
    seed = f"{first.get('role', '')}:{first.get('content', '')}"
    return hashlib.sha1(seed.encode("utf-8", "replace")).hexdigest()


class Router:
    def __init__(self, pool: ReplicaPool, policy: str = "least_busy",
                 affinity_capacity: int = 4096,
                 prefill_threshold: int = 0):
        if policy not in POLICIES:
            raise ValueError(f"unknown policy {policy!r}; one of {POLICIES}")
        self.pool = pool
        self.policy = policy
        # disaggregation: prompts of >= this many tokens PREFER replicas
        # declaring role=prefill; shorter prompts prefer non-prefill
        # replicas. 0 disables the stage entirely (routing byte-identical
        # to a role-less fleet).
        self.prefill_threshold = int(prefill_threshold or 0)
        self._rr = 0
        self._wrr: dict = {}  # smooth-WRR current weights, by replica name
        self._affinity: "OrderedDict[str, str]" = OrderedDict()
        self._affinity_capacity = affinity_capacity
        self._lock = threading.Lock()
        # adapter-routing outcomes (gateway /metrics): how often the
        # residency preference paid off vs forced a load-on-miss
        self.adapter_routes = {"resident": 0, "load_miss": 0, "blind": 0}
        # adapter -> routed count. Only adapters some replica actually
        # reports (non-blind) are counted, and the key set is capped:
        # the 'model' field is client-controlled, and every key becomes a
        # Prometheus series — unvalidated names must not grow either
        # without bound.
        self.adapter_requests: dict = {}
        self._adapter_requests_cap = 1024
        # spec-routing outcomes: how often spec-friendly (greedy) traffic
        # found a healthy speculative-decode replica to prefer
        self.spec_routes = {"preferred": 0, "blind": 0}
        # role-routing outcomes: long prompts steered to prefill
        # specialists, short ones away from them, or no role signal
        self.role_routes = {"prefill": 0, "decode": 0, "blind": 0}
        # replicas whose acceptance EMA sits below this report as spec-
        # enabled but are NOT preferred — their controller has effectively
        # disabled drafting, so there is no TPOT win to chase there.
        # Matches AdaptiveK's stand-down floor (serving/speculative.py),
        # kept as a literal so the router stays importable without jax.
        self.spec_accept_floor = 0.35

    def route(self, messages: Optional[List[dict]] = None,
              adapter: str = "", session_id: Optional[str] = None,
              exclude: Optional[set] = None, on_event=None,
              prefer_spec: bool = False,
              prompt_tokens: Optional[int] = None) -> Replica:
        """Pick a replica. ``exclude`` names replicas already tried for this
        request (failover must not retry the replica that just died).
        ``on_event(name, **detail)`` receives routing decisions — the
        gateway wires it to the request's trace span so adapter
        residency/load-miss outcomes land in GET /debug/trace/<id>."""
        exclude = exclude or set()
        candidates = [r for r in self.pool.available()
                      if r.name not in exclude]
        # weight 0 = receives no NEW requests (rolled-back canary); if
        # every candidate is weighted out, weights are ignored — serving
        # degraded beats serving nothing
        weighted = [r for r in candidates
                    if getattr(r, "weight", 1.0) > 0.0]
        candidates = weighted or candidates
        if not candidates:
            raise NoReplicaAvailable(
                f"no available replica (total={len(self.pool.replicas())}, "
                f"excluded={sorted(exclude)})")

        if adapter:
            candidates = self._adapter_candidates(adapter, candidates,
                                                  on_event)
        if self.prefill_threshold > 0 and prompt_tokens is not None:
            candidates = self._role_candidates(prompt_tokens, candidates,
                                               on_event)
        if prefer_spec:
            candidates = self._spec_candidates(candidates, on_event)

        key = session_key(messages or [], session_id)
        if key:
            with self._lock:
                pinned = self._affinity.get(key)
            if pinned:
                for r in candidates:
                    if r.name == pinned:
                        self._touch(key, r.name)
                        return r

        chosen = self._pick(candidates)
        if key:
            self._touch(key, chosen.name)
        return chosen

    def _adapter_candidates(self, adapter: str,
                            candidates: List[Replica], on_event) -> list:
        """Narrow candidates by adapter CACHE LOCALITY: resident replicas
        first (the request decodes immediately), else replicas that can
        load-on-miss (static stack or registered in their pool — routing
        there warms the adapter for its next requests), else everyone (no
        signal; the engine answers authoritatively). Never a hard filter:
        an adapter nowhere resident still gets served."""
        resident_set: List[Replica] = []
        capable: List[Replica] = []
        no_signal: List[Replica] = []
        for r in candidates:
            st = r.stats()
            res = st.get("resident_adapters")
            known = st.get("adapters")
            if res is not None and adapter in res:
                resident_set.append(r)
            if known is None:
                # stats unknown (scrape failed / pre-first-fetch): not
                # evidence the replica must load — counting it as a
                # load_miss would report missing stats as cold adapters
                no_signal.append(r)
            elif adapter in known:
                capable.append(r)
        if resident_set:
            outcome, picked = "resident", resident_set
        elif capable:
            outcome, picked = "load_miss", capable
        else:
            outcome, picked = "blind", no_signal or candidates
        with self._lock:
            self.adapter_routes[outcome] += 1
            if outcome != "blind" and (
                    adapter in self.adapter_requests
                    or len(self.adapter_requests)
                    < self._adapter_requests_cap):
                self.adapter_requests[adapter] = \
                    self.adapter_requests.get(adapter, 0) + 1
        if on_event is not None:
            on_event("adapter_route", adapter=adapter, outcome=outcome,
                     resident=[r.name for r in resident_set],
                     candidates=len(picked))
        return picked

    def _spec_candidates(self, candidates: List[Replica], on_event) -> list:
        """Spec-friendly traffic (greedy/low-temperature — the workloads
        whose drafts verify best) PREFERS replicas running speculative
        decoding with a healthy acceptance rate, read from replica stats
        (``dtx_serving_spec_enabled`` / ``_accept_rate`` on remote
        replicas). A preference, never a filter — a fleet with no spec
        replica, or one whose acceptance collapsed below the floor, routes
        exactly as before."""
        preferred: List[Replica] = []
        for r in candidates:
            try:
                st = r.stats_snapshot()
            except Exception:  # noqa: BLE001 — stats are advisory
                continue
            if not st.get("spec_enabled"):
                continue
            rate = st.get("spec_accept_rate")
            if rate is None or rate >= self.spec_accept_floor:
                preferred.append(r)
        with self._lock:
            if preferred and len(preferred) < len(candidates):
                self.spec_routes["preferred"] += 1
            else:
                self.spec_routes["blind"] += 1
        if preferred and len(preferred) < len(candidates):
            if on_event is not None:
                on_event("spec_route", outcome="preferred",
                         replicas=[r.name for r in preferred])
            return preferred
        return candidates

    def _role_candidates(self, prompt_tokens: int,
                         candidates: List[Replica], on_event) -> list:
        """Disaggregated routing: prompts at/above the threshold PREFER
        prefill specialists (their chunked-prefill budget is the product
        there — the handoff coordinator re-homes them for decode);
        everything else prefers non-prefill replicas so specialists stay
        free for prompt work. A preference, never a filter — a fleet with
        no matching role routes exactly as before (mixed replicas satisfy
        both sides)."""
        long_prompt = prompt_tokens >= self.prefill_threshold
        if long_prompt:
            preferred = [r for r in candidates
                         if getattr(r, "role", "mixed") == "prefill"]
            outcome = "prefill"
        else:
            preferred = [r for r in candidates
                         if getattr(r, "role", "mixed") != "prefill"]
            outcome = "decode"
        if not preferred or len(preferred) == len(candidates):
            with self._lock:
                self.role_routes["blind"] += 1
            return candidates
        with self._lock:
            self.role_routes[outcome] += 1
        if on_event is not None:
            on_event("role_route", outcome=outcome,
                     prompt_tokens=prompt_tokens,
                     replicas=[r.name for r in preferred])
        return preferred

    def _pick(self, candidates: List[Replica]) -> Replica:
        weights = {r.name: max(0.0, getattr(r, "weight", 1.0))
                   for r in candidates}
        if len(set(weights.values())) > 1:
            return self._pick_weighted(candidates, weights)
        if self.policy == "round_robin":
            with self._lock:
                # stable order so the rotation actually rotates
                ordered = sorted(candidates, key=lambda r: r.name)
                chosen = ordered[self._rr % len(ordered)]
                self._rr += 1
            return chosen
        return min(candidates, key=lambda r: (r.busy_fraction(), r.inflight,
                                              r.name))

    def _pick_weighted(self, candidates: List[Replica],
                       weights: dict) -> Replica:
        """Smooth weighted round-robin: each pick adds every candidate's
        weight to its running credit, the highest credit wins and pays the
        total back. Deterministic, and over any window the share of picks
        converges to weight/sum(weights) — the property the canary shift
        test asserts."""
        with self._lock:
            total = sum(weights.values())
            best: Optional[Replica] = None
            for r in sorted(candidates, key=lambda r: r.name):
                cur = self._wrr.get(r.name, 0.0) + weights[r.name]
                self._wrr[r.name] = cur
                if best is None or cur > self._wrr[best.name]:
                    best = r
            self._wrr[best.name] -= total
            return best

    def _touch(self, key: str, name: str):
        with self._lock:
            self._affinity[key] = name
            self._affinity.move_to_end(key)
            while len(self._affinity) > self._affinity_capacity:
                self._affinity.popitem(last=False)

    def forget_replica(self, name: str):
        """Drop affinity pins to a removed/dead replica so stale sessions
        rebalance instead of pinning to a ghost.

        Deliberately does NOT clear the replica's smooth-WRR credit: this
        is called on EVERY replica failure, and erasing the debt a just-
        picked replica owes would hand a failing canary the next pick
        again (over-weighting exactly the replica that is erroring). A
        stale credit entry for a removed replica is inert — it only moves
        when the replica is a candidate again."""
        with self._lock:
            for k in [k for k, v in self._affinity.items() if v == name]:
                del self._affinity[k]
