"""Gateway HTTP front-end: one endpoint fronting N serving replicas.

Same wire surface the single server exposes (POST /chat/completions and
/v1/chat/completions with SSE streaming, GET /healthz, /v1/models,
/metrics, POST /perplexity) plus gateway-only endpoints:

  GET  /autoscale            queue/p95 summary + desired-replica hint
                             (operator/capacity.py consumes this)
  POST /admin/scale          {"replicas": N} — resize the managed replica
                             set (graceful drain on downscale)
  POST /admin/drain          {"replica": name} — drain one replica for a
                             rolling restart

Request handling: admission control first (429 + Retry-After on overload),
then routed to a replica (least-busy / round-robin / session affinity /
adapter awareness), with failover — a replica dying yields a retry on
another replica, including MID-STREAM: the replacement's output has the
already-emitted prefix skipped, so the client's SSE stream continues
seamlessly. Every request carries an X-DTX-Trace-Id, generated here when
absent and propagated to the replica, so one id follows a request
operator → gateway → engine.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
import uuid
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from datatunerx_tpu.fleet import FleetPlane
from datatunerx_tpu.gateway.admission import AdmissionController, Overloaded
from datatunerx_tpu.gateway.autoscale import autoscale_hint
from datatunerx_tpu.gateway.metrics import MS_BUCKETS, Registry
from datatunerx_tpu.gateway.replica_pool import (
    MIGRATED_MARKER,
    HTTPReplica,
    NoReplicaAvailable,
    Replica,
    ReplicaError,
    ReplicaPool,
)
from datatunerx_tpu.gateway.router import Router
from datatunerx_tpu.obs.metrics import (
    exemplars_requested,
    set_build_info,
    set_uptime,
)
from datatunerx_tpu.obs.slo import SLOEvaluator, default_slos, load_slos
from datatunerx_tpu.obs.trace import Span, Tracer, TraceStore
from datatunerx_tpu.serving.local_backend import _free_port
from datatunerx_tpu.tenancy import load_tenants


# an import may PARK on the target's scheduler this long waiting for
# capacity (BatchedEngine.import_session wait_s default) — the claim wait
# must outlast it, or a session that imports late degrades to a cold
# re-prefill PLUS an orphaned continuation
HANDOFF_IMPORT_WAIT_S = 10.0
HANDOFF_CLAIM_WAIT_S = HANDOFF_IMPORT_WAIT_S + 2.0


class _HandoffBuffer:
    """Imported session continuations parked between the drain thread
    (which exports from the source and imports on the target) and the
    request thread whose stream just died with the migrated marker. One
    entry per trace id, claimed once; ``claim`` can WAIT because the
    stream's death races the import completing. Entries unclaimed past
    the TTL are swept (streams closed) on every put AND claim — any
    gateway traffic at all unpins an abandoned handoff's HTTP response."""

    def __init__(self, ttl_s: float = 120.0):
        self.ttl_s = ttl_s
        self._cond = threading.Condition()
        self._entries: dict = {}

    @staticmethod
    def _close(entries):
        for e in entries:
            close = getattr(e.get("stream"), "close", None)
            if callable(close):
                try:
                    close()
                except Exception:  # noqa: BLE001 — cleanup is best-effort
                    pass

    def _sweep_locked(self):
        now = time.monotonic()
        return [self._entries.pop(tid)
                for tid in [t for t, e in self._entries.items()
                            if now - e["t"] > self.ttl_s]]

    def put(self, trace_id: str, entry: dict):
        if not trace_id:
            # unclaimable (payload with no trace id): release the imported
            # continuation immediately — nobody can ever splice it
            self._close([entry])
            return
        entry["t"] = time.monotonic()
        with self._cond:
            stale = self._sweep_locked()
            self._entries[trace_id] = entry
            self._cond.notify_all()
        self._close(stale)

    def claim(self, trace_id: str, wait_s: float = 0.0) -> Optional[dict]:
        with self._cond:
            stale = self._sweep_locked()
        self._close(stale)  # outside the lock: close() may do socket work
        deadline = time.monotonic() + wait_s
        with self._cond:
            while True:
                entry = self._entries.pop(trace_id, None)
                if entry is not None:
                    return entry
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self._cond.wait(left)


class Gateway:
    """Transport-independent core: tests drive this directly; the HTTP
    handler below is a thin shell around it."""

    def __init__(self, pool: ReplicaPool, policy: str = "least_busy",
                 admission: Optional[AdmissionController] = None,
                 max_attempts: int = 3, model_name: str = "",
                 trace_ring: int = 256,
                 trace_log_path: Optional[str] = None,
                 slos=None, session_handoff: bool = True,
                 prefill_threshold: int = 0,
                 fleet_prefix_bytes: int = 0,
                 fleet_handoff: bool = False,
                 fleet_spill: bool = False,
                 tenants=None):
        self.pool = pool
        self.router = Router(pool, policy=policy,
                             prefill_threshold=prefill_threshold)
        self.admission = admission or AdmissionController()
        # fleet-true admission: tie 429/Retry-After to the fleet's LIVE
        # free-block sum whenever the replicas report a paged pool (dense
        # fleets return None and the static token budget stays the gate).
        # Only wired when the controller wasn't given its own source —
        # tests injecting a custom fn keep it.
        if getattr(self.admission, "fleet_blocks_fn", None) is None \
                and hasattr(self.admission, "fleet_blocks_fn"):
            self.admission.fleet_blocks_fn = self.fleet_kv_blocks
        self.max_attempts = max_attempts
        self.model_name = model_name
        self.registry = Registry()
        self.started_at = time.monotonic()
        self._requests = self.registry.counter(
            "dtx_gateway_requests_total", "Requests by terminal HTTP code.")
        self._failovers = self.registry.counter(
            "dtx_gateway_failovers_total",
            "Requests retried on another replica after a replica fault.")
        self._latency = self.registry.histogram(
            "dtx_gateway_request_latency_seconds",
            "End-to-end request latency through the gateway.")
        self._queue_wait = self.registry.histogram(
            "dtx_gateway_queue_wait_ms",
            "Admission + routing time before the first replica attempt "
            "(time a request spends queued at the gateway, not serving).",
            buckets=MS_BUCKETS)
        # the gateway's half of a request's trace: spans for admission /
        # route / retry / stream land here, keyed by the X-DTX-Trace-Id the
        # handler mints; GET /debug/trace/<id> merges the replica's half in
        self.trace_store = TraceStore(capacity=trace_ring,
                                      jsonl_path=trace_log_path)
        self.tracer = Tracer(store=self.trace_store)
        self.replica_set = None  # ManagedReplicaSet when the gateway spawns
        # serializes snapshot-gauge restating (concurrent scrapes would race
        # clear/set and drop per-replica series) and the shed-delta tracking
        self._scrape_lock = threading.Lock()
        self._shed_at_last_hint = 0
        # active canary promotion (experiment/promotion.py), single-flight;
        # started by POST /admin/promote or ExperimentRunner
        self.promotion = None
        self._promotion_lock = threading.Lock()
        # SLO plane (obs/slo.py): objectives over this registry's own
        # request histograms/counters, judged at GET /debug/slo and restated
        # as dtx_slo_* gauges on every /metrics scrape — the same evaluator
        # class the promotion guard and the replay epilogue run
        self.slo = SLOEvaluator(self.registry, slos or default_slos("gateway"))
        # operator-configured SLOs also drive /autoscale off burn rate
        # instead of raw p95 (defaults stay advisory-only: they are loose
        # bootstrap objectives, not a scaling contract)
        self.slo_configured = slos is not None
        # KV migration fabric: drain exports every in-flight session from
        # the leaving replica and imports it elsewhere; the dying streams
        # splice the imported continuation instead of re-prefilling
        self.session_handoff = session_handoff
        self._handoff = _HandoffBuffer()
        self.last_handoff: Optional[dict] = None
        self._handoffs = self.registry.counter(
            "dtx_gateway_handoff_total",
            "Drain/failover session handoffs by outcome (imported = "
            "resumed re-prefill-free elsewhere, cold = fell back to the "
            "re-prefill path, export_failed / unsupported = source could "
            "not export).")
        self._splices = self.registry.counter(
            "dtx_gateway_handoff_splices_total",
            "Client streams spliced onto an imported continuation, by "
            "outcome.")
        self._h_handoff = self.registry.histogram(
            "dtx_gateway_handoff_ms",
            "Per-session export→import handoff time (trace exemplars "
            "resolve at /debug/trace/<id>).",
            buckets=MS_BUCKETS)
        # disaggregated fleet plane (datatunerx_tpu/fleet/): prefix tier
        # + prefill→decode handoff + peer spill, each flag-gated. With
        # every flag at its default the plane is never constructed and
        # the gateway is byte-identical to a fleet-less build.
        self.fleet: Optional[FleetPlane] = None
        if fleet_prefix_bytes > 0 or fleet_handoff or fleet_spill:
            self.fleet = FleetPlane(
                pool, self._handoff.put,
                prefix_budget_bytes=fleet_prefix_bytes,
                handoff=fleet_handoff, spill=fleet_spill)
        # multi-tenant QoS plane (datatunerx_tpu/tenancy/): same gating
        # contract as the fleet plane — no tenant config means no
        # directory, no per-tenant admission pricing, no dtx_gateway_
        # tenant_* families, and an exposition byte-identical to a
        # tenancy-less build.
        self.tenants = load_tenants(tenants)
        # adapter → checkpoint catalog for prefetch-on-route, merged
        # lazily (and stickily) from replicas' adapter_inventory() — the
        # serving side's adapter_catalog() over the wire
        self._adapter_catalog: dict = {}
        self._catalog_lock = threading.Lock()
        self._tenant_lock = threading.Lock()
        # per-tenant TTFT observations (ms) for the /autoscale burn
        # branch; bounded deques keyed by directory names only
        self._tenant_ttft: dict = {}
        self._tenant_outcomes: dict = {}  # (tenant, outcome) -> count
        # distinct tenant label values, capped like router.adapter_requests
        # (PR 10): every name becomes a Prometheus series, and a directory
        # grown through POST /admin/tenants must not grow the exposition
        # without bound
        self._tenant_seen: set = set()
        self._tenant_series_cap = 1024
        self._prefetches = 0
        # live fire-and-forget workers (adapter prefetch, promotion run):
        # pruned on spawn, joined by close() so no worker outlives the
        # gateway and ticks against torn-down replicas in tests
        self._worker_threads: list = []
        self._promotion_thread = None

    # -------------------------------------------------------------- routing
    def _kwargs_from(self, req: dict) -> dict:
        return dict(
            max_new_tokens=int(req.get("max_tokens", 128)),
            temperature=float(req.get("temperature", 0.0)),
            top_p=float(req.get("top_p", 1.0)),
        )

    def _adapter_from(self, req: dict) -> str:
        adapter = req.get("model") or ""
        if adapter and adapter == self.model_name:
            return ""
        return adapter

    def _route(self, messages, adapter, session_id, tried,
               on_event=None, prefer_spec: bool = False,
               prompt_tokens: Optional[int] = None) -> Replica:
        return self.router.route(messages=messages, adapter=adapter,
                                 session_id=session_id, exclude=tried,
                                 on_event=on_event, prefer_spec=prefer_spec,
                                 prompt_tokens=prompt_tokens)

    @staticmethod
    def _spec_friendly(kwargs: dict) -> bool:
        """Greedy requests are the spec-friendliest traffic (deterministic
        proposals verify best and the guarantee is token-exactness, not
        just distribution-exactness) — prefer replicas whose speculative
        plane is live for them."""
        return float(kwargs.get("temperature", 0.0) or 0.0) <= 0.0

    def _replica_failed(self, replica: Replica):
        replica.breaker.record_failure()
        self.router.forget_replica(replica.name)

    # -------------------------------------------------------------- tenancy
    def _resolve_tenant(self, tenant: str, adapter: str):
        """The request's TenantSpec (header first, adapter mapping second)
        or None — anonymous requests take the pre-tenancy path exactly."""
        if self.tenants is None:
            return None
        return self.tenants.resolve(tenant=tenant, adapter=adapter)

    def _admission_tenant(self, spec) -> Optional[dict]:
        """A resolved tenant's admission pricing row; share_total is the
        directory-wide Σshares the weighted-fair cap divides by."""
        if spec is None:
            return None
        return {"name": spec.name, "share": spec.share,
                "share_total": sum(self.tenants.shares().values()) or 1.0,
                "kv_block_quota": spec.kv_block_quota}

    def _catalog_checkpoint(self, adapter: str) -> Optional[str]:
        """adapter → checkpoint, merged lazily (and stickily) from the
        replicas: in-process replicas expose the engine's FULL
        adapter_catalog(); remote ones their resident inventory."""
        with self._catalog_lock:
            ckpt = self._adapter_catalog.get(adapter)
        if ckpt:
            return ckpt
        for r in self.pool.replicas():
            cat = None
            fn = getattr(getattr(r, "engine", None), "adapter_catalog",
                         None)
            if callable(fn):
                try:
                    cat = dict(fn())
                except Exception:  # noqa: BLE001 — catalog is best-effort
                    cat = None
            if cat is None:
                try:
                    cat = r.adapter_inventory()
                except Exception:  # noqa: BLE001
                    cat = None
            if cat:
                with self._catalog_lock:
                    for n, c in cat.items():
                        self._adapter_catalog.setdefault(n, c)
        with self._catalog_lock:
            return self._adapter_catalog.get(adapter)

    def note_adapter_checkpoint(self, adapter: str, checkpoint: str):
        """Seed the prefetch catalog (admin adapter registration path)."""
        if adapter and checkpoint:
            with self._catalog_lock:
                self._adapter_catalog[adapter] = checkpoint

    def _maybe_prefetch(self, adapter: str, root: Span):
        """Prefetch-on-route: when NO replica holds the adapter resident,
        fire its load on the least-loaded available replica in parallel
        with admission — by the time the request clears admission and
        routes, the load-on-miss it would have paid is already in
        flight. Purely an optimization: any fault is swallowed and the
        request proceeds down the ordinary load-on-miss path."""
        try:
            candidates = self.pool.available()
            if not candidates:
                return
            for r in candidates:
                try:
                    st = r.stats_snapshot()
                except Exception:  # noqa: BLE001 — stats are advisory
                    st = {}
                if adapter in (st.get("resident_adapters") or ()):
                    return  # warm somewhere — the router will find it
            ckpt = self._catalog_checkpoint(adapter)
            if not ckpt:
                return
            target = min(candidates, key=lambda c: c.inflight)
            root.event("adapter_prefetch", replica=target.name,
                       adapter=adapter)
            with self._tenant_lock:
                self._prefetches += 1
            t = threading.Thread(
                target=self._prefetch_worker, args=(target, adapter, ckpt),
                name=f"dtx-prefetch-{adapter}", daemon=True)
            with self._tenant_lock:
                self._worker_threads = [
                    w for w in self._worker_threads if w.is_alive()]
                self._worker_threads.append(t)
            t.start()
        except Exception:  # noqa: BLE001 — prefetch must never fail a request
            pass

    @staticmethod
    def _prefetch_worker(replica, adapter: str, checkpoint: str):
        try:
            replica.preload_adapter(adapter, checkpoint)
        except Exception:  # noqa: BLE001 — best-effort warm
            pass

    def _tenant_observe(self, name: str, outcome: str,
                        ttft_ms: Optional[float] = None):
        if self.tenants is None or not name:
            return
        with self._tenant_lock:
            if name not in self._tenant_seen:
                if len(self._tenant_seen) >= self._tenant_series_cap:
                    return
                self._tenant_seen.add(name)
            key = (name, outcome)
            self._tenant_outcomes[key] = self._tenant_outcomes.get(key, 0) + 1
            if ttft_ms is not None:
                dq = self._tenant_ttft.get(name)
                if dq is None:
                    dq = self._tenant_ttft[name] = deque(maxlen=256)
                dq.append(float(ttft_ms))

    def _tenant_ttft_p95(self, name: str) -> Optional[float]:
        with self._tenant_lock:
            window = list(self._tenant_ttft.get(name) or ())
        if not window:
            return None
        window.sort()
        return window[min(len(window) - 1, int(0.95 * len(window)))]

    def _tenant_burn(self) -> Optional[dict]:
        """Worst per-tenant TTFT-objective burn, shaped like _slo_burn's
        verdict — tenants with a ttft_p95_ms objective drive /autoscale
        even when no gateway-wide SLO doc is configured."""
        if self.tenants is None:
            return None
        worst: Optional[dict] = None
        for name in self.tenants.names():
            spec = self.tenants.get(name)
            if spec is None or spec.ttft_p95_ms <= 0:
                continue
            p95 = self._tenant_ttft_p95(name)
            if p95 is None:
                continue
            burn = p95 / spec.ttft_p95_ms
            if worst is None or burn > worst["burn_rate"]:
                worst = {"name": f"tenant/{name}:ttft_p95_ms",
                         "burn_rate": round(burn, 4)}
        return worst

    # -------------------------------------------------------------- tracing
    def _begin_request_span(self, name: str, trace_id: str,
                            adapter: str) -> Span:
        """Open the gateway's root span for one request. Explicit spans
        (Tracer.start / finish), not the contextvar manager: chat_stream is
        a generator and a ``with`` block suspending across yields would
        leak the contextvar into the HTTP handler's context."""
        sp = self.tracer.start(name, trace_id=trace_id)
        if adapter:
            sp.set(adapter=adapter)
        return sp

    def _finish_request_span(self, sp: Span, status: str = "ok",
                             error: Optional[BaseException] = None):
        if error is not None and "error" not in sp.attrs:
            sp.set(error=str(error) or type(error).__name__)
        self.tracer.finish(sp, status=status)

    # ----------------------------------------------------------- non-stream
    def chat(self, req: dict, trace_id: str = "",
             session_id: Optional[str] = None, tenant: str = "") -> str:
        """Complete a non-streamed chat request with failover. Raises
        Overloaded / NoReplicaAvailable / ValueError(client error)."""
        messages = req.get("messages")
        if not isinstance(messages, list) or not messages:
            raise ValueError("messages must be a non-empty list")
        adapter = self._adapter_from(req)
        kwargs = self._kwargs_from(req)
        if adapter:
            kwargs["adapter"] = adapter
        t_spec = self._resolve_tenant(tenant, adapter)
        if t_spec is not None:
            kwargs["tenant"] = t_spec.name
        t0 = time.monotonic()
        root = self._begin_request_span("gateway.request", trace_id, adapter)
        if t_spec is not None:
            root.set(tenant=t_spec.name)
        try:
            if self.tenants is not None and adapter:
                # fired BEFORE admission so the adapter load overlaps it
                self._maybe_prefetch(adapter, root)
            admit_kw = ({"tenant": self._admission_tenant(t_spec)}
                        if t_spec is not None else {})
            with self.admission.try_admit(messages, **admit_kw) as ticket:
                root.event("admitted")
                tried: set = set()
                last: Optional[Exception] = None
                expect_handoff = False
                for attempt in range(self.max_attempts):
                    # a drained-away session leaves its imported
                    # continuation here — splice it instead of re-routing
                    # (and re-prefilling) the whole request
                    entry = self._claim_handoff(root.trace_id,
                                                expect_handoff)
                    expect_handoff = False
                    if entry is not None:
                        try:
                            # emitted="" makes the splice yield the full
                            # text: migrated tail + continuation
                            text = "".join(
                                self._consume_splice(entry, "", root))
                        except ReplicaError as e:
                            last = e
                            continue
                        self._latency.observe(time.monotonic() - t0,
                                              trace_id=root.trace_id)
                        if t_spec is not None:
                            self._tenant_observe(
                                t_spec.name, "ok",
                                ttft_ms=(time.monotonic() - t0) * 1e3)
                        root.set(replica=entry.get("target"),
                                 attempts=attempt + 1, handoff=True)
                        self._finish_request_span(root)
                        return text
                    replica = self._route(
                        messages, adapter, session_id, tried,
                        on_event=root.event,
                        prefer_spec=self._spec_friendly(kwargs),
                        # the admission estimate IS the routing signal:
                        # tokenizer-exact when one is wired, else the
                        # calibrated chars-per-token heuristic (PR 15)
                        prompt_tokens=ticket.tokens)
                    tried.add(replica.name)
                    root.event("route", replica=replica.name,
                               attempt=attempt)
                    if attempt == 0:
                        self._queue_wait.observe(
                            (time.monotonic() - t0) * 1e3,
                            trace_id=root.trace_id)
                    replica.acquire()
                    t_attempt = time.monotonic()
                    try:
                        text = replica.chat(messages, trace_id=root.trace_id,
                                            **kwargs)
                        replica.breaker.record_success()
                        self._calibrate_usage(replica)
                        replica.record_outcome(
                            True, (time.monotonic() - t_attempt) * 1e3)
                        self._latency.observe(time.monotonic() - t0,
                                              trace_id=root.trace_id)
                        if t_spec is not None:
                            self._tenant_observe(
                                t_spec.name, "ok",
                                ttft_ms=(time.monotonic() - t0) * 1e3)
                        root.set(replica=replica.name, attempts=attempt + 1)
                        self._finish_request_span(root)
                        return text
                    except ReplicaError as e:
                        if self.session_handoff and MIGRATED_MARKER in str(e):
                            # not a fault: the session was exported off a
                            # draining replica; next pass splices it
                            expect_handoff = True
                            root.event("handoff_pending",
                                       replica=replica.name)
                            last = e
                            continue
                        replica.record_outcome(
                            False, (time.monotonic() - t_attempt) * 1e3)
                        self._replica_failed(replica)
                        self._failovers.inc()
                        root.event("retry", replica=replica.name,
                                   error=str(e))
                        last = e
                    finally:
                        replica.release()
                raise NoReplicaAvailable(
                    f"all {len(tried)} attempted replicas failed: {last}")
        except BaseException as e:
            if t_spec is not None:
                self._tenant_observe(
                    t_spec.name,
                    "shed" if isinstance(e, Overloaded) else "error")
            self._finish_request_span(root, status="error", error=e)
            raise

    # --------------------------------------------------------------- stream
    def chat_stream(self, req: dict, trace_id: str = "",
                    session_id: Optional[str] = None, tenant: str = ""):
        """Yield text deltas with MID-STREAM failover: when a replica dies
        after emitting part of the answer, the request restarts on another
        replica and the already-emitted character prefix is skipped — the
        client's stream continues where it stopped. (Deterministic decode
        gives byte-identical resumption; sampled requests resume the same
        way but may diverge, which beats a dead stream.)"""
        messages = req.get("messages")
        if not isinstance(messages, list) or not messages:
            raise ValueError("messages must be a non-empty list")
        adapter = self._adapter_from(req)
        kwargs = self._kwargs_from(req)
        if adapter:
            kwargs["adapter"] = adapter
        t_spec = self._resolve_tenant(tenant, adapter)
        if t_spec is not None:
            kwargs["tenant"] = t_spec.name
        t0 = time.monotonic()
        root = self._begin_request_span("gateway.stream", trace_id, adapter)
        if t_spec is not None:
            root.set(tenant=t_spec.name)
        try:
            if self.tenants is not None and adapter:
                # fired BEFORE admission so the adapter load overlaps it
                self._maybe_prefetch(adapter, root)
            admit_kw = ({"tenant": self._admission_tenant(t_spec)}
                        if t_spec is not None else {})
            with self.admission.try_admit(messages, **admit_kw) as ticket:
                root.event("admitted")
                emitted = ""
                t_first: Optional[float] = None
                tried: set = set()
                expect_handoff = False
                for attempt in range(self.max_attempts):
                    # a drained-away session leaves its imported
                    # continuation in the handoff buffer: splice it onto
                    # the client's stream instead of re-prefilling
                    entry = self._claim_handoff(root.trace_id,
                                                expect_handoff)
                    expect_handoff = False
                    if entry is not None:
                        try:
                            for delta in self._consume_splice(entry,
                                                              emitted, root):
                                if not emitted:
                                    root.event("first_delta",
                                               replica=entry.get("target"))
                                    t_first = time.monotonic()
                                emitted += delta
                                yield delta
                        except ReplicaError:
                            continue  # next attempt: the cold path
                        self._latency.observe(time.monotonic() - t0,
                                              trace_id=root.trace_id)
                        if t_spec is not None:
                            self._tenant_observe(
                                t_spec.name, "ok",
                                ttft_ms=((t_first or time.monotonic())
                                         - t0) * 1e3)
                        root.set(replica=entry.get("target"),
                                 attempts=attempt + 1, chars=len(emitted),
                                 handoff=True)
                        self._finish_request_span(root)
                        return
                    replica = self._route(
                        messages, adapter, session_id, tried,
                        on_event=root.event,
                        prefer_spec=self._spec_friendly(kwargs),
                        prompt_tokens=ticket.tokens)
                    tried.add(replica.name)
                    root.event("route", replica=replica.name,
                               attempt=attempt)
                    if attempt == 0:
                        self._queue_wait.observe(
                            (time.monotonic() - t0) * 1e3,
                            trace_id=root.trace_id)
                    replica.acquire()
                    skip = len(emitted)
                    t_attempt = time.monotonic()
                    try:
                        for delta in replica.chat_stream(
                                messages, trace_id=root.trace_id, **kwargs):
                            if skip > 0:
                                if len(delta) <= skip:
                                    skip -= len(delta)
                                    continue
                                delta = delta[skip:]
                                skip = 0
                            if not emitted:
                                root.event("first_delta",
                                           replica=replica.name)
                                t_first = time.monotonic()
                            emitted += delta
                            yield delta
                        replica.breaker.record_success()
                        self._calibrate_usage(replica)
                        replica.record_outcome(
                            True, (time.monotonic() - t_attempt) * 1e3)
                        self._latency.observe(time.monotonic() - t0,
                                              trace_id=root.trace_id)
                        if t_spec is not None:
                            self._tenant_observe(
                                t_spec.name, "ok",
                                ttft_ms=((t_first or time.monotonic())
                                         - t0) * 1e3)
                        root.set(replica=replica.name, attempts=attempt + 1,
                                 chars=len(emitted))
                        self._finish_request_span(root)
                        return
                    except ReplicaError as e:
                        if self.session_handoff and MIGRATED_MARKER in str(e):
                            # the session was exported off a draining
                            # replica — not a fault; the next pass waits
                            # for (then splices) the imported continuation
                            expect_handoff = True
                            root.event("handoff_pending",
                                       replica=replica.name,
                                       resumed_at_char=len(emitted))
                            continue
                        replica.record_outcome(
                            False, (time.monotonic() - t_attempt) * 1e3)
                        self._replica_failed(replica)
                        self._failovers.inc()
                        root.event("retry", replica=replica.name,
                                   error=str(e),
                                   resumed_at_char=len(emitted))
                    finally:
                        replica.release()
                raise NoReplicaAvailable(
                    f"stream failed over {len(tried)} replicas")
        except BaseException as e:
            if t_spec is not None:
                self._tenant_observe(
                    t_spec.name,
                    "shed" if isinstance(e, Overloaded) else "error")
            # GeneratorExit included: a client hanging up mid-stream still
            # closes the gateway's span (status error, error=GeneratorExit)
            self._finish_request_span(root, status="error", error=e)
            raise

    # ----------------------------------------------------------- perplexity
    def perplexity(self, req: dict, trace_id: str = "") -> dict:
        import urllib.error

        replica = self._route(None, req.get("model") or "", None, set())
        if not isinstance(replica, HTTPReplica):
            raise NotImplementedError(
                "perplexity proxying requires HTTP replicas")
        replica.acquire()
        try:
            with replica._post("/perplexity", req, trace_id) as r:
                out = json.load(r)
            replica.breaker.record_success()
            return out
        except urllib.error.HTTPError as e:
            # 4xx is the CLIENT's error (same rule as chat): the replica is
            # fine — don't trip its breaker over someone's malformed body
            if 400 <= e.code < 500:
                try:
                    detail = json.load(e).get("error", e.reason)
                except Exception:  # noqa: BLE001
                    detail = e.reason
                raise ValueError(str(detail)) from e
            self._replica_failed(replica)
            raise ReplicaError(f"{replica.name}: HTTP {e.code}") from e
        except (OSError, ValueError) as e:
            self._replica_failed(replica)
            raise ReplicaError(f"{replica.name}: {e}") from e
        finally:
            replica.release()

    # ----------------------------------------------------- session handoff
    def _claim_handoff(self, trace_id: str,
                       expect: bool) -> Optional[dict]:
        """Pop this request's imported continuation, if any. When the
        previous attempt died with the migrated marker (``expect``), wait
        long enough to outlast the import's own park deadline — giving up
        earlier would re-prefill cold AND orphan the late import."""
        if not self.session_handoff:
            return None
        entry = self._handoff.claim(
            trace_id, wait_s=HANDOFF_CLAIM_WAIT_S if expect else 0.0)
        if entry is None or entry.get("failed"):
            return None  # tombstone = the drain already counted it cold
        return entry

    def _consume_splice(self, entry: dict, emitted: str, root: Span):
        """Relay an imported continuation, recording splice outcome and
        target-replica accounting — the shared core of chat's and
        chat_stream's handoff paths. Yields net-new text; raises
        ReplicaError (after failure accounting) when the target dies
        mid-splice, which the caller turns into a cold retry."""
        target = self.pool.get(entry.get("target") or "")
        root.event("handoff_splice", replica=entry.get("target"),
                   resumed_at_char=len(emitted))
        t_attempt = time.monotonic()
        try:
            for delta in self._splice_deltas(entry, emitted):
                yield delta
        except ReplicaError as e:
            self._splices.inc({"outcome": "failed"})
            root.event("handoff_splice_failed", error=str(e))
            if target is not None:
                target.record_outcome(
                    False, (time.monotonic() - t_attempt) * 1e3)
                self._replica_failed(target)
            raise
        self._splices.inc({"outcome": "ok"})
        if target is not None:
            target.breaker.record_success()
            target.record_outcome(
                True, (time.monotonic() - t_attempt) * 1e3)

    def _splice_deltas(self, entry: dict, emitted: str):
        """Yield ONLY net-new text for a spliced stream: reconcile the
        import's ``text_so_far`` against what the client already received
        (token-exact resume makes them equal; the skip logic absorbs any
        detokenization-boundary char drift), then relay the continuation."""
        pre = str(entry.get("text_so_far") or "")
        if len(pre) > len(emitted):
            yield pre[len(emitted):]
        skip = max(0, len(emitted) - len(pre))
        for delta in entry["stream"]:
            if skip > 0:
                if len(delta) <= skip:
                    skip -= len(delta)
                    continue
                delta = delta[skip:]
                skip = 0
            if delta:
                yield delta

    def handoff_sessions(self, source: Replica) -> dict:
        """Export every in-flight decode session from ``source`` and
        import each onto another available replica (adapter-resident
        targets first, like the router's preference). Imported sessions
        park in the handoff buffer keyed by trace id; the dying client
        streams splice them. Sessions no target can admit are counted
        cold and fall back to today's re-prefill failover."""
        summary: dict = {"source": source.name, "exported": 0,
                         "imported": 0, "cold": 0, "skipped": 0}
        # with the fleet handoff plane on, a drain also ships MID-chunked-
        # prefill tails (blocks written so far + remaining prompt) — a
        # prefill specialist drained mid-prompt re-prefills nothing.
        # Off (default) keeps the PR 15 behavior: mid-prefill slots are
        # skipped and their streams take the cold path.
        include_prefill = (self.fleet is not None
                           and self.fleet.handoff is not None)
        try:
            doc = source.export_sessions(include_prefill=include_prefill)
        except ReplicaError as e:
            self._handoffs.inc({"outcome": "export_failed"})
            summary["error"] = str(e)
            return summary
        if doc is None:
            self._handoffs.inc({"outcome": "unsupported"})
            summary["unsupported"] = True
            return summary
        skipped = doc.get("skipped") or []
        summary["skipped"] = len(skipped)
        if skipped:
            print(f"[gateway] handoff from {source.name}: "
                  f"{len(skipped)} session(s) not exportable "
                  f"({sorted({s.get('reason') for s in skipped})})",
                  flush=True)
        for payload in doc.get("sessions") or []:
            summary["exported"] += 1
            self._handoff_one(source, payload, summary)
        self.last_handoff = summary
        return summary

    def _handoff_one(self, source: Replica, payload: dict, summary: dict):
        t0 = time.monotonic()
        tid = str(payload.get("trace_id") or "")
        adapter = str(payload.get("adapter") or "")
        targets = [r for r in self.pool.available() if r.name != source.name]

        def _resident_rank(r: Replica) -> int:
            if not adapter:
                return 0
            try:
                res = r.stats().get("resident_adapters")
            except Exception:  # noqa: BLE001 — stats are advisory
                return 1
            return 0 if (res and adapter in res) else 1

        targets.sort(key=lambda r: (_resident_rank(r), r.name))
        last_err: Optional[Exception] = None
        for target in targets:
            try:
                res = target.import_session(payload)
            except ReplicaError as e:
                last_err = e
                continue
            if res is None:
                continue  # replica kind without the migration surface
            meta, stream = res
            self._handoff.put(tid, {
                "target": target.name, "meta": meta, "stream": stream,
                "text_so_far": str(meta.get("text_so_far") or "")})
            self._handoffs.inc({"outcome": "imported"})
            self._h_handoff.observe((time.monotonic() - t0) * 1e3,
                                    trace_id=tid or None)
            summary["imported"] += 1
            return
        # nothing could admit it: the dying stream takes the cold path
        # (a tombstone stops the claimer's wait immediately)
        self._handoff.put(tid, {"failed": True})
        self._handoffs.inc({"outcome": "cold"})
        summary["cold"] += 1
        if last_err is not None:
            summary["last_error"] = str(last_err)
            print(f"[gateway] handoff of {tid or '<no-trace>'} fell back "
                  f"cold: {last_err}", flush=True)

    def handoff_stats(self) -> dict:
        """Handoff outcome counts (the dtx_gateway_handoff_total series),
        plus splice outcomes — the replay harness's zero-drop assertion
        reads this."""
        out: dict = {}
        for key, value in self._handoffs.series().items():
            out[dict(key).get("outcome", "")] = int(value)
        for key, value in self._splices.series().items():
            out[f"splice_{dict(key).get('outcome', '')}"] = int(value)
        return out

    # -------------------------------------------------------- observability
    def trace(self, trace_id: str) -> Optional[dict]:
        """The merged end-to-end view of one trace: the gateway's own spans
        (admission/route/retry/stream) plus every replica's half (engine
        span timelines with per-request TTFT/TPOT), sorted by wall-clock
        start. None = no plane has seen the id."""
        doc = self.trace_store.get(trace_id)
        spans = list(doc["spans"]) if doc else []
        for replica in self.pool.replicas():
            try:
                rdoc = replica.fetch_trace(trace_id)
            except Exception:  # noqa: BLE001 — debug path, best-effort
                rdoc = None
            if rdoc:
                for sp in rdoc.get("spans", []):
                    # copy: an in-process replica hands back references into
                    # its live ring — annotating those in place would write
                    # gateway state into the engine's store
                    sp = dict(sp)
                    sp.setdefault("replica", replica.name)
                    spans.append(sp)
        if not spans:
            return None
        spans.sort(key=lambda s: s.get("start_ms") or 0)
        return {"trace_id": trace_id, "spans": spans}

    def profile(self, seconds: float, log_dir: Optional[str] = None,
                replica_name: str = "") -> dict:
        """Arm a jax.profiler window on one replica (named, or the first
        available). Raises NoReplicaAvailable / ReplicaError /
        NotImplementedError (replica kind has no profiler)."""
        if replica_name:
            replica = self.pool.get(replica_name)
            if replica is None:
                raise NoReplicaAvailable(f"no replica {replica_name!r}")
        else:
            available = self.pool.available()
            if not available:
                raise NoReplicaAvailable("no replica available to profile")
            replica = available[0]
        out = replica.start_profile(seconds, log_dir)
        if out is None:
            raise NotImplementedError(
                f"replica {replica.name!r} does not support profiling")
        return out

    def slo_report(self) -> dict:
        """The /debug/slo body: every declared objective judged over its
        burn-rate windows, from the same registry the request paths record
        into (one evaluator — obs/slo.py — shared with the promotion guard
        and the replay epilogue)."""
        return self.slo.report(plane="gateway")

    # -------------------------------------------------------------- reports
    def fleet_kv_blocks(self) -> Optional[dict]:
        """The fleet's live paged-KV inventory, summed over AVAILABLE
        replicas: {"free", "total", "block_size"} — the signal fleet-true
        admission and the /autoscale hint derive from. None when no
        available replica reports a block pool (dense fleet / no stats):
        callers fall back to their static heuristics."""
        free = total = block_size = 0
        for r in self.pool.available():
            try:
                st = r.stats()  # TTL-cached on HTTP replicas
            except Exception:  # noqa: BLE001 — stats are advisory
                continue
            if st.get("kv_blocks_total"):
                free += int(st.get("kv_blocks_free", 0))
                total += int(st["kv_blocks_total"])
                block_size = max(block_size,
                                 int(st.get("kv_block_size", 0) or 0))
        if total <= 0:
            return None
        return {"free": free, "total": total,
                "block_size": block_size or 16}

    def _calibrate_usage(self, replica: Replica):
        """After a successful attempt, fold the replica-reported tokenized
        prompt length into admission's chars-per-token estimate."""
        take = getattr(replica, "take_usage", None)
        cal = getattr(self.admission, "calibrate", None)
        if not callable(take) or not callable(cal):
            return
        usage = take()
        if usage:
            cal(usage.get("prompt_chars", 0), usage.get("prompt_tokens", 0))

    def healthy(self) -> bool:
        return len(self.pool.available()) > 0

    def autoscale(self) -> dict:
        shed_total = self.admission.shed_count
        with self._scrape_lock:
            shed_recent = shed_total - self._shed_at_last_hint
            self._shed_at_last_hint = shed_total
        slo_burn = self._slo_burn() if self.slo_configured else None
        # a tenant with a ttft_p95_ms objective burns the same branch —
        # the hint's reason names the tenant and objective
        t_burn = self._tenant_burn()
        if t_burn is not None and (slo_burn is None
                                   or t_burn["burn_rate"]
                                   > slo_burn["burn_rate"]):
            slo_burn = t_burn
        return autoscale_hint(
            replicas=len(self.pool.replicas()),
            available_replicas=len(self.pool.available()),
            queue_depth=self.admission.depth,
            queued_tokens=self.admission.queued_tokens,
            shed_count=shed_total,
            shed_recent=shed_recent,
            p95_latency_s=self._latency.percentile(0.95),
            slo_burn=slo_burn,
            # the hint derives from blocks, not slots: the same live
            # free-block sum admission sheds on
            fleet_blocks=self.fleet_kv_blocks(),
        )

    def _slo_burn(self) -> Optional[dict]:
        """The worst-burning configured objective, for the autoscale hint.
        Per the multi-window page rule, an SLO's effective burn is the MIN
        over its populated windows (every window must burn to page); the
        hint reports the max of those across objectives."""
        worst: Optional[dict] = None
        try:
            self.slo.sample()
            for doc in self.slo.evaluate():
                populated = [w for w in doc["windows"] if not w["no_data"]]
                if not populated:
                    continue
                burn = min(w["burn_rate"] for w in populated)
                if worst is None or burn > worst["burn_rate"]:
                    worst = {"name": doc["name"],
                             "burn_rate": round(burn, 4)}
        except Exception:  # noqa: BLE001 — a broken SLO eval must not 500 /autoscale
            return None
        return worst

    def record_request(self, code: int):
        self._requests.inc({"code": str(code)})

    def metrics_text(self, with_exemplars: bool = True) -> str:
        with self._scrape_lock:
            return self._metrics_text_locked(with_exemplars)

    def _metrics_text_locked(self, with_exemplars: bool = True) -> str:
        # re-state snapshot gauges at scrape time
        set_build_info(self.registry, "gateway")
        set_uptime(self.registry, "gateway", self.started_at)
        # dtx_slo_* verdict gauges: SAMPLE first so window baselines keep
        # advancing even when nothing polls /debug/slo and no background
        # sampler runs — a scrape-only deployment still gets honest windows
        self.slo.sample()
        self.slo.restate_gauges(self.slo.evaluate())
        g = self.registry.gauge
        g("dtx_gateway_trace_open_spans",
          "Spans opened and not yet finished (a growing value means "
          "leaking request handlers; orphans reap at 10 min).").set(
            self.tracer.open_count())
        g("dtx_gateway_up", "1 when at least one replica is available.").set(
            1 if self.healthy() else 0)
        g("dtx_gateway_queue_depth",
          "Admitted requests currently queued or in flight.").set(
            self.admission.depth)
        g("dtx_gateway_queued_tokens",
          "Estimated prefill tokens admitted and not yet released.").set(
            self.admission.queued_tokens)
        shed = self.registry.counter(
            "dtx_gateway_shed_total",
            "Requests rejected with 429 by admission control.")
        shed.set(self.admission.shed_count)
        circuit = g("dtx_gateway_replica_circuit_state",
                    "One-hot per-replica breaker state "
                    "(closed/half_open/open).")
        up = g("dtx_gateway_replica_up",
               "Per-replica health-probe verdict (0 = draining too).")
        busy = g("dtx_gateway_replica_inflight",
                 "Gateway-side in-flight requests per replica.")
        blocks_free = g("dtx_gateway_replica_kv_blocks_free",
                        "Free paged KV-cache blocks per replica — the "
                        "admission headroom gauge (0 labels absent on "
                        "dense-cache replicas).")
        blocks_reserved = g("dtx_gateway_replica_kv_blocks_reserved",
                            "Reserved (allocated) paged KV-cache blocks "
                            "per replica, restated from the same stats "
                            "snapshot as the free gauge — together they "
                            "are the fleet-true admission ledger.")
        weight = g("dtx_gateway_replica_weight",
                   "Traffic weight per replica (canary promotion: the "
                   "router's smooth-WRR share when weights are "
                   "non-uniform; 0 = receives no new requests).")
        attempts = self.registry.counter(
            "dtx_gateway_replica_attempts_total",
            "Routed attempts per replica by outcome (ok/error) — the "
            "promotion guard's error-rate source, restated at scrape "
            "time from the per-replica outcome windows.")
        # adapter plane: residency-preference routing outcomes + per-adapter
        # demand (restated from the router's counters at scrape time)
        a_routes = self.registry.counter(
            "dtx_gateway_adapter_routes_total",
            "Adapter-request routing outcomes: resident = cache-locality "
            "hit, load_miss = routed to a replica that must load-on-miss, "
            "blind = no replica reported the adapter.")
        a_reqs = self.registry.counter(
            "dtx_gateway_adapter_requests_total",
            "Requests routed per adapter name.")
        a_resident = g("dtx_gateway_adapter_resident_replicas",
                       "Replicas whose pool currently holds each adapter "
                       "(from replica stats snapshots).")
        # speculative decoding: the per-replica acceptance-rate gauge the
        # spec-friendly routing preference reads, plus preference outcomes
        spec_rate = g("dtx_gateway_replica_spec_accept_rate",
                      "Per-replica speculative-decode acceptance-rate EMA "
                      "(labels absent on replicas without a draft model "
                      "or with no observations yet).")
        spec_routes = self.registry.counter(
            "dtx_gateway_spec_routes_total",
            "Spec-friendly (greedy) routing outcomes: preferred = "
            "narrowed to spec-enabled replicas, blind = no narrowing "
            "possible (none or all candidates run spec).")
        # disaggregated routing: long prompts steered to prefill
        # specialists / short ones away, plus each replica's declared role
        role_routes = self.registry.counter(
            "dtx_gateway_role_routes_total",
            "Role-aware routing outcomes: prefill = long prompt steered "
            "to a prefill specialist, decode = short prompt steered away "
            "from them, blind = no role signal narrowed the candidates.")
        replica_role = g("dtx_gateway_replica_role",
                         "Per-replica declared disaggregation role, "
                         "one-hot by label (scraped from "
                         "dtx_serving_role on remote replicas).")
        circuit.clear()
        up.clear()
        busy.clear()
        blocks_free.clear()
        blocks_reserved.clear()
        weight.clear()
        attempts.clear()
        a_routes.clear()
        a_reqs.clear()
        a_resident.clear()
        spec_rate.clear()
        spec_routes.clear()
        role_routes.clear()
        replica_role.clear()
        with self.router._lock:
            routes = dict(self.router.adapter_routes)
            per_adapter = dict(self.router.adapter_requests)
            s_routes = dict(getattr(self.router, "spec_routes", {}))
            r_routes = dict(getattr(self.router, "role_routes", {}))
        for outcome, n in sorted(s_routes.items()):
            spec_routes.set(n, {"outcome": outcome})
        for outcome, n in sorted(r_routes.items()):
            role_routes.set(n, {"outcome": outcome})
        for outcome, n in sorted(routes.items()):
            a_routes.set(n, {"outcome": outcome})
        for name, n in sorted(per_adapter.items()):
            a_reqs.set(n, {"adapter": name})
        residency: dict = {}
        for r in self.pool.replicas():
            state = r.breaker.state
            for s in ("closed", "half_open", "open"):
                circuit.set(1 if s == state else 0,
                            {"replica": r.name, "state": s})
            up.set(1 if r.available() else 0, {"replica": r.name})
            busy.set(r.inflight, {"replica": r.name})
            try:
                # snapshot, not stats(): a scrape must never block on a hung
                # replica's 2s-timeout fetch — routing keeps the cache warm
                st = r.stats_snapshot()
            except Exception:  # noqa: BLE001 — stats are advisory
                st = {}
            if st.get("kv_blocks_total"):
                blocks_free.set(st.get("kv_blocks_free", 0),
                                {"replica": r.name})
                blocks_reserved.set(
                    st["kv_blocks_total"] - st.get("kv_blocks_free", 0),
                    {"replica": r.name})
            for a in st.get("resident_adapters") or ():
                if a:
                    residency[a] = residency.get(a, 0) + 1
            if st.get("spec_enabled") and \
                    st.get("spec_accept_rate") is not None:
                spec_rate.set(round(st["spec_accept_rate"], 4),
                              {"replica": r.name})
            weight.set(round(getattr(r, "weight", 1.0), 6),
                       {"replica": r.name})
            replica_role.set(1, {"replica": r.name,
                                 "role": getattr(r, "role", "mixed")})
            out = r.outcome_stats()
            attempts.set(out["requests"] - out["errors"],
                         {"replica": r.name, "outcome": "ok"})
            attempts.set(out["errors"],
                         {"replica": r.name, "outcome": "error"})
        for a, n in sorted(residency.items()):
            a_resident.set(n, {"adapter": a})
        if self.fleet is not None:
            self._restate_fleet_locked()
        if self.tenants is not None:
            self._restate_tenants_locked()
        return self.registry.expose(with_exemplars=with_exemplars)

    def _restate_tenants_locked(self):
        """dtx_gateway_tenant_* series, restated from the tenancy plane's
        counters at scrape time. Only emitted when a tenant directory is
        configured — a tenant-less gateway's exposition is unchanged down
        to the byte. Label values are resolved directory names plus the
        bounded outcome enum, so cardinality is operator-controlled."""
        g = self.registry.gauge
        t_reqs = self.registry.counter(
            "dtx_gateway_tenant_requests_total",
            "Requests per tenant by terminal outcome (ok/shed/error).")
        t_tokens = g("dtx_gateway_tenant_inflight_tokens",
                     "Admitted prefill tokens currently held per tenant "
                     "(the weighted-fair share ledger).")
        t_blocks = g("dtx_gateway_tenant_inflight_blocks",
                     "Admission-priced KV blocks currently held per "
                     "tenant (the kv_block_quota ledger).")
        t_share = g("dtx_gateway_tenant_share",
                    "Configured weighted-fair share per tenant.")
        t_ttft = g("dtx_gateway_tenant_ttft_p95_ms",
                   "Observed per-tenant TTFT p95 over the rolling "
                   "window (absent until a tenant has traffic).")
        prefetch = self.registry.counter(
            "dtx_gateway_adapter_prefetch_total",
            "Adapter loads fired on route (prefetch-on-route) in "
            "parallel with admission.")
        t_reqs.clear()
        t_tokens.clear()
        t_blocks.clear()
        t_share.clear()
        t_ttft.clear()
        with self._tenant_lock:
            outcomes = dict(self._tenant_outcomes)
            prefetch.set(self._prefetches)
        for (name, outcome), n in sorted(outcomes.items()):
            t_reqs.set(n, {"tenant": name, "outcome": outcome})
        usage = (self.admission.tenant_usage()
                 if hasattr(self.admission, "tenant_usage") else {})
        for name, n in sorted((usage.get("tokens") or {}).items()):
            t_tokens.set(n, {"tenant": name})
        for name, n in sorted((usage.get("blocks") or {}).items()):
            t_blocks.set(n, {"tenant": name})
        for name in self.tenants.names():
            spec = self.tenants.get(name)
            if spec is None:
                continue
            t_share.set(spec.share, {"tenant": name})
            p95 = self._tenant_ttft_p95(name)
            if p95 is not None:
                t_ttft.set(round(p95, 3), {"tenant": name})

    def _restate_fleet_locked(self):
        """dtx_fleet_* series, restated from the fleet plane's counters
        at scrape time (same pattern as the router's). Only emitted when
        the plane exists — a fleet-less gateway's exposition is unchanged
        down to the byte."""
        g = self.registry.gauge
        fstats = self.fleet.stats()
        prefix = fstats.get("prefix")
        if prefix is not None:
            g("dtx_fleet_prefix_entries",
              "Prefix payloads resident in the fleet-shared tier "
              "directory.").set(prefix["entries"])
            g("dtx_fleet_prefix_bytes",
              "Approximate directory footprint of the fleet prefix tier "
              "(b64 wire bytes; LRU-evicted past the budget).").set(
                prefix["bytes"])
            pub = self.registry.counter(
                "dtx_fleet_prefix_publishes_total",
                "Prefix entries pulled from a replica into the fleet "
                "tier (first prefill of a shared prompt).")
            hits = self.registry.counter(
                "dtx_fleet_prefix_hits_total",
                "Peer imports that activated a fleet prefix entry — "
                "that replica's next matching request prefills zero "
                "chunks.")
            misses = self.registry.counter(
                "dtx_fleet_prefix_misses_total",
                "Prefix pushes a peer refused or failed (no free "
                "slot/blocks, adapter not loaded there, transport "
                "fault).")
            pub.set(prefix["publishes"])
            hits.set(prefix["hits"])
            misses.set(prefix["misses"])
        handoff = fstats.get("handoff")
        if handoff is not None:
            c = self.registry.counter(
                "dtx_fleet_handoff_total",
                "Prefill→decode re-homings by outcome (ok = continuation "
                "parked on a decode peer, cold = no peer could admit, "
                "skipped = still mid-prefill this tick, none = no "
                "decode-side peer existed).")
            c.clear()
            for outcome, n in sorted(handoff.items()):
                c.set(n, {"outcome": outcome})
        spill = fstats.get("spill")
        if spill is not None:
            c = self.registry.counter(
                "dtx_fleet_spill_total",
                "Parked-session spills to a peer by outcome (ok = "
                "re-homed token-exactly, refused = every peer 409'd, "
                "error = transport/drop fault, skipped = no eligible "
                "peer).")
            c.clear()
            for outcome, n in sorted(spill.items()):
                c.set(n, {"outcome": outcome})

    # ------------------------------------------------------------ promotion
    def set_weight(self, name: str, weight: float) -> bool:
        """Set one replica's traffic weight (router smooth-WRR share when
        weights are non-uniform; 0 = no new requests)."""
        r = self.pool.get(name)
        if r is None:
            return False
        r.weight = max(0.0, float(weight))
        return True

    def start_promotion(self, canary: str, config: Optional[dict] = None,
                        metrics=None, background: bool = True):
        """Start a canary promotion (experiment/promotion.py): weighted
        traffic shift through the schedule with auto-rollback. Single
        flight — an active promotion raises ValueError. Returns the
        controller (its status() is the /admin/promote response)."""
        from datatunerx_tpu.experiment.promotion import (
            TERMINAL,
            PromotionConfig,
            PromotionController,
        )

        with self._promotion_lock:
            if self.promotion is not None \
                    and self.promotion.state not in TERMINAL:
                raise ValueError(
                    f"a promotion of {self.promotion.canary_name!r} is "
                    "already active")
            cfg = PromotionConfig.from_dict(config or {})
            promo = PromotionController(self, canary, config=cfg,
                                        metrics=metrics)
            self.promotion = promo
        if background:
            t = threading.Thread(target=promo.run, daemon=True)
            self._promotion_thread = t
            t.start()
        return promo

    def promotion_status(self) -> Optional[dict]:
        promo = self.promotion
        return promo.status() if promo is not None else None

    def scale(self, n: int) -> int:
        if self.replica_set is None:
            raise NotImplementedError("gateway does not manage its replicas")
        return self.replica_set.scale(n)

    def drain(self, name: str) -> bool:
        """Drain a replica for a rolling restart. Managed replicas get the
        full treatment (reap the subprocess, spawn a replacement); bare
        pool replicas just stop receiving new requests.

        With ``session_handoff`` on (default), every in-flight decode
        session is exported from the leaving replica and imported onto a
        peer BEFORE the reap — the drained replica empties immediately and
        no client stream re-prefills. Sessions nothing can admit fall back
        to today's cold path, logged and counted."""
        replica = self.pool.get(name)
        if replica is None:
            return False
        if self.session_handoff:
            replica.drain()  # no new routes while sessions migrate
            if any(r.name != name for r in self.pool.available()):
                self.handoff_sessions(replica)  # summary → self.last_handoff
        if self.replica_set is not None and self.replica_set.drain(name):
            self.router.forget_replica(name)
            return True
        if self.pool.drain(name) or replica.draining:
            self.router.forget_replica(name)
            return True
        return False

    def close(self):
        self.slo.stop()
        # abort an in-flight promotion so its run loop goes terminal, then
        # reap the background workers — a promotion ticking against a
        # closed gateway was a real leak the thread sanitizer flagged
        promo = self.promotion
        if promo is not None:
            promo.abort("gateway shutdown")
        t = self._promotion_thread
        if t is not None and t.is_alive():
            t.join(timeout=10)
        with self._tenant_lock:
            workers, self._worker_threads = self._worker_threads, []
        for w in workers:
            w.join(timeout=5)
        if self.fleet is not None:
            self.fleet.stop()
        if self.replica_set is not None:
            self.replica_set.close()
        self.pool.close()


# ------------------------------------------------------------------- subprocs
class ManagedReplicaSet:
    """Supervises serving.server subprocess replicas on localhost — the
    process-per-replica deployment LocalServingBackend/`dtx serve
    --replicas N` uses. A supervisor thread reconciles toward ``target``:
    dead processes (crashed/killed replicas) are reaped and REPLACED, so the
    fleet self-heals like Ray Serve restarting a dead deployment replica.
    Downscale AND /admin/drain are graceful: the replica drains (no new
    requests) and its process is reaped once in-flight work finishes —
    every drained managed replica gets a reaper, so a drain can never
    leave a zombie subprocess + pool entry behind (the fleet previously
    grew past target by one zombie per /admin/drain)."""

    def __init__(self, pool: ReplicaPool, server_args: List[str],
                 workdir: str = "", drain_timeout_s: float = 30.0,
                 supervise_interval_s: float = 2.0,
                 roles: Optional[List[str]] = None):
        self.pool = pool
        self.server_args = list(server_args)
        # disaggregation role cycle ("prefill,decode" = half and half):
        # each spawn takes the role furthest below its share of the
        # cycle, so a replacement restores the fleet's role balance no
        # matter which replica died. Empty/None = role-less (mixed).
        self.roles = [r for r in (roles or []) if r]
        self.workdir = workdir or os.getcwd()
        self.drain_timeout_s = drain_timeout_s
        self.target = 0
        self._procs: dict = {}
        self._reaping: set = set()
        self._next_idx = 0
        # drained replicas' promotion weight + adapter warm-set, queued for
        # the replacement spawn to inherit: a replacement joining at
        # defaults (weight 1.0, cold pool) skews smooth-WRR shares
        # mid-promotion and pays every tenant's load-on-miss again
        self._inherit: List[dict] = []
        self._lock = threading.Lock()
        # serializes whole reconcile passes: drain()/scale() callers (HTTP
        # handler threads) race the supervisor tick, and two concurrent
        # passes would both see live < target and double-spawn a replica
        self._reconcile_lock = threading.Lock()
        os.makedirs(self.workdir, exist_ok=True)
        self._shutdown = threading.Event()
        self._supervisor = None
        if supervise_interval_s > 0:
            self._supervisor = threading.Thread(
                target=self._supervise, args=(supervise_interval_s,),
                daemon=True)
            self._supervisor.start()

    def _next_role(self) -> Optional[str]:
        """The role this spawn should take: the cycle entry furthest
        below its share of the live fleet (ties break in cycle order, so
        a fresh fleet spawns exactly the configured cycle)."""
        if not self.roles:
            return None
        want: dict = {}
        for r in self.roles:
            want[r] = want.get(r, 0) + 1
        live = {r: 0 for r in want}
        for rep in self.pool.replicas():
            role = getattr(rep, "role", "mixed")
            if role in live and not rep.draining:
                live[role] += 1
        return min(want, key=lambda r: (live[r] / want[r],
                                        self.roles.index(r)))

    def spawn(self) -> HTTPReplica:
        with self._lock:
            idx = self._next_idx
            self._next_idx += 1
        name = f"replica-{idx}"
        port = _free_port()
        role = self._next_role()
        args = list(self.server_args)
        if role:
            args += ["--role", role]
        log = open(os.path.join(self.workdir, f"{name}.log"), "w")
        proc = subprocess.Popen(
            [sys.executable, "-m", "datatunerx_tpu.serving.server",
             *args, "--port", str(port)],
            stdout=log, stderr=subprocess.STDOUT, cwd=self.workdir,
        )
        with self._lock:
            self._procs[name] = proc
        replica = HTTPReplica(name, f"http://127.0.0.1:{port}",
                              role=role or "mixed")
        replica.healthy = False  # until the health probe sees model loaded
        self._apply_inheritance(replica)
        self.pool.add(replica)
        return replica

    def _apply_inheritance(self, replica: Replica):
        """Hand a freshly-spawned replacement the drained replica's
        promotion weight immediately, and rebuild its adapter warm set
        once it reports healthy (a background thread — the model load is
        minutes, the spawn must not block on it)."""
        with self._lock:
            now = time.monotonic()
            # entries expire: a drain whose replacement never spawned
            # (target dropped meanwhile) must not skew a later scale-up
            self._inherit = [e for e in self._inherit
                             if now - e["t"] < 300.0]
            entry = self._inherit.pop(0) if self._inherit else None
        if entry is None:
            return
        replica.weight = entry["weight"]
        if entry.get("adapters"):
            threading.Thread(
                target=self._warm_replacement,
                args=(replica, dict(entry["adapters"])),
                daemon=True).start()

    def _warm_replacement(self, replica: Replica, adapters: dict):
        deadline = time.monotonic() + max(self.drain_timeout_s, 30.0) + 300.0
        while not self._shutdown.is_set() and time.monotonic() < deadline:
            try:
                if replica.probe_health():
                    break
            except Exception:  # noqa: BLE001 — still booting
                pass
            if self._shutdown.wait(0.2):
                return
        else:
            return
        for name, ckpt in sorted(adapters.items()):
            try:
                replica.preload_adapter(name, ckpt)
            except Exception as e:  # noqa: BLE001 — warm-set is best-effort
                print(f"[gateway] warm-set {name!r} on {replica.name} "
                      f"failed: {e}", flush=True)

    def scale(self, n: int) -> int:
        n = max(0, int(n))
        with self._lock:  # target is read by the supervisor thread
            self.target = n
        self._reconcile()
        return n

    def drain(self, name: str) -> bool:
        """Drain one MANAGED replica for a rolling restart: stop routing to
        it, reap its process once in-flight work finishes, and let the
        supervisor spawn a replacement to hold ``target``."""
        with self._lock:
            managed = name in self._procs
        replica = self.pool.get(name)
        if not managed or replica is None:
            return False
        replica.drain()
        self._start_reap(replica, inherit=True)
        self._reconcile()  # spawn the replacement now, not next tick
        return True

    def _supervise(self, interval: float):
        while not self._shutdown.wait(interval):
            self._reconcile()

    def _reconcile(self):
        """Converge the live managed fleet on ``target``: reap dead
        processes first (a killed replica must not count toward the target,
        or the fleet would stay degraded forever), then spawn/drain."""
        with self._reconcile_lock:
            self._reconcile_locked()

    def _reconcile_locked(self):
        with self._lock:
            dead = [name for name, proc in self._procs.items()
                    if proc.poll() is not None]
            for name in dead:
                self._procs.pop(name, None)
        for name in dead:
            self.pool.remove(name)
        with self._lock:
            managed = set(self._procs)
            target = self.target
        live = []
        for r in self.pool.replicas():
            if r.name not in managed:
                continue
            if r.draining:
                # safety net: however a managed replica got its draining
                # flag (/admin/drain via pool.drain, an operator poking the
                # pool directly), it must end up reaped — draining without
                # a reaper is how zombies used to accumulate. The target is
                # unchanged here, so a replacement will spawn: it inherits.
                self._start_reap(r, inherit=True)
            else:
                live.append(r)
        live.sort(key=lambda r: r.name)
        for _ in range(target - len(live)):
            self.spawn()
        for replica in live[target:][::-1]:  # drain newest-first
            replica.drain()
            self._start_reap(replica)

    def _start_reap(self, replica: HTTPReplica, inherit: bool = False):
        with self._lock:
            if replica.name in self._reaping or replica.name not in self._procs:
                return
            self._reaping.add(replica.name)
        if inherit:
            # snapshot NOW, while the draining replica still answers: the
            # replacement spawn (possibly this same reconcile pass) pops it
            entry = {"weight": float(getattr(replica, "weight", 1.0)),
                     "adapters": None, "t": time.monotonic()}
            try:
                entry["adapters"] = replica.adapter_inventory()
            except Exception:  # noqa: BLE001 — inventory is best-effort
                pass
            with self._lock:
                self._inherit.append(entry)
        threading.Thread(target=self._reap, args=(replica,),
                         daemon=True).start()

    def _reap(self, replica: HTTPReplica):
        try:
            deadline = time.monotonic() + self.drain_timeout_s
            while replica.inflight > 0 and time.monotonic() < deadline:
                time.sleep(0.1)
            self.pool.remove(replica.name)
            with self._lock:
                proc = self._procs.pop(replica.name, None)
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        finally:
            with self._lock:
                self._reaping.discard(replica.name)

    def close(self):
        self._shutdown.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=5)
        with self._lock:
            procs = list(self._procs.values())
            self._procs.clear()
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


# ----------------------------------------------------------------------- http
def make_handler(gw: Gateway):
    class Handler(BaseHTTPRequestHandler):
        gateway = gw

        # ------------------------------------------------------------ plumbing
        def _trace_id(self) -> str:
            return (self.headers.get("X-DTX-Trace-Id")
                    or f"dtx-{uuid.uuid4().hex[:16]}")

        def _json(self, code: int, payload: dict, trace_id: str = "",
                  extra_headers: Optional[dict] = None):
            # count BEFORE the body goes out: a client that scrapes
            # /metrics the instant its response arrives must see its own
            # request counted (the code is already terminal here)
            self.gateway.record_request(code)
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if trace_id:
                self.send_header("X-DTX-Trace-Id", trace_id)
            for k, v in (extra_headers or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(body)

        # -------------------------------------------------------------- GET
        def do_GET(self):
            if self.path == "/healthz":
                if self.gateway.healthy():
                    self._json(200, {
                        "status": "HEALTHY",
                        "replicas": len(self.gateway.pool.replicas()),
                        "available": len(self.gateway.pool.available()),
                    })
                else:
                    self._json(503, {"status": "LOADING"})
            elif self.path == "/v1/models":
                self._json(200, {"object": "list", "data": [
                    {"id": self.gateway.model_name, "object": "model"}]})
            elif self.path == "/autoscale":
                self._json(200, self.gateway.autoscale())
            elif self.path == "/admin/promote":
                status = self.gateway.promotion_status()
                if status is None:
                    self._json(404, {"error": "no promotion started"})
                else:
                    self._json(200, status)
            elif self.path.split("?")[0] == "/metrics":
                # exemplars only on the explicit ?exemplars=1 debug view:
                # the annotation tail is a parse error to a classic
                # Prometheus parser and would fail the WHOLE scrape
                body = self.gateway.metrics_text(
                    with_exemplars=exemplars_requested(self.path)).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/debug/slo":
                self._json(200, self.gateway.slo_report())
            elif self.path == "/debug/fleet":
                if self.gateway.fleet is None:
                    self._json(404, {"error": "fleet plane not enabled"})
                else:
                    self._json(200, self.gateway.fleet.stats())
            elif self.path == "/admin/tenants":
                if self.gateway.tenants is None:
                    self._json(404, {"error": "tenancy plane not enabled"})
                else:
                    self._json(200, {
                        "tenants": self.gateway.tenants.to_dict(),
                        "generation": self.gateway.tenants.generation})
            elif self.path.startswith("/debug/trace/"):
                tid = self.path[len("/debug/trace/"):]
                doc = self.gateway.trace(tid) if tid else None
                if doc is None:
                    self._json(404, {"error": f"no trace {tid!r}"})
                else:
                    self._json(200, doc)
            else:
                self._json(404, {"error": "not found"})

        # ------------------------------------------------------------- POST
        def do_POST(self):
            trace_id = self._trace_id()
            try:
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, json.JSONDecodeError) as e:
                self._json(400, {"error": f"invalid JSON body: {e}"},
                           trace_id)
                return
            if self.path in ("/chat/completions", "/v1/chat/completions"):
                self._chat(req, trace_id)
            elif self.path == "/perplexity":
                self._perplexity(req, trace_id)
            elif self.path == "/admin/scale":
                self._scale(req, trace_id)
            elif self.path == "/admin/drain":
                self._drain(req, trace_id)
            elif self.path == "/admin/promote":
                self._promote(req, trace_id)
            elif self.path == "/admin/tenants":
                self._tenants_admin(req, trace_id)
            elif self.path == "/debug/profile":
                self._profile(req, trace_id)
            else:
                self._json(404, {"error": "not found"}, trace_id)

        def _session_id(self, req: dict) -> Optional[str]:
            return (self.headers.get("X-DTX-Session-Id")
                    or req.get("session_id") or req.get("user"))

        def _tenant(self, req: dict) -> str:
            return (self.headers.get("X-DTX-Tenant")
                    or req.get("tenant") or "")

        def _chat(self, req: dict, trace_id: str):
            session_id = self._session_id(req)
            try:
                if req.get("stream"):
                    self._chat_sse(req, trace_id, session_id)
                    return
                text = self.gateway.chat(req, trace_id=trace_id,
                                         session_id=session_id,
                                         tenant=self._tenant(req))
                self._json(200, {
                    "id": f"chatcmpl-{uuid.uuid4().hex[:12]}",
                    "object": "chat.completion",
                    "created": int(time.time()),
                    "model": self.gateway.model_name,
                    "choices": [{
                        "index": 0,
                        "message": {"role": "assistant", "content": text},
                        "finish_reason": "stop",
                    }],
                }, trace_id)
            except Overloaded as e:
                self._json(429, {"error": f"overloaded: {e.reason}"},
                           trace_id,
                           {"Retry-After": e.retry_after_s})
            except ValueError as e:
                self._json(400, {"error": str(e)}, trace_id)
            except NoReplicaAvailable as e:
                self._json(503, {"error": str(e)}, trace_id)
            except Exception as e:  # noqa: BLE001 — gateway must answer
                self._json(500, {"error": str(e)}, trace_id)

        def _chat_sse(self, req: dict, trace_id: str,
                      session_id: Optional[str]):
            rid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
            try:
                deltas = self.gateway.chat_stream(req, trace_id=trace_id,
                                                  session_id=session_id,
                                                  tenant=self._tenant(req))
                first = next(deltas, None)
            except Overloaded as e:
                self._json(429, {"error": f"overloaded: {e.reason}"},
                           trace_id, {"Retry-After": e.retry_after_s})
                return
            except ValueError as e:
                self._json(400, {"error": str(e)}, trace_id)
                return
            except (NoReplicaAvailable, ReplicaError) as e:
                self._json(503, {"error": str(e)}, trace_id)
                return
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("X-DTX-Trace-Id", trace_id)
            self.end_headers()

            def event(payload: dict):
                self.wfile.write(b"data: " + json.dumps(payload).encode()
                                 + b"\n\n")
                self.wfile.flush()

            def chunk(delta, finish=None):
                event({
                    "id": rid, "object": "chat.completion.chunk",
                    "created": int(time.time()),
                    "model": self.gateway.model_name,
                    "choices": [{"index": 0,
                                 "delta": ({"content": delta}
                                           if delta is not None else {}),
                                 "finish_reason": finish}],
                })

            code = 200
            try:
                try:
                    if first is not None:
                        chunk(first)
                    for delta in deltas:
                        chunk(delta)
                    chunk(None, finish="stop")
                except Exception as e:  # noqa: BLE001 — headers already sent
                    event({"error": {"message": str(e)}})
                    code = 500
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                code = 499
            self.gateway.record_request(code)

        def _perplexity(self, req: dict, trace_id: str):
            try:
                self._json(200, self.gateway.perplexity(req, trace_id),
                           trace_id)
            except NotImplementedError as e:
                self._json(501, {"error": str(e)}, trace_id)
            except ValueError as e:  # replica judged the request malformed
                self._json(400, {"error": str(e)}, trace_id)
            except NoReplicaAvailable as e:
                self._json(503, {"error": str(e)}, trace_id)
            except Exception as e:  # noqa: BLE001 — replica fault
                self._json(502, {"error": str(e)}, trace_id)

        def _tenants_admin(self, req: dict, trace_id: str):
            gw_t = self.gateway.tenants
            if gw_t is None:
                self._json(404, {"error": "tenancy plane not enabled "
                                          "(start with --tenants_config)"},
                           trace_id)
                return
            name = req.get("name") or ""
            try:
                if req.get("remove"):
                    if not gw_t.remove(name):
                        self._json(404, {"error": f"no tenant {name!r}"},
                                   trace_id)
                        return
                else:
                    entry = {k: v for k, v in req.items()
                             if k in ("tier", "adapters", "share",
                                      "kv_block_quota", "ttft_p95_ms")}
                    gw_t.upsert(name, entry)
            except ValueError as e:
                self._json(400, {"error": str(e)}, trace_id)
                return
            self._json(200, {"tenants": gw_t.to_dict(),
                             "generation": gw_t.generation}, trace_id)

        def _scale(self, req: dict, trace_id: str):
            try:
                n = int(req.get("replicas"))
            except (TypeError, ValueError):
                self._json(400, {"error": "replicas must be an integer"},
                           trace_id)
                return
            try:
                self._json(200, {"replicas": self.gateway.scale(n)}, trace_id)
            except NotImplementedError as e:
                self._json(501, {"error": str(e)}, trace_id)

        def _drain(self, req: dict, trace_id: str):
            name = req.get("replica") or ""
            self.gateway.last_handoff = None
            if self.gateway.drain(name):
                body = {"draining": name}
                if self.gateway.last_handoff is not None:
                    body["handoff"] = self.gateway.last_handoff
                self._json(200, body, trace_id)
            else:
                self._json(404, {"error": f"no replica {name!r}"}, trace_id)

        def _promote(self, req: dict, trace_id: str):
            """Start a canary promotion: {"replica": name, "schedule":
            [w...], "step_s": s, "min_requests": n, "max_error_rate": f,
            "max_latency_ratio": f}. The named replica must already be in
            the pool (spawned from the winning checkpoint). 409 while a
            promotion is active; the 202 body (and later GETs of this
            path) carry the shift state + trace id."""
            name = str(req.get("replica") or "")
            if not name:
                self._json(400, {"error": "replica is required"}, trace_id)
                return
            try:
                promo = self.gateway.start_promotion(name, config=req)
            except ValueError as e:
                code = 409 if "already active" in str(e) else 400
                self._json(code, {"error": str(e)}, trace_id)
                return
            self._json(202, promo.status(), trace_id)

        def _profile(self, req: dict, trace_id: str):
            """Pass a profiling request through to a replica (serving's
            POST /debug/profile); in-process replicas capture the gateway's
            own process."""
            try:
                seconds = float(req.get("seconds", 2.0))
            except (TypeError, ValueError):
                self._json(400, {"error": "seconds must be a number"},
                           trace_id)
                return
            try:
                out = self.gateway.profile(
                    seconds, log_dir=str(req.get("dir") or "") or None,
                    replica_name=str(req.get("replica") or ""))
                self._json(202, out, trace_id)
            except ValueError as e:  # dir escapes the allowed root
                self._json(400, {"error": str(e)}, trace_id)
            except NoReplicaAvailable as e:
                self._json(503, {"error": str(e)}, trace_id)
            except NotImplementedError as e:
                self._json(501, {"error": str(e)}, trace_id)
            except ReplicaError as e:
                # relay the replica's own status (409 conflict, 400 bad
                # dir); no status on the error = the replica itself failed
                code = e.status if e.status in (400, 409) else 502
                self._json(code, {"error": str(e)}, trace_id)

        def log_message(self, *a):
            pass

    return Handler


def serve(gw: Gateway, port: int = 0,
          host: str = "0.0.0.0") -> ThreadingHTTPServer:
    srv = ThreadingHTTPServer((host, port), make_handler(gw))
    return srv


def main(argv=None):
    p = argparse.ArgumentParser(prog="datatunerx-tpu-gateway")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--policy", default="least_busy",
                   choices=["least_busy", "round_robin"])
    p.add_argument("--max_queue", type=int, default=64)
    p.add_argument("--token_budget", type=int, default=32768,
                   help="estimated queued prefill tokens before shedding")
    p.add_argument("--chars_per_token", type=float, default=4.0,
                   help="admission prefill estimate when no tokenizer is "
                        "available (~4 for English BPE; lower for CJK)")
    p.add_argument("--tokenizer_path", default="",
                   help="model dir or preset:NAME for token-accurate "
                        "admission estimates (defaults to --model_path)")
    p.add_argument("--health_interval", type=float, default=2.0)
    p.add_argument("--trace_ring", type=int, default=256,
                   help="completed request traces kept for "
                        "GET /debug/trace/<id>")
    p.add_argument("--trace_log", default="",
                   help="append every completed gateway span as one JSON "
                        "line to this file (offline trace forensics)")
    p.add_argument("--slo_config", default="",
                   help="JSON file of SLO specs (obs/slo.py format) judged "
                        "at GET /debug/slo; default: the built-in gateway "
                        "availability + latency objectives")
    p.add_argument("--slo_sample_s", type=float, default=15.0,
                   help="background SLO sampling interval so the burn-rate "
                        "windows have history without a /debug/slo poller "
                        "(0 disables the sampler)")
    p.add_argument("--prefill_threshold", type=int, default=0,
                   help="prompts of >= this many tokens PREFER replicas "
                        "declaring role=prefill (shorter prompts prefer "
                        "the rest); 0 (default) disables role-aware "
                        "routing entirely")
    p.add_argument("--fleet_prefix_mb", type=float, default=0.0,
                   help="fleet-shared prefix tier budget in MB: the "
                        "first replica to prefill a shared system prompt "
                        "publishes it and peers import it COW — their "
                        "first matching request prefills zero chunks. "
                        "0 (default) disables the tier")
    p.add_argument("--fleet_handoff", type=int, default=0,
                   help="1: prefill→decode handoff — finished prompt "
                        "work on role=prefill replicas is re-homed onto "
                        "decode peers (and drains ship mid-prefill "
                        "tails); 0 (default) off")
    p.add_argument("--fleet_spill", type=int, default=0,
                   help="1: peer-replica KV spill — preemption-parked "
                        "sessions re-home onto a peer with free blocks "
                        "instead of waiting locally; 0 (default) off")
    p.add_argument("--fleet_interval", type=float, default=1.0,
                   help="fleet coordination tick interval in seconds "
                        "(prefix sync + handoff + spill passes)")
    p.add_argument("--role", default="",
                   help="comma-separated role cycle for spawned replicas "
                        "(e.g. 'prefill,decode' alternates; entries from "
                        "prefill/decode/mixed); empty = all mixed")
    p.add_argument("--tenants_config", default="",
                   help="tenant directory: a JSON file path or inline "
                        "JSON object mapping tenant -> {tier, adapters, "
                        "share, kv_block_quota, ttft_p95_ms}. Enables "
                        "the multi-tenant QoS plane (weighted-fair "
                        "admission, per-tenant KV quotas, pinned adapter "
                        "tiers); empty (default) leaves the gateway "
                        "byte-identical to a tenant-less build")
    p.add_argument("--host_adapter_cache_mb", type=float, default=0.0,
                   help="per-replica host-RAM adapter tier budget in MB "
                        "(spawn mode pass-through): evicted adapters "
                        "re-load from host arrays instead of orbax. "
                        "0 (default) disables the tier")
    p.add_argument("--session_handoff", type=int, default=1,
                   help="1 (default): drain exports every in-flight KV "
                        "session from the leaving replica and imports it "
                        "on a peer — rolling restarts drop nothing and "
                        "re-prefill nothing; 0 reverts to cold drain")
    p.add_argument("--replica_url", action="append", default=[],
                   help="front an EXISTING serving server (repeatable); "
                        "mutually exclusive with --replicas spawning")
    p.add_argument("--replicas", type=int, default=0,
                   help="spawn N serving.server subprocesses to front")
    p.add_argument("--workdir", default="",
                   help="replica log directory (spawn mode)")
    # pass-through model flags for spawn mode (mirror serving.server)
    p.add_argument("--model_path", default="")
    p.add_argument("--checkpoint_path", default="")
    p.add_argument("--template", default="llama2")
    p.add_argument("--max_seq_len", type=int, default=1024)
    p.add_argument("--quantization", default="")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--decode_chunk", type=int, default=8)
    p.add_argument("--adapters", default="")
    p.add_argument("--adapter_pool", type=int, default=0)
    p.add_argument("--adapter_rank_max", type=int, default=8)
    p.add_argument("--adapter_targets", default="")
    p.add_argument("--kv_quant", default="")
    p.add_argument("--prefix_cache", type=int, default=0)
    p.add_argument("--kv_block_size", type=int, default=0)
    p.add_argument("--kv_blocks", type=int, default=0)
    p.add_argument("--kv_overcommit", default="off",
                   choices=["off", "on"])
    p.add_argument("--spec_draft_config", default="")
    p.add_argument("--spec_k", type=int, default=4)
    p.add_argument("--spec_mode", default="auto",
                   choices=["auto", "on", "off"])
    p.add_argument("--spec_tree", default="")
    p.add_argument("--sampling_epilogue", default="auto",
                   choices=["auto", "on", "off"])
    p.add_argument("--paged_kernel", default="auto",
                   choices=["auto", "on", "off"])
    p.add_argument("--prefill_chunk", type=int, default=256)
    p.add_argument("--prefill_token_budget", type=int, default=0)
    args = p.parse_args(argv)

    if not args.replica_url and args.replicas <= 0:
        p.error("need --replica_url URL(s) or --replicas N with --model_path")
    if args.replicas > 0 and not args.model_path:
        p.error("--replicas spawning requires --model_path")
    roles = [r.strip() for r in args.role.split(",") if r.strip()]
    for r in roles:
        if r not in ("prefill", "decode", "mixed"):
            p.error(f"--role entries must be prefill/decode/mixed, got {r!r}")

    # token-accurate admission (ROADMAP): count prefill tokens with the real
    # tokenizer when one is loadable; otherwise the chars/token heuristic
    count_tokens = None
    tok_src = args.tokenizer_path or args.model_path
    if tok_src:
        from datatunerx_tpu.utils.model_loader import load_tokenizer

        tok = load_tokenizer(tok_src)
        if tok is not None:
            count_tokens = lambda text: len(tok.encode(text))  # noqa: E731
            print(f"[gateway] admission using tokenizer from {tok_src}",
                  flush=True)

    pool = ReplicaPool(health_interval_s=args.health_interval)
    gw = Gateway(pool, policy=args.policy,
                 admission=AdmissionController(
                     max_queue=args.max_queue,
                     token_budget=args.token_budget,
                     chars_per_token=args.chars_per_token,
                     count_tokens=count_tokens),
                 model_name=args.model_path,
                 trace_ring=args.trace_ring,
                 trace_log_path=args.trace_log or None,
                 slos=load_slos(args.slo_config) if args.slo_config else None,
                 session_handoff=bool(args.session_handoff),
                 prefill_threshold=args.prefill_threshold,
                 fleet_prefix_bytes=int(args.fleet_prefix_mb * 1024 * 1024),
                 fleet_handoff=bool(args.fleet_handoff),
                 fleet_spill=bool(args.fleet_spill),
                 tenants=args.tenants_config or None)
    if args.slo_sample_s > 0:
        gw.slo.start(args.slo_sample_s)
    if gw.fleet is not None:
        gw.fleet.start(args.fleet_interval)
    for i, url in enumerate(args.replica_url):
        pool.add(HTTPReplica(f"replica-{i}", url))
    if args.replicas > 0:
        server_args = ["--model_path", args.model_path,
                       "--checkpoint_path", args.checkpoint_path,
                       "--template", args.template,
                       "--max_seq_len", str(args.max_seq_len),
                       "--quantization", args.quantization,
                       "--slots", str(args.slots),
                       "--decode_chunk", str(args.decode_chunk),
                       "--adapters", args.adapters,
                       "--adapter_pool", str(args.adapter_pool),
                       "--adapter_rank_max", str(args.adapter_rank_max),
                       "--adapter_targets", args.adapter_targets,
                       "--kv_quant", args.kv_quant,
                       "--prefix_cache", str(args.prefix_cache),
                       "--kv_block_size", str(args.kv_block_size),
                       "--kv_blocks", str(args.kv_blocks),
                       "--kv_overcommit", args.kv_overcommit,
                       "--paged_kernel", args.paged_kernel,
                       "--spec_draft_config", args.spec_draft_config,
                       "--spec_k", str(args.spec_k),
                       "--spec_mode", args.spec_mode,
                       "--spec_tree", args.spec_tree,
                       "--sampling_epilogue", args.sampling_epilogue,
                       "--prefill_chunk", str(args.prefill_chunk),
                       "--prefill_token_budget",
                       str(args.prefill_token_budget)]
        if args.tenants_config:
            server_args += ["--tenants_config", args.tenants_config]
        if args.host_adapter_cache_mb > 0:
            server_args += ["--host_adapter_cache_mb",
                            str(args.host_adapter_cache_mb)]
        gw.replica_set = ManagedReplicaSet(
            pool, server_args, workdir=args.workdir or "gateway-replicas",
            roles=roles)
        gw.replica_set.scale(args.replicas)

    srv = serve(gw, port=args.port)
    print(f"[gateway] listening on :{args.port} "
          f"({len(pool.replicas())} replicas, policy={args.policy})",
          flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        gw.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
