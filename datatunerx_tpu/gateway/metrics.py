"""Compatibility shim: the metrics registry moved to ``obs/metrics.py``.

PR 2 grew the registry here for the gateway's own exposition; PR 7 promoted
it to the shared observability plane (``datatunerx_tpu/obs``) so the serving
server and training logger build their expositions from the same classes.
Existing imports (`from datatunerx_tpu.gateway.metrics import Registry`)
keep working through this re-export.
"""

from datatunerx_tpu.obs.metrics import (  # noqa: F401
    LATENCY_BUCKETS,
    MS_BUCKETS,
    Histogram,
    Metric,
    Registry,
    escape_label_value,
    format_sample,
)

__all__ = [
    "LATENCY_BUCKETS",
    "MS_BUCKETS",
    "Histogram",
    "Metric",
    "Registry",
    "escape_label_value",
    "format_sample",
]
