"""Prometheus metrics registry + correct text exposition.

The serving server hand-assembles its exposition lines; the gateway has
enough series (labeled counters, histograms, per-replica gauges) that a tiny
registry pays for itself and guarantees the format invariants the scraper
relies on: one # TYPE line per metric name preceding all its samples, no
duplicate series, label values escaped per the exposition spec
(backslash, double-quote, newline).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

LATENCY_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                   60.0, float("inf"))


def escape_label_value(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def format_sample(name: str, labels: Optional[dict], value) -> str:
    if labels:
        inner = ",".join(
            f'{k}="{escape_label_value(v)}"' for k, v in sorted(labels.items())
        )
        return f"{name}{{{inner}}} {value}"
    return f"{name} {value}"


class Metric:
    def __init__(self, name: str, mtype: str, help_text: str = ""):
        self.name = name
        self.mtype = mtype
        self.help_text = help_text
        self._lock = threading.Lock()
        self._series: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def _key(self, labels: Optional[dict]):
        return tuple(sorted((labels or {}).items()))

    def inc(self, labels: Optional[dict] = None, by: float = 1.0):
        with self._lock:
            k = self._key(labels)
            self._series[k] = self._series.get(k, 0.0) + by

    def set(self, value: float, labels: Optional[dict] = None):
        with self._lock:
            self._series[self._key(labels)] = float(value)

    def get(self, labels: Optional[dict] = None) -> float:
        with self._lock:
            return self._series.get(self._key(labels), 0.0)

    def clear(self):
        """Drop all series (per-replica gauges are re-stated each scrape so
        removed replicas don't linger as stale series)."""
        with self._lock:
            self._series.clear()

    def expose(self) -> List[str]:
        lines = []
        if self.help_text:
            lines.append(f"# HELP {self.name} {self.help_text}")
        lines.append(f"# TYPE {self.name} {self.mtype}")
        with self._lock:
            for key, value in sorted(self._series.items()):
                fv = int(value) if float(value).is_integer() else value
                lines.append(format_sample(self.name, dict(key), fv))
        return lines


class Histogram:
    """Cumulative-bucket histogram (classic Prometheus shape)."""

    def __init__(self, name: str, help_text: str = "",
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        self.name = name
        self.help_text = help_text
        self.buckets = tuple(buckets)
        if self.buckets[-1] != float("inf"):
            self.buckets = self.buckets + (float("inf"),)
        self._counts = [0] * len(self.buckets)
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, value: float):
        with self._lock:
            self._sum += value
            self._total += 1
            for i, edge in enumerate(self.buckets):
                if value <= edge:
                    self._counts[i] += 1
                    break

    def percentile(self, q: float) -> float:
        """Approximate quantile from bucket upper edges (the autoscale
        signal's p95; the +inf bucket reports the largest finite edge)."""
        with self._lock:
            if self._total == 0:
                return 0.0
            target = q * self._total
            run = 0
            for i, edge in enumerate(self.buckets):
                run += self._counts[i]
                if run >= target:
                    if edge == float("inf"):
                        return self.buckets[-2] if len(self.buckets) > 1 else 0.0
                    return edge
            return self.buckets[-2]

    @property
    def count(self) -> int:
        with self._lock:
            return self._total

    def expose(self) -> List[str]:
        lines = []
        if self.help_text:
            lines.append(f"# HELP {self.name} {self.help_text}")
        lines.append(f"# TYPE {self.name} histogram")
        with self._lock:
            cumulative = 0
            for i, edge in enumerate(self.buckets):
                cumulative += self._counts[i]
                le = "+Inf" if edge == float("inf") else repr(edge)
                lines.append(format_sample(
                    f"{self.name}_bucket", {"le": le}, cumulative))
            lines.append(f"{self.name}_sum {self._sum}")
            lines.append(f"{self.name}_count {self._total}")
        return lines


class Registry:
    def __init__(self):
        self._metrics: "Dict[str, object]" = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_text: str = "") -> Metric:
        return self._register(name, "counter", help_text)

    def gauge(self, name: str, help_text: str = "") -> Metric:
        return self._register(name, "gauge", help_text)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_text, buckets)
                self._metrics[name] = m
            return m

    def _register(self, name: str, mtype: str, help_text: str) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Metric(name, mtype, help_text)
                self._metrics[name] = m
            return m

    def expose(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"
