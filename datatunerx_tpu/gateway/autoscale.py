"""Autoscale signal: queue depth + p95 latency → replica-count hint.

The gateway only OBSERVES; deciding replica count is the operator's job
(operator/capacity.py ``serving_replicas_for`` clamps the hint against
min/max and free slice inventory, and finetunejob_controller applies it).
The hint is exposed at GET /autoscale as JSON so any consumer — the
FinetuneJob controller's serving reconciler, an HPA adapter, a human with
curl — reads the same numbers.
"""

from __future__ import annotations

from typing import Optional


def autoscale_hint(
    *,
    replicas: int,
    available_replicas: int,
    queue_depth: int,
    queued_tokens: int,
    shed_count: int,
    p95_latency_s: float,
    shed_recent: Optional[int] = None,
    queue_high_per_replica: int = 4,
    latency_target_s: float = 30.0,
    slo_burn: Optional[dict] = None,
    fleet_blocks: Optional[dict] = None,
    block_low_watermark: float = 0.1,
) -> dict:
    """Pure function of current observations → desired-replica hint.

    Scale up when the queue backs up past ``queue_high_per_replica`` waiting
    requests per available replica, when requests are being shed, or when
    the latency signal breaches. Scale down only on a fully idle gateway
    (empty queue, comfortable latency). One step per poll: the controller
    re-polls, so ramping is feedback-driven rather than jumpy.

    ``shed_count`` is the lifetime total (reported); the scale-up trigger
    uses ``shed_recent`` — sheds since the previous poll — so one overload
    blip long past doesn't demand scale-up forever. Callers without a
    since-last-poll delta may omit it, accepting the ratchet.

    ``slo_burn`` (``{"name", "burn_rate"}`` — the gateway's worst-burning
    configured objective) REPLACES the raw-p95 signal when present: burn
    rate > 1.0 spends error budget faster than the objective allows, which
    is the scaling contract an operator actually declared; a raw p95
    threshold is a guess about one. Without it (no ``--slo_config``), the
    p95 branch behaves exactly as before.

    ``fleet_blocks`` (``{"free", "total"}`` — the fleet's live paged-KV
    inventory) makes the hint derive from BLOCKS rather than slots: when
    the free fraction drops below ``block_low_watermark`` the fleet is
    about to shed/queue on KV capacity regardless of how latency looks,
    so scale-up fires on the same signal admission sheds on. The block
    numbers are echoed in the output either way.
    """
    n = max(1, replicas)
    desired = n
    reason = "steady"
    if available_replicas < n:
        # dead/draining replicas: first priority is restoring capacity,
        # not adding more — the operator redeploys on FAILED status
        reason = f"degraded: {available_replicas}/{n} replicas available"
    backlog_high = queue_high_per_replica * max(1, available_replicas)
    shedding = shed_count if shed_recent is None else shed_recent
    blocks_low = (fleet_blocks is not None
                  and fleet_blocks.get("total", 0) > 0
                  and (fleet_blocks.get("free", 0)
                       / fleet_blocks["total"]) < block_low_watermark)
    if shedding > 0 and queue_depth > 0:
        desired = n + 1
        reason = f"shedding load ({shedding} shed, queue={queue_depth})"
    elif queue_depth > backlog_high:
        desired = n + 1
        reason = f"queue depth {queue_depth} > {backlog_high}"
    elif blocks_low:
        desired = n + 1
        reason = (f"fleet KV blocks low ({fleet_blocks.get('free', 0)}/"
                  f"{fleet_blocks['total']} free < "
                  f"{block_low_watermark:.0%})")
    elif slo_burn is not None:
        if slo_burn["burn_rate"] > 1.0:
            desired = n + 1
            reason = (f"SLO {slo_burn['name']} burn rate "
                      f"{slo_burn['burn_rate']:.2f} > 1.0")
        elif (queue_depth == 0 and n > 1
              and slo_burn["burn_rate"] < 0.25):
            desired = n - 1
            reason = "idle"
    elif p95_latency_s > latency_target_s:
        desired = n + 1
        reason = (f"p95 latency {p95_latency_s:.2f}s > "
                  f"{latency_target_s:.2f}s target")
    elif (queue_depth == 0 and n > 1
          and p95_latency_s < latency_target_s / 4):
        desired = n - 1
        reason = "idle"
    out = {
        "replicas": n,
        "availableReplicas": available_replicas,
        "desiredReplicas": desired,
        "queueDepth": queue_depth,
        "queuedTokens": queued_tokens,
        "shedCount": shed_count,
        "p95LatencySeconds": round(p95_latency_s, 4),
        "reason": reason,
    }
    if slo_burn is not None:
        out["sloBurnRate"] = slo_burn["burn_rate"]
        out["sloObjective"] = slo_burn["name"]
    if fleet_blocks is not None:
        out["fleetKvBlocksFree"] = int(fleet_blocks.get("free", 0))
        out["fleetKvBlocksTotal"] = int(fleet_blocks.get("total", 0))
    return out


def parse_hint(doc: Optional[dict]) -> Optional[dict]:
    """Validate a hint document polled over HTTP (operator side): any
    missing/garbled field voids the hint rather than scaling on junk."""
    if not isinstance(doc, dict):
        return None
    try:
        return {
            "replicas": int(doc["replicas"]),
            "availableReplicas": int(doc.get("availableReplicas",
                                             doc["replicas"])),
            "desiredReplicas": int(doc["desiredReplicas"]),
            "queueDepth": int(doc.get("queueDepth", 0)),
            "queuedTokens": int(doc.get("queuedTokens", 0)),
            "shedCount": int(doc.get("shedCount", 0)),
            "p95LatencySeconds": float(doc.get("p95LatencySeconds", 0.0)),
            "reason": str(doc.get("reason", "")),
        }
    except (KeyError, TypeError, ValueError):
        return None
