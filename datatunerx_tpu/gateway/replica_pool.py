"""Replica pool: the gateway's inventory of serving replicas.

Two replica flavors share one interface:

  InProcessReplica — wraps an engine object (BatchedEngine/InferenceEngine or
                     any duck-typed stand-in) directly; the test/CI path.
  HTTPReplica      — speaks the serving/server.py wire protocol (POST
                     /chat/completions with SSE streaming, GET /healthz,
                     GET /metrics for slot stats); the production path.

Each replica carries a circuit breaker (closed → open on consecutive
failures → half-open probe after a cooldown → closed on success), replacing
KubeRay's pod-restart-only failure handling with request-level routing
awareness, and a ``draining`` flag for graceful rolling restarts: a draining
replica finishes in-flight requests but receives no new ones.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, Iterator, List, Optional

from datatunerx_tpu.obs.metrics import (
    annotation_start,
    sample_percentile,
)


# The error text a migrated-away request dies with (same literal as
# serving/migration.MIGRATED_SESSION — it crosses the wire as an SSE error
# event's plain-text message, so the marker is matched, not typed). A
# ReplicaError carrying it means "this session was exported, splice the
# imported continuation" — not a replica fault.
MIGRATED_MARKER = "session migrated"


class ReplicaError(Exception):
    """A replica failed to serve a request (connection refused, died
    mid-stream, 5xx). The gateway fails over; the breaker records it.

    ``status`` optionally carries the upstream HTTP status (e.g. a
    replica's 409 profile-conflict) so the gateway can relay the real
    code instead of guessing from the message text."""

    def __init__(self, message: str, status: Optional[int] = None):
        super().__init__(message)
        self.status = status


def _strip_annotation(line: str) -> str:
    """Drop an OpenMetrics-style trailing annotation (`` # {…} v ts`` —
    exemplars — or any future `` # …`` tail) from an exposition line, so a
    new replica's exemplar-bearing /metrics can't break an older gateway's
    stats scrape (and vice versa in a mixed-version fleet). Quote-aware
    via the shared obs.metrics.annotation_start scanner."""
    pos = annotation_start(line)
    return line if pos < 0 else line[:pos].rstrip()


def _adapter_label(line: str, prefix: str) -> Optional[str]:
    """Extract the adapter label value from a ``<prefix>adapter="x"} 1``
    exposition line with value 1 (0 = series cleared, not a member).
    Walks to the first UNESCAPED quote, undoing the exposition escapes
    (obs.metrics.escape_label_value: \\\\, \\n, \\") as it goes — a tenant
    name containing a quote must not truncate to the wrong name."""
    if not line.startswith(prefix):
        return None
    rest = line[len(prefix):]
    if not rest.startswith('adapter="'):
        return None
    s = rest[len('adapter="'):]
    out: list = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            out.append("\n" if nxt == "n" else nxt)
            i += 2
            continue
        if c == '"':
            break
        out.append(c)
        i += 1
    else:
        return None  # unterminated label value
    try:
        if float(s[i + 1:].rsplit(None, 1)[-1]) != 1:
            return None
    except (ValueError, IndexError):
        return None
    return "".join(out)


def _error_detail(e: "urllib.error.HTTPError") -> str:
    """The serving server's JSON error body (or the bare HTTP reason) —
    the one extraction every HTTPReplica error path shares."""
    try:
        return str(json.load(e).get("error", e.reason))
    except Exception:  # noqa: BLE001 — non-JSON body: the reason is all we have
        return str(e.reason)


def _client_error_message(e: BaseException) -> str:
    # KeyError.__str__ reprs its argument — unwrap so the 400 body reads
    # "unknown adapter 'x'", not "\"unknown adapter 'x'\""
    if isinstance(e, KeyError) and e.args:
        return str(e.args[0])
    return str(e)


class NoReplicaAvailable(Exception):
    """No healthy, non-draining, circuit-closed replica to route to."""


class CircuitBreaker:
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 5.0):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._failures = 0
        self._opened_at = 0.0
        self._state = self.CLOSED
        self._lock = threading.Lock()

    @property
    def state(self) -> str:
        with self._lock:
            if (self._state == self.OPEN
                    and time.monotonic() - self._opened_at >= self.cooldown_s):
                self._state = self.HALF_OPEN
            return self._state

    def allow(self) -> bool:
        """May a request be routed here? Open circuits reject until the
        cooldown elapses; half-open admits (the probe) — its outcome decides
        between re-open and close."""
        return self.state != self.OPEN

    def record_success(self):
        with self._lock:
            self._failures = 0
            self._state = self.CLOSED

    def record_failure(self):
        with self._lock:
            self._failures += 1
            if (self._state == self.HALF_OPEN
                    or self._failures >= self.failure_threshold):
                self._state = self.OPEN
                self._opened_at = time.monotonic()


class Replica:
    """Interface + shared bookkeeping. Subclasses implement ``chat``,
    ``chat_stream``, ``probe_health`` and ``stats``."""

    def __init__(self, name: str, failure_threshold: int = 3,
                 cooldown_s: float = 5.0, role: str = "mixed"):
        self.name = name
        self.breaker = CircuitBreaker(failure_threshold, cooldown_s)
        # disaggregation role: "prefill" (long-prompt specialist), "decode"
        # (steady-state token production), or "mixed" (either — the
        # default, which keeps routing byte-identical to a role-less
        # fleet). HTTP replicas refresh this from their /metrics scrape.
        self.role = role or "mixed"
        self.draining = False
        self.healthy = True  # last health-probe verdict
        self.inflight = 0  # gateway-side in-flight count (least-busy fallback)
        self._inflight_lock = threading.Lock()
        # canary/traffic weight: the router's smooth-WRR share when weights
        # in the candidate set are non-uniform (all-1.0 = policy as before);
        # weight 0 receives no new requests (a rolled-back canary)
        self.weight = 1.0
        # per-replica outcome window: the promotion controller compares the
        # canary's error rate and latency p95 against the fleet's from these
        # (fed by the gateway per attempt, same measurements as the PR 7
        # request histograms)
        self.requests_total = 0
        self.errors_total = 0
        self._latency_ms: List[float] = []
        self._outcome_lock = threading.Lock()
        # last completed request's replica-side tokenized prompt length
        # (the serving response's ``usage``): the gateway pops this after
        # each success and calibrates its admission estimator with it
        self._last_usage: Optional[dict] = None

    def note_usage(self, prompt_chars: int, prompt_tokens: int):
        """Record one request's REAL tokenized prompt length (replica-side
        truth) next to the prompt's char count."""
        self._last_usage = {"prompt_chars": int(prompt_chars),
                            "prompt_tokens": int(prompt_tokens)}

    def take_usage(self) -> Optional[dict]:
        usage, self._last_usage = self._last_usage, None
        return usage

    @staticmethod
    def _prompt_chars(messages) -> int:
        return sum(len(str(m.get("content", ""))) for m in messages or [])

    def record_outcome(self, ok: bool, latency_ms: float):
        """One routed attempt's terminal outcome (gateway-side). Client
        errors (4xx/ValueError) are NOT recorded — they say nothing about
        the replica."""
        with self._outcome_lock:
            self.requests_total += 1
            if not ok:
                self.errors_total += 1
            self._latency_ms.append(float(latency_ms))
            if len(self._latency_ms) > 512:
                del self._latency_ms[:256]

    def outcome_stats(self, last_n: Optional[int] = None) -> dict:
        """Rolling outcome summary. ``last_n`` limits the latency p95 to
        the most recent n samples — the promotion guard judges a stage on
        the traffic served DURING it, not on warm-up requests that happen
        to still sit in the rolling window."""
        with self._outcome_lock:
            window = self._latency_ms[-last_n:] if last_n else \
                list(self._latency_ms)
            reqs, errs = self.requests_total, self.errors_total
        return {"requests": reqs, "errors": errs,
                "error_rate": errs / reqs if reqs else 0.0,
                "latency_p95_ms": sample_percentile(window, 0.95)}

    # ------------------------------------------------------------- requests
    def chat(self, messages: List[dict], **kwargs) -> str:
        raise NotImplementedError

    def chat_stream(self, messages: List[dict], **kwargs) -> Iterator[str]:
        raise NotImplementedError

    # --------------------------------------------------------------- health
    def probe_health(self) -> bool:
        raise NotImplementedError

    def stats(self) -> dict:
        """{"slots_busy": int, "slots_total": int, "kv_blocks_free": int,
        "kv_blocks_total": int, "kv_block_size": int,
        "adapters": set|None,
        "resident_adapters": set|None, "spec_enabled": bool,
        "spec_accept_rate": float|None}.
        kv_blocks_total 0 means the replica runs a dense cache (no block
        signal; kv_block_size is then 0 too — fleet-true admission prices
        admits in blocks of kv_block_size tokens when present);
        adapters=None means unknown — the router treats it as
        capable of anything (load-on-demand fallback). resident_adapters
        is the subset already materialised in the replica's pool (static
        stacks: everything it knows) — the router's cache-locality
        preference; None = no residency signal. spec_enabled/_accept_rate
        carry the speculative-decode plane's signal for the router's
        spec-friendly preference (rate None = no observations yet)."""
        raise NotImplementedError

    def stats_snapshot(self) -> dict:
        """Last-known stats WITHOUT doing any fetch work — for observability
        paths (the gateway /metrics scrape handler) that must never block on
        a slow replica. Local replicas answer live; remote replicas return
        whatever the routing/stats path last cached (possibly stale on an
        idle gateway — a stale gauge beats a scrape that hangs 2s per hung
        replica)."""
        return self.stats()

    # --------------------------------------------------- KV migration fabric
    def export_sessions(self, slots: Optional[List[int]] = None,
                        wire: Optional[str] = None,
                        include_prefill: bool = False) -> Optional[dict]:
        """Serialize (and terminate) the replica's in-flight decode
        sessions for handoff. None = the replica kind/engine has no
        migration surface; otherwise {"sessions": [payload...],
        "skipped": [...]}. ``include_prefill`` ships mid-chunked-prefill
        slots too (disaggregated prefill→decode handoff). Raises
        ReplicaError on transport faults."""
        return None

    def import_session(self, payload: dict):
        """Admit an exported session and resume its decode. None =
        unsupported; otherwise ``(meta, stream)`` where ``meta`` carries
        ``text_so_far`` (the detokenized migrated tail) and ``stream``
        yields the continuation deltas. Raises ReplicaError on refusal
        (status 409: no slot / blocks / adapter) or fault."""
        return None

    # ------------------------------------------------------ fleet plane
    def hold_parked(self, max_sessions: int = 4,
                    hold_s: float = 10.0) -> Optional[dict]:
        """Lease preemption-parked sessions for a peer spill (phase 1).
        None = unsupported; otherwise {"sessions": [...], "parked": n}."""
        return None

    def drop_parked(self, trace_ids: List[str]) -> Optional[dict]:
        """Finish a spill (phase 2, success): drop the re-homed sessions
        and terminate their source requests with the migrated marker."""
        return None

    def release_parked(self, trace_ids: List[str]) -> Optional[dict]:
        """Abort a spill (phase 2, failure): clear the leases so the
        sessions resume locally."""
        return None

    def export_prefix_entries(self, exclude: Optional[List[str]] = None,
                              max_entries: int = 4,
                              wire: Optional[str] = None) -> Optional[dict]:
        """Publishable local prefix-cache entries (dtx-kv-prefix payloads)
        for the fleet prefix tier; None = unsupported."""
        return None

    def import_prefix_entry(self, payload: dict) -> Optional[dict]:
        """Install a fleet-published prefix payload into the replica's
        local prefix cache; None = unsupported. Raises ReplicaError on
        refusal (status 409) or fault."""
        return None

    def adapter_inventory(self) -> Optional[Dict[str, str]]:
        """Resident adapter name → checkpoint path (the warm set a
        replacement replica should rebuild); None when unknown."""
        return None

    def preload_adapter(self, name: str, checkpoint: str) -> bool:
        """Register + warm one adapter (warm-set inheritance); False when
        the replica kind can't."""
        return False

    # -------------------------------------------------------- observability
    def fetch_trace(self, trace_id: str) -> Optional[dict]:
        """The replica's span timeline for one trace id (None = unknown or
        unsupported) — the gateway merges this into its own trace view so
        GET /debug/trace/<id> spans gateway→replica→engine."""
        return None

    def start_profile(self, seconds: float,
                      log_dir: Optional[str] = None) -> Optional[dict]:
        """Arm an N-second jax.profiler capture on the replica (None =
        unsupported; raises ReplicaError on a refused/failed capture)."""
        return None

    # ------------------------------------------------------------ lifecycle
    def available(self) -> bool:
        return self.healthy and not self.draining and self.breaker.allow()

    def drain(self):
        self.draining = True

    def undrain(self):
        self.draining = False

    def acquire(self):
        with self._inflight_lock:
            self.inflight += 1

    def release(self):
        with self._inflight_lock:
            self.inflight = max(0, self.inflight - 1)

    def busy_fraction(self) -> float:
        """Load signal for least-busy routing. Paged replicas report KV
        block occupancy — the gauge that actually bounds admission (a free
        slot with no free blocks cannot take work) — combined with slot
        occupancy (no free slot means no admission however many blocks
        remain). Dense replicas fall back to slot occupancy, then to the
        gateway-side in-flight count."""
        st = self.stats()
        slot_total = st.get("slots_total") or 0
        slot_frac = (st.get("slots_busy", 0) / slot_total
                     if slot_total > 0 else None)
        block_total = st.get("kv_blocks_total") or 0
        if block_total > 0:
            block_frac = 1.0 - st.get("kv_blocks_free", 0) / block_total
            return max(block_frac, slot_frac or 0.0)
        if slot_frac is not None:
            return slot_frac
        return float(self.inflight)

    def close(self):
        pass


class InProcessReplica(Replica):
    """Wraps an engine object living in this process — the tier-1 test path
    and single-host `dtx serve --gateway` without subprocess replicas.
    The engine contract is duck-typed: ``chat(messages, **kw) -> str`` and
    optionally ``chat_stream``, ``slots``/``_slot_req``, ``adapter_ids``."""

    def __init__(self, name: str, engine, **kw):
        super().__init__(name, **kw)
        self.engine = engine

    def _trace_kwargs(self, kwargs: dict) -> dict:
        """Engines that keep span timelines (BatchedEngine) take the trace
        id; duck-typed stand-ins get it popped like before. Same rule for
        the tenant tag: only engines running a tenant directory take it."""
        trace_id = kwargs.pop("trace_id", "")
        if trace_id and getattr(self.engine, "trace_store", None) is not None:
            kwargs["trace_id"] = trace_id
        tenant = kwargs.pop("tenant", "")
        if tenant and getattr(self.engine, "tenants", None) is not None:
            kwargs["tenant"] = tenant
        return kwargs

    def _note_engine_usage(self, messages):
        """Tokenize the prompt with the ENGINE's own tokenizer — the same
        count the serving wire's ``usage`` carries — so in-process and
        HTTP replicas feed admission calibration identically."""
        enc = getattr(self.engine, "_encode_chat", None)
        if not callable(enc):
            return
        try:
            ids, _ = enc(messages)
            self.note_usage(self._prompt_chars(messages), len(ids))
        except Exception:  # noqa: BLE001 — usage is advisory
            pass

    def chat(self, messages, **kwargs) -> str:
        kwargs = self._trace_kwargs(kwargs)
        try:
            text = self.engine.chat(messages, **kwargs)
            self._note_engine_usage(messages)
            return text
        except (ValueError, KeyError) as e:
            # the CLIENT's error (unknown adapter, over-length prompt, bad
            # params): same rule as HTTPReplica's 4xx mapping — the replica
            # is fine, don't trip its breaker or fail over; the gateway
            # answers 400, not 503
            raise ValueError(_client_error_message(e)) from e
        except Exception as e:  # noqa: BLE001 — engine fault = replica fault
            raise ReplicaError(f"{self.name}: {e}") from e

    def chat_stream(self, messages, **kwargs):
        kwargs = self._trace_kwargs(kwargs)
        stream_fn = getattr(self.engine, "chat_stream", None)
        if stream_fn is None:
            kwargs.pop("trace_id", None)  # duck-typed chat may not take it
        try:
            if stream_fn is None:
                yield self.engine.chat(messages, **kwargs)
                self._note_engine_usage(messages)
                return
            for delta in stream_fn(messages, **kwargs):
                yield delta
            self._note_engine_usage(messages)
        except ReplicaError:
            raise
        except (ValueError, KeyError) as e:  # client error — no failover
            raise ValueError(_client_error_message(e)) from e
        except Exception as e:  # noqa: BLE001
            raise ReplicaError(f"{self.name}: {e}") from e

    def probe_health(self) -> bool:
        probe = getattr(self.engine, "healthy", None)
        if callable(probe):
            try:
                self.healthy = bool(probe())
            except Exception:  # noqa: BLE001
                self.healthy = False
        else:
            self.healthy = self.engine is not None
        return self.healthy

    # --------------------------------------------------- KV migration fabric
    def export_sessions(self, slots=None, wire=None, include_prefill=False):
        fn = getattr(self.engine, "export_sessions", None)
        if not callable(fn):
            return None
        try:
            if include_prefill:
                return fn(slots=slots, wire_quant=wire, include_prefill=True)
            # older engines lack the kwarg — the default call keeps them
            return fn(slots=slots, wire_quant=wire)
        except Exception as e:  # noqa: BLE001 — export fault = replica fault
            raise ReplicaError(f"{self.name}: export failed: {e}") from e

    def import_session(self, payload: dict):
        fn = getattr(self.engine, "import_session", None)
        if not callable(fn):
            return None
        try:
            meta = dict(fn(dict(payload)))
        except (ValueError, KeyError) as e:
            raise ReplicaError(
                f"{self.name}: import refused: {_client_error_message(e)}",
                status=409) from e
        except Exception as e:  # noqa: BLE001
            raise ReplicaError(f"{self.name}: import failed: {e}") from e
        handle = meta.pop("_request", None)
        return meta, self._guarded_resume(handle)

    def _guarded_resume(self, handle):
        """Map resume-stream faults to ReplicaError like chat_stream does,
        so the gateway's splice failure handling sees one exception type."""
        if handle is None:
            return
        try:
            for delta in self.engine.resume_stream(handle):
                yield delta
        except ReplicaError:
            raise
        except Exception as e:  # noqa: BLE001
            raise ReplicaError(f"{self.name}: resume failed: {e}") from e

    # ------------------------------------------------------ fleet plane
    def _fleet_call(self, attr: str, **kw) -> Optional[dict]:
        """One error-mapping shim for the engine's fleet surface:
        ValueError/KeyError = refusal (409, no failover), anything else =
        replica fault; None when the engine lacks the method."""
        fn = getattr(self.engine, attr, None)
        if not callable(fn):
            return None
        try:
            return fn(**kw)
        except (ValueError, KeyError) as e:
            raise ReplicaError(
                f"{self.name}: {attr} refused: {_client_error_message(e)}",
                status=409) from e
        except Exception as e:  # noqa: BLE001
            raise ReplicaError(f"{self.name}: {attr} failed: {e}") from e

    def hold_parked(self, max_sessions: int = 4,
                    hold_s: float = 10.0) -> Optional[dict]:
        return self._fleet_call("hold_parked", max_sessions=max_sessions,
                                hold_s=hold_s)

    def drop_parked(self, trace_ids: List[str]) -> Optional[dict]:
        return self._fleet_call("drop_parked", trace_ids=trace_ids)

    def release_parked(self, trace_ids: List[str]) -> Optional[dict]:
        return self._fleet_call("release_parked", trace_ids=trace_ids)

    def export_prefix_entries(self, exclude: Optional[List[str]] = None,
                              max_entries: int = 4,
                              wire: Optional[str] = None) -> Optional[dict]:
        return self._fleet_call("export_prefix_entries", exclude=exclude,
                                max_entries=max_entries, wire_quant=wire)

    def import_prefix_entry(self, payload: dict) -> Optional[dict]:
        return self._fleet_call("import_prefix_entry",
                                payload=dict(payload))

    def adapter_inventory(self) -> Optional[Dict[str, str]]:
        catalog_fn = getattr(self.engine, "adapter_catalog", None)
        if not callable(catalog_fn):
            return None
        try:
            catalog = dict(catalog_fn())
        except Exception:  # noqa: BLE001 — inventory is best-effort
            return None
        resident = getattr(self.engine, "resident_adapters", None)
        if resident is not None:
            catalog = {n: c for n, c in catalog.items() if n in resident}
        return catalog or None

    def preload_adapter(self, name: str, checkpoint: str) -> bool:
        loader = getattr(self.engine, "load_adapter", None)
        if not callable(loader):
            return False
        loader(name, checkpoint, preload=True)
        return True

    def fetch_trace(self, trace_id: str) -> Optional[dict]:
        store = getattr(self.engine, "trace_store", None)
        if store is None:
            return None
        return store.get(trace_id)

    def start_profile(self, seconds: float,
                      log_dir: Optional[str] = None) -> Optional[dict]:
        """In-process replica = the gateway's own process, so the capture
        covers the engine's decode/prefill ticks directly. Raises
        ValueError for a dir escaping the allowed root (client error) and
        ReplicaError(status=409) when a capture is already running."""
        from datatunerx_tpu.obs.profiling import (
            process_profiler,
            resolve_profile_dir,
        )

        log_dir = resolve_profile_dir(log_dir)
        try:
            effective = process_profiler().start(log_dir, seconds)
        except Exception as e:  # noqa: BLE001 — profiler fault, not replica
            raise ReplicaError(f"{self.name}: profiler failed: {e}") from e
        if effective is None:
            raise ReplicaError(
                f"{self.name}: a profile capture is already running",
                status=409)
        return {"profiling": log_dir, "seconds": effective,
                "replica": self.name}

    def stats(self) -> dict:
        slot_req = getattr(self.engine, "_slot_req", None)
        busy = (sum(1 for r in slot_req if r is not None)
                if slot_req is not None else 0)
        adapter_ids = getattr(self.engine, "adapter_ids", None)
        # residency: dynamic pools report their live resident set; static
        # stacks ARE resident (weights baked at startup), so everything the
        # engine knows counts — the router's preference degrades gracefully
        resident = getattr(self.engine, "resident_adapters", None)
        if resident is not None:
            resident = set(resident)
        elif adapter_ids is not None:
            resident = set(adapter_ids)
        spec_fn = getattr(self.engine, "spec_info", None)
        try:
            spec_doc = spec_fn() if callable(spec_fn) else None
        except Exception:  # noqa: BLE001 — stats are advisory
            spec_doc = None
        return {
            "slots_busy": busy,
            "slots_total": getattr(self.engine, "slots", 0),
            "kv_blocks_free": getattr(self.engine, "free_kv_blocks", None) or 0,
            "kv_blocks_total": getattr(self.engine, "total_kv_blocks", None) or 0,
            # tokens per block — fleet-true admission prices an admit with
            # this (0 = dense cache, no block signal)
            "kv_block_size": getattr(self.engine, "block_size", 0) or 0,
            "adapters": set(adapter_ids) if adapter_ids is not None else None,
            "resident_adapters": resident,
            # speculative decoding: the router's spec-friendly preference
            # and the gateway's per-replica acceptance gauge read these
            "spec_enabled": bool(spec_doc),
            "spec_accept_rate": (spec_doc or {}).get("accept_rate"),
            # disaggregation: routing role + parked-session count (the
            # spill coordinator's candidate signal)
            "role": self.role,
            "sessions_parked": int(
                getattr(self.engine, "parked_sessions", 0) or 0),
        }

    def close(self):
        closer = getattr(self.engine, "close", None)
        if callable(closer):
            closer()


class HTTPReplica(Replica):
    """A remote serving/server.py process. Requests carry the gateway's
    trace id via the X-DTX-Trace-Id header so a request can be followed
    operator → gateway → replica log."""

    def __init__(self, name: str, base_url: str, timeout: float = 300.0,
                 stats_ttl_s: float = 1.0, **kw):
        super().__init__(name, **kw)
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        # stats() is on the ROUTING hot path (least-busy + adapter filter
        # both consult it per request); cache the scrape for a TTL so a slow
        # replica can't add its /metrics round-trip to every routed request
        self.stats_ttl_s = stats_ttl_s
        self._stats_cache: Optional[dict] = None
        self._stats_at = 0.0

    # ------------------------------------------------------------------ http
    def _post(self, path: str, payload: dict, trace_id: str = "",
              tenant: str = ""):
        headers = {"Content-Type": "application/json"}
        if trace_id:
            headers["X-DTX-Trace-Id"] = trace_id
        if tenant:
            headers["X-DTX-Tenant"] = tenant
        req = urllib.request.Request(
            self.base_url + path, data=json.dumps(payload).encode(),
            headers=headers, method="POST")
        return urllib.request.urlopen(req, timeout=self.timeout)

    def _payload(self, messages, kwargs) -> dict:
        payload = {
            "messages": messages,
            "max_tokens": kwargs.get("max_new_tokens", 128),
            "temperature": kwargs.get("temperature", 0.0),
            "top_p": kwargs.get("top_p", 1.0),
        }
        if kwargs.get("adapter"):
            payload["model"] = kwargs["adapter"]
        return payload

    def _note_wire_usage(self, messages, usage):
        """Feed the serving response's ``usage`` (replica-side tokenized
        prompt length) back — the truthful count gateway admission
        calibrates against instead of the chars-per-token heuristic."""
        if not isinstance(usage, dict):
            return
        try:
            tokens = int(usage.get("prompt_tokens") or 0)
        except (TypeError, ValueError):
            return
        if tokens > 0:
            self.note_usage(self._prompt_chars(messages), tokens)

    def chat(self, messages, **kwargs) -> str:
        trace_id = kwargs.pop("trace_id", "")
        tenant = kwargs.pop("tenant", "")
        try:
            with self._post("/chat/completions",
                            self._payload(messages, kwargs), trace_id,
                            tenant=tenant) as r:
                body = json.load(r)
            self._note_wire_usage(messages, body.get("usage"))
            return body["choices"][0]["message"]["content"]
        except urllib.error.HTTPError as e:
            detail = _error_detail(e)
            # 4xx is the CLIENT's error (bad adapter name, bad body): the
            # replica is fine, don't trip the breaker or fail over
            if 400 <= e.code < 500:
                raise ValueError(detail) from e
            # the detail rides along so markers the gateway matches on
            # (MIGRATED_MARKER) survive a non-streamed 500 crossing the wire
            raise ReplicaError(f"{self.name}: HTTP {e.code}: {detail}") from e
        except (OSError, ValueError, KeyError) as e:
            raise ReplicaError(f"{self.name}: {e}") from e

    def chat_stream(self, messages, **kwargs):
        trace_id = kwargs.pop("trace_id", "")
        tenant = kwargs.pop("tenant", "")
        payload = self._payload(messages, kwargs)
        payload["stream"] = True
        try:
            resp = self._post("/chat/completions", payload, trace_id,
                              tenant=tenant)
        except urllib.error.HTTPError as e:
            if 400 <= e.code < 500:
                raise ValueError(f"HTTP {e.code}") from e
            raise ReplicaError(f"{self.name}: HTTP {e.code}") from e
        except OSError as e:
            raise ReplicaError(f"{self.name}: {e}") from e
        try:
            with resp:
                for raw in resp:
                    line = raw.decode("utf-8", "replace").strip()
                    if not line.startswith("data: "):
                        continue
                    data = line[len("data: "):]
                    if data == "[DONE]":
                        return
                    evt = json.loads(data)
                    if "error" in evt:
                        raise ReplicaError(
                            f"{self.name}: {evt['error'].get('message')}")
                    if "usage" in evt:  # terminal chunk's token truth
                        self._note_wire_usage(messages, evt["usage"])
                    delta = evt["choices"][0]["delta"].get("content")
                    if delta:
                        yield delta
        except ReplicaError:
            raise
        except Exception as e:  # noqa: BLE001 — stream cut = replica fault
            raise ReplicaError(f"{self.name}: stream died: {e}") from e

    def probe_health(self) -> bool:
        try:
            with urllib.request.urlopen(
                    self.base_url + "/healthz", timeout=2) as r:
                self.healthy = json.load(r).get("status") == "HEALTHY"
        except Exception:  # noqa: BLE001
            self.healthy = False
        return self.healthy

    # --------------------------------------------------- KV migration fabric
    def _admin_error(self, e: "urllib.error.HTTPError") -> ReplicaError:
        return ReplicaError(f"{self.name}: {_error_detail(e)}",
                            status=e.code)

    def export_sessions(self, slots=None, wire=None, include_prefill=False):
        body: dict = {}
        if slots is not None:
            body["slots"] = list(slots)
        if wire:
            body["wire"] = wire
        if include_prefill:
            body["prefill"] = True
        try:
            with self._post("/admin/sessions/export", body) as r:
                return json.load(r)
        except urllib.error.HTTPError as e:
            if e.code == 501:
                return None  # replica build without the migration surface
            raise self._admin_error(e) from e
        except (OSError, ValueError) as e:
            raise ReplicaError(f"{self.name}: export failed: {e}") from e

    # ------------------------------------------------------ fleet plane
    def _fleet_post(self, path: str, body: dict,
                    what: str) -> Optional[dict]:
        """POST a fleet-plane admin call; 501 (or 404 from an older
        serving build) = surface absent → None, like export_sessions."""
        try:
            with self._post(path, body) as r:
                return json.load(r)
        except urllib.error.HTTPError as e:
            if e.code in (404, 501):
                return None
            raise self._admin_error(e) from e
        except (OSError, ValueError) as e:
            raise ReplicaError(f"{self.name}: {what} failed: {e}") from e

    def hold_parked(self, max_sessions: int = 4,
                    hold_s: float = 10.0) -> Optional[dict]:
        return self._fleet_post("/admin/sessions/hold",
                                {"max_sessions": max_sessions,
                                 "hold_s": hold_s}, "hold_parked")

    def drop_parked(self, trace_ids: List[str]) -> Optional[dict]:
        return self._fleet_post("/admin/sessions/drop",
                                {"trace_ids": list(trace_ids)},
                                "drop_parked")

    def release_parked(self, trace_ids: List[str]) -> Optional[dict]:
        return self._fleet_post("/admin/sessions/release",
                                {"trace_ids": list(trace_ids)},
                                "release_parked")

    def export_prefix_entries(self, exclude: Optional[List[str]] = None,
                              max_entries: int = 4,
                              wire: Optional[str] = None) -> Optional[dict]:
        body: dict = {"max_entries": max_entries}
        if exclude:
            body["exclude"] = list(exclude)
        if wire:
            body["wire"] = wire
        return self._fleet_post("/admin/prefix/export", body,
                                "export_prefix")

    def import_prefix_entry(self, payload: dict) -> Optional[dict]:
        return self._fleet_post("/admin/prefix/import", dict(payload),
                                "import_prefix")

    def import_session(self, payload: dict):
        body = dict(payload)
        body["stream"] = True
        try:
            resp = self._post("/admin/sessions/import", body)
        except urllib.error.HTTPError as e:
            if e.code == 501:
                return None
            raise self._admin_error(e) from e
        except OSError as e:
            raise ReplicaError(f"{self.name}: import failed: {e}") from e
        # first SSE event is the import receipt; the rest is the spliced
        # continuation stream, handed back lazily
        try:
            first = self._next_event(resp)
        except Exception as e:  # noqa: BLE001
            resp.close()
            raise ReplicaError(
                f"{self.name}: import stream died: {e}") from e
        if first is None or "imported" not in first:
            resp.close()
            detail = (first or {}).get("error", {}).get("message", "no receipt")
            raise ReplicaError(f"{self.name}: import failed: {detail}")
        return first["imported"], self._resume_deltas(resp)

    @staticmethod
    def _next_event(resp) -> Optional[dict]:
        for raw in resp:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data: "):
                continue
            data = line[len("data: "):]
            if data == "[DONE]":
                return None
            return json.loads(data)
        return None

    def _resume_deltas(self, resp):
        try:
            with resp:
                while True:
                    evt = self._next_event(resp)
                    if evt is None:
                        return
                    if "error" in evt:
                        raise ReplicaError(
                            f"{self.name}: "
                            f"{evt['error'].get('message')}")
                    delta = evt.get("delta")
                    if delta:
                        yield delta
        except ReplicaError:
            raise
        except Exception as e:  # noqa: BLE001 — stream cut = replica fault
            raise ReplicaError(f"{self.name}: resume died: {e}") from e

    def adapter_inventory(self) -> Optional[Dict[str, str]]:
        try:
            with urllib.request.urlopen(
                    self.base_url + "/admin/adapters", timeout=2) as r:
                doc = json.load(r)
        except Exception:  # noqa: BLE001 — inventory is best-effort
            return None
        checkpoints = doc.get("checkpoints") or {}
        resident = doc.get("resident") or []
        out = {n: checkpoints[n] for n in resident if n in checkpoints}
        return out or None

    def preload_adapter(self, name: str, checkpoint: str) -> bool:
        try:
            with self._post("/admin/adapters",
                            {"name": name, "checkpoint": checkpoint,
                             "load": True}) as r:
                json.load(r)
            return True
        except urllib.error.HTTPError as e:
            raise self._admin_error(e) from e
        except (OSError, ValueError) as e:
            raise ReplicaError(
                f"{self.name}: adapter preload failed: {e}") from e

    def fetch_trace(self, trace_id: str) -> Optional[dict]:
        """GET the replica's half of a trace. Debug path, not routing: a
        short timeout and None on any failure (the gateway still returns
        its own spans)."""
        try:
            with urllib.request.urlopen(
                    self.base_url + "/debug/trace/" + trace_id,
                    timeout=2) as r:
                return json.load(r)
        except Exception:  # noqa: BLE001 — trace fetch is best-effort
            return None

    def start_profile(self, seconds: float,
                      log_dir: Optional[str] = None) -> Optional[dict]:
        payload: dict = {"seconds": seconds}
        if log_dir:
            payload["dir"] = log_dir
        try:
            with self._post("/debug/profile", payload) as r:
                out = json.load(r)
            out["replica"] = self.name
            return out
        except urllib.error.HTTPError as e:
            # carry the replica's real status (409 conflict, 400 bad dir)
            # so the gateway relays it instead of guessing from the text
            raise self._admin_error(e) from e
        except (OSError, ValueError) as e:
            raise ReplicaError(f"{self.name}: {e}") from e

    def stats(self) -> dict:
        now = time.monotonic()
        if (self._stats_cache is not None
                and now - self._stats_at < self.stats_ttl_s):
            return self._stats_cache
        out = {"slots_busy": 0, "slots_total": 0,
               "kv_blocks_free": 0, "kv_blocks_total": 0,
               "kv_block_size": 0, "adapters": None,
               "resident_adapters": None,
               "spec_enabled": False, "spec_accept_rate": None,
               "role": self.role, "sessions_parked": 0}
        try:
            with urllib.request.urlopen(
                    self.base_url + "/metrics", timeout=2) as r:
                for line in r.read().decode().splitlines():
                    # exemplars / unknown trailing annotations are stripped
                    # first: a mixed-version fleet must never break scraping
                    line = _strip_annotation(line)
                    # *_capacity is the PR 7 name; *_total accepted so a new
                    # gateway can front not-yet-restarted older replicas
                    if line.startswith("dtx_serving_slots_busy "):
                        out["slots_busy"] = int(float(line.split()[-1]))
                    elif line.startswith(("dtx_serving_slots_capacity ",
                                          "dtx_serving_slots_total ")):
                        out["slots_total"] = int(float(line.split()[-1]))
                    elif line.startswith("dtx_serving_kv_blocks_free "):
                        out["kv_blocks_free"] = int(float(line.split()[-1]))
                    elif line.startswith(("dtx_serving_kv_blocks_capacity ",
                                          "dtx_serving_kv_blocks_total ")):
                        out["kv_blocks_total"] = int(float(line.split()[-1]))
                    elif line.startswith("dtx_serving_kv_block_size "):
                        out["kv_block_size"] = int(float(line.split()[-1]))
                    elif line.startswith("dtx_serving_spec_enabled "):
                        out["spec_enabled"] = float(line.split()[-1]) > 0
                    elif line.startswith("dtx_serving_spec_accept_rate "):
                        out["spec_accept_rate"] = float(line.split()[-1])
                    elif line.startswith("dtx_serving_sessions_parked "):
                        out["sessions_parked"] = int(float(line.split()[-1]))
                    elif line.startswith('dtx_serving_role{role="'):
                        rest = line[len('dtx_serving_role{role="'):]
                        name = rest.split('"', 1)[0]
                        try:
                            if float(line.rsplit(None, 1)[-1]) == 1:
                                out["role"] = name
                                self.role = name  # routing reads the attr
                        except ValueError:
                            pass
                    else:
                        # residency/capability sets from the labeled gauges
                        # (absent series = no signal, stays None)
                        for prefix, key in (
                                ("dtx_serving_adapter_resident{",
                                 "resident_adapters"),
                                ("dtx_serving_adapter_registered{",
                                 "adapters")):
                            name = _adapter_label(line, prefix)
                            if name is not None:
                                if out[key] is None:
                                    out[key] = set()
                                out[key].add(name)
        except Exception:  # noqa: BLE001 — stats are advisory
            pass
        self._stats_cache = out
        self._stats_at = now
        return out

    def stats_snapshot(self) -> dict:
        """Never fetches: the last stats() result (routing keeps it warm
        under any traffic), or all-zeros/unknown before the first fetch."""
        if self._stats_cache is not None:
            return self._stats_cache
        return {"slots_busy": 0, "slots_total": 0,
                "kv_blocks_free": 0, "kv_blocks_total": 0,
                "kv_block_size": 0, "adapters": None,
                "resident_adapters": None,
                "spec_enabled": False, "spec_accept_rate": None,
                "role": self.role, "sessions_parked": 0}


class ReplicaPool:
    """Thread-safe replica set + periodic health checking.

    ``health_interval_s=0`` disables the background thread (tests drive
    ``check_health()`` explicitly)."""

    def __init__(self, replicas: Optional[List[Replica]] = None,
                 health_interval_s: float = 0.0):
        self._replicas: Dict[str, Replica] = {}
        self._lock = threading.Lock()
        for r in replicas or []:
            self._replicas[r.name] = r
        self._shutdown = threading.Event()
        self._thread = None
        if health_interval_s > 0:
            self._thread = threading.Thread(
                target=self._health_loop, args=(health_interval_s,),
                daemon=True)
            self._thread.start()

    # ------------------------------------------------------------ membership
    def add(self, replica: Replica):
        with self._lock:
            self._replicas[replica.name] = replica

    def remove(self, name: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.pop(name, None)

    def get(self, name: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(name)

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def available(self) -> List[Replica]:
        return [r for r in self.replicas() if r.available()]

    def drain(self, name: str) -> bool:
        r = self.get(name)
        if r is None:
            return False
        r.drain()
        return True

    # --------------------------------------------------------------- health
    def check_health(self):
        for r in self.replicas():
            r.probe_health()

    def _health_loop(self, interval: float):
        while not self._shutdown.wait(interval):
            self.check_health()

    # -------------------------------------------------------------- reports
    def circuit_states(self) -> Dict[str, str]:
        return {r.name: r.breaker.state for r in self.replicas()}

    def close(self):
        self._shutdown.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        for r in self.replicas():
            r.close()
