"""Inference gateway: multi-replica routing, admission control, failover.

Replaces the Ray Serve tier of the reference (RayService CRs fronting
LlamaDeployment replicas): the operator deploys N `serving.server` replicas
behind ONE gateway endpoint that spreads load, sheds overload with 429 +
Retry-After instead of OOMing a TPU replica, and survives a replica dying
mid-request. CPU-only and jax-free — the gateway never touches the model.

    replica_pool  — replica abstraction (in-process / HTTP), health checks,
                    per-replica circuit breaker, graceful drain
    router        — pluggable routing: least-busy-slots, round-robin,
                    session/prefix affinity, LoRA-adapter awareness
    admission     — bounded queue + prefill-token budget backpressure
    metrics       — Prometheus text exposition (counters/gauges/histograms)
    autoscale     — queue depth + p95 latency → replica-count hint the
                    operator consumes (operator/capacity.py)
    server        — the HTTP front-end + managed replica subprocess set
"""

from datatunerx_tpu.gateway.admission import AdmissionController, Overloaded
from datatunerx_tpu.gateway.replica_pool import (
    CircuitBreaker,
    HTTPReplica,
    InProcessReplica,
    NoReplicaAvailable,
    Replica,
    ReplicaError,
    ReplicaPool,
)
from datatunerx_tpu.gateway.router import Router

__all__ = [
    "AdmissionController",
    "CircuitBreaker",
    "HTTPReplica",
    "InProcessReplica",
    "NoReplicaAvailable",
    "Overloaded",
    "Replica",
    "ReplicaError",
    "ReplicaPool",
    "Router",
]
