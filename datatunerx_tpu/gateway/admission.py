"""Admission control: bounded queue + prefill-token budget backpressure.

Overload on a TPU replica is not graceful: an unbounded admission queue
turns into unbounded prefill work and eventually an HBM OOM that kills every
in-flight request on the chip. The gateway instead bounds BOTH the request
count and the estimated queued prefill tokens; past either limit it sheds
with 429 + Retry-After, so clients back off and in-flight requests finish
untouched (the degradation mode Ray Serve's max_concurrent_queries provides
in the reference).

Retry-After is derived from observed drain throughput (EWMA of completed
prefill tokens/s), so a shed client waits roughly one queue-drain, not a
fixed guess.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional


class Overloaded(Exception):
    def __init__(self, reason: str, retry_after_s: int):
        super().__init__(reason)
        self.reason = reason
        self.retry_after_s = retry_after_s


def estimate_prompt_tokens(
    messages: List[dict],
    chars_per_token: float = 4.0,
    count_tokens: Optional[Callable[[str], int]] = None,
) -> int:
    """Prefill-cost estimate for admission.

    With ``count_tokens`` (a real tokenizer's text→token-count function,
    wired when the gateway has the model's tokenizer) the estimate is exact
    up to template overhead. Without one, ~``chars_per_token`` chars/token
    (default 4, the BPE English average — configurable because CJK text runs
    ~1.5 chars/token and code ~3, which under/over-admits by 2x+) + a few
    tokens of template overhead per message. Only relative magnitude matters
    — the budget is calibrated in the same units."""
    total = 0
    for m in messages or []:
        content = str(m.get("content", ""))
        if count_tokens is not None:
            try:
                total += int(count_tokens(content)) + 4
                continue
            except Exception:  # noqa: BLE001 — estimator must never shed 500s
                pass
        total += int(len(content) / max(chars_per_token, 0.1)) + 4
    return max(1, total)


class Ticket:
    """An admitted request's reservation; release exactly once."""

    def __init__(self, controller: "AdmissionController", tokens: int):
        self._controller = controller
        self.tokens = tokens
        self._released = False

    def release(self):
        if not self._released:
            self._released = True
            self._controller._release(self.tokens)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class AdmissionController:
    def __init__(self, max_queue: int = 64, token_budget: int = 32768,
                 min_retry_after_s: int = 1, max_retry_after_s: int = 30,
                 chars_per_token: float = 4.0,
                 count_tokens: Optional[Callable[[str], int]] = None):
        self.max_queue = max_queue
        self.token_budget = token_budget
        self.min_retry_after_s = min_retry_after_s
        self.max_retry_after_s = max_retry_after_s
        self.chars_per_token = chars_per_token
        self.count_tokens = count_tokens
        self._depth = 0
        self._tokens = 0
        self._shed = 0
        self._lock = threading.Lock()
        # drain-rate EWMA (tokens/s) for the Retry-After estimate
        self._rate = 0.0
        self._last_release = time.monotonic()

    # ------------------------------------------------------------ admission
    def estimate(self, messages: List[dict]) -> int:
        return estimate_prompt_tokens(messages,
                                      chars_per_token=self.chars_per_token,
                                      count_tokens=self.count_tokens)

    def try_admit(self, messages: List[dict],
                  tokens: Optional[int] = None) -> Ticket:
        n = tokens if tokens is not None else self.estimate(messages)
        with self._lock:
            if self._depth + 1 > self.max_queue:
                self._shed += 1
                raise Overloaded(
                    f"queue full ({self._depth}/{self.max_queue} requests)",
                    self._retry_after_locked())
            if self._tokens + n > self.token_budget:
                self._shed += 1
                raise Overloaded(
                    f"prefill token budget exhausted ({self._tokens}+{n}"
                    f">{self.token_budget})",
                    self._retry_after_locked())
            self._depth += 1
            self._tokens += n
        return Ticket(self, n)

    def _release(self, tokens: int):
        now = time.monotonic()
        with self._lock:
            self._depth = max(0, self._depth - 1)
            self._tokens = max(0, self._tokens - tokens)
            dt = max(1e-3, now - self._last_release)
            self._last_release = now
            inst = tokens / dt
            self._rate = inst if self._rate == 0 else (
                0.8 * self._rate + 0.2 * inst)

    def _retry_after_locked(self) -> int:
        if self._rate > 0:
            est = self._tokens / self._rate
        else:
            est = float(self.max_retry_after_s)
        return int(min(self.max_retry_after_s,
                       max(self.min_retry_after_s, round(est))))

    # -------------------------------------------------------------- reports
    @property
    def depth(self) -> int:
        with self._lock:
            return self._depth

    @property
    def queued_tokens(self) -> int:
        with self._lock:
            return self._tokens

    @property
    def shed_count(self) -> int:
        with self._lock:
            return self._shed
