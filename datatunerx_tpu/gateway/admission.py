"""Admission control: bounded queue + prefill-token budget backpressure,
plus FLEET-TRUE block admission when the replicas report a paged KV pool.

Overload on a TPU replica is not graceful: an unbounded admission queue
turns into unbounded prefill work and eventually an HBM OOM that kills every
in-flight request on the chip. The gateway instead bounds BOTH the request
count and the estimated queued prefill tokens; past either limit it sheds
with 429 + Retry-After, so clients back off and in-flight requests finish
untouched (the degradation mode Ray Serve's max_concurrent_queries provides
in the reference).

Fleet-true mode (``fleet_blocks_fn`` wired by the gateway): the static
token budget is a calibration guess, but paged replicas publish their LIVE
free-block sum — the resource that actually caps concurrent sessions. Each
admit is priced in blocks (tokenized-prompt estimate + a decode headroom,
the overcommit-aware blocks-per-admit: engines running ``--kv_overcommit
on`` grow past the headroom on demand, so pricing the full ``max_tokens``
here would re-create the eager pessimism server-side), and admission sheds
when the price exceeds what the fleet has free, net of admits so recent the
replicas' gauges cannot reflect them yet. Dense fleets (or missing stats)
return no block signal and the static budget remains the only gate.

Retry-After is BLOCK-denominated when the fleet reports a paged pool:
successive ``fleet_blocks_fn`` samples yield a freed-blocks/s EWMA, and a
shed client waits roughly until the fleet has freed the blocks its admit
needs — the same currency admission itself is priced in. When that rate
is unpopulated (dense fleet, no frees observed yet) it falls back to the
token-drain EWMA (completed prefill tokens/s), so a shed client still
waits roughly one queue-drain, not a fixed guess. ``calibrate()`` lets the gateway feed REAL replica-side
tokenized prompt counts back (the serving response's ``usage``), so the
chars-per-token heuristic converges on the deployment's actual ratio when
no local tokenizer is available.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional


class Overloaded(Exception):
    def __init__(self, reason: str, retry_after_s: int):
        super().__init__(reason)
        self.reason = reason
        self.retry_after_s = retry_after_s


def estimate_prompt_tokens(
    messages: List[dict],
    chars_per_token: float = 4.0,
    count_tokens: Optional[Callable[[str], int]] = None,
) -> int:
    """Prefill-cost estimate for admission.

    With ``count_tokens`` (a real tokenizer's text→token-count function,
    wired when the gateway has the model's tokenizer) the estimate is exact
    up to template overhead. Without one, ~``chars_per_token`` chars/token
    (default 4, the BPE English average — configurable because CJK text runs
    ~1.5 chars/token and code ~3, which under/over-admits by 2x+) + a few
    tokens of template overhead per message. Only relative magnitude matters
    — the budget is calibrated in the same units."""
    total = 0
    for m in messages or []:
        content = str(m.get("content", ""))
        if count_tokens is not None:
            try:
                total += int(count_tokens(content)) + 4
                continue
            except Exception:  # noqa: BLE001 — estimator must never shed 500s
                pass
        total += int(len(content) / max(chars_per_token, 0.1)) + 4
    return max(1, total)


class Ticket:
    """An admitted request's reservation; release exactly once."""

    def __init__(self, controller: "AdmissionController", tokens: int,
                 tenant: str = "", blocks: int = 0):
        self._controller = controller
        self.tokens = tokens
        self.tenant = tenant
        self.blocks = blocks
        self._released = False

    def release(self):
        if not self._released:
            self._released = True
            self._controller._release(self.tokens, tenant=self.tenant,
                                      blocks=self.blocks)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class AdmissionController:
    def __init__(self, max_queue: int = 64, token_budget: int = 32768,
                 min_retry_after_s: int = 1, max_retry_after_s: int = 30,
                 chars_per_token: float = 4.0,
                 count_tokens: Optional[Callable[[str], int]] = None,
                 fleet_blocks_fn: Optional[Callable[[], Optional[dict]]] = None,
                 decode_headroom_tokens: int = 64,
                 pending_window_s: float = 2.0,
                 share_enforce_util: float = 0.8):
        self.max_queue = max_queue
        self.token_budget = token_budget
        self.min_retry_after_s = min_retry_after_s
        self.max_retry_after_s = max_retry_after_s
        self.chars_per_token = chars_per_token
        self.count_tokens = count_tokens
        # fleet-true block admission: () -> {"free", "total", "block_size"}
        # summed over available paged replicas, or None (no block signal)
        self.fleet_blocks_fn = fleet_blocks_fn
        self.decode_headroom_tokens = decode_headroom_tokens
        # admits so recent the replicas' scraped free-block gauges cannot
        # reflect their engine-side reservation yet — counted against the
        # fleet sum for one stats-refresh window, then auto-expired (the
        # live gauge carries them from there; keeping the reserve for the
        # whole request lifetime would double-count every running session)
        self.pending_window_s = pending_window_s
        self._pending_blocks: List[tuple] = []  # (t_admit, blocks)
        # weighted-fair shares only bite once the GLOBAL budget is this
        # contended — an idle gateway lets any tenant burst past its share
        # (work-conserving, the smooth-WRR property the router already has)
        self.share_enforce_util = share_enforce_util
        self._tenant_tokens: dict = {}  # tenant -> in-flight prefill tokens
        self._tenant_blocks: dict = {}  # tenant -> in-flight priced blocks
        self._depth = 0
        self._tokens = 0
        self._shed = 0
        self._lock = threading.Lock()
        # drain-rate EWMA (tokens/s) for the Retry-After estimate
        self._rate = 0.0
        self._last_release = time.monotonic()
        # block-drain EWMA (freed blocks/s) from successive fleet samples:
        # the Retry-After currency once admission is priced in blocks.
        # Only POSITIVE free-count deltas feed it (a growing free count is
        # the fleet draining; admissions shrinking it are not a drain).
        self._blocks_rate = 0.0
        self._last_fleet: Optional[tuple] = None  # (t, free)

    # ------------------------------------------------------------ admission
    def estimate(self, messages: List[dict]) -> int:
        return estimate_prompt_tokens(messages,
                                      chars_per_token=self.chars_per_token,
                                      count_tokens=self.count_tokens)

    def calibrate(self, chars: int, tokens: int):
        """Fold one observed (prompt chars, replica-side tokenized count)
        pair into the chars-per-token estimate — truthful token counts
        over the wire replace the static heuristic as traffic flows. A
        wired ``count_tokens`` still wins at estimate time; this keeps the
        fallback honest for gateways without the model's tokenizer."""
        if tokens <= 0 or chars <= 0:
            return
        ratio = max(0.1, chars / tokens)
        with self._lock:
            self.chars_per_token = (0.8 * self.chars_per_token
                                    + 0.2 * ratio)

    def blocks_for_admit(self, tokens: int, block_size: int) -> int:
        """Overcommit-aware blocks-per-admit estimate: the tokenized
        prompt plus a decode headroom, in blocks — what one admission
        costs an overcommitted engine up front (lazy growth covers the
        rest; an eager fleet simply sheds a little later than its own
        FIFO would queue)."""
        bs = max(1, int(block_size))
        return -(-(tokens + self.decode_headroom_tokens) // bs)

    def try_admit(self, messages: List[dict],
                  tokens: Optional[int] = None,
                  tenant: Optional[dict] = None) -> Ticket:
        """Admit or shed. ``tenant`` (when the gateway runs a tenant
        directory) is ``{"name", "share", "share_total", "kv_block_quota"}``
        — the resolved tenant's pricing row. ``None`` takes exactly the
        pre-tenancy path, byte for byte."""
        n = tokens if tokens is not None else self.estimate(messages)
        fleet = None
        if self.fleet_blocks_fn is not None:
            try:
                fleet = self.fleet_blocks_fn()
            except Exception:  # noqa: BLE001 — a stats fault must not shed 500s
                fleet = None
        t_name = str(tenant.get("name", "")) if tenant else ""
        with self._lock:
            if fleet and fleet.get("total"):
                self._note_fleet_locked(fleet)
            if self._depth + 1 > self.max_queue:
                self._shed += 1
                raise Overloaded(
                    f"queue full ({self._depth}/{self.max_queue} requests)",
                    self._retry_after_locked())
            if self._tokens + n > self.token_budget:
                self._shed += 1
                raise Overloaded(
                    f"prefill token budget exhausted ({self._tokens}+{n}"
                    f">{self.token_budget})",
                    self._retry_after_locked())
            if tenant:
                # weighted-fair share: once the global budget is contended,
                # tenant i holds at most share_i/Σshares of it. Below the
                # contention watermark any tenant may burst (work-conserving).
                share = float(tenant.get("share", 1) or 1)
                total = float(tenant.get("share_total", share) or share)
                contended = (self._tokens + n
                             > self.share_enforce_util * self.token_budget)
                cap = int(self.token_budget * share / max(total, share))
                held = self._tenant_tokens.get(t_name, 0)
                if contended and held + n > cap:
                    self._shed += 1
                    raise Overloaded(
                        f"tenant {t_name} over fair share "
                        f"({held}+{n}>{cap} tokens, share {share:g}/"
                        f"{total:g})",
                        self._retry_after_locked())
            t_blocks = 0
            if tenant:
                # KV-block quota is enforced whether or not the fleet
                # publishes a block signal — without one the default block
                # size prices the admit, so a quota'd tenant is still
                # capped on a dense fleet
                quota = int(tenant.get("kv_block_quota", 0) or 0)
                bs = (fleet.get("block_size") or 16) if fleet else 16
                t_blocks = self.blocks_for_admit(n, bs)
                t_held = self._tenant_blocks.get(t_name, 0)
                if quota > 0 and t_held + t_blocks > quota:
                    self._shed += 1
                    raise Overloaded(
                        f"tenant {t_name} KV block quota exhausted "
                        f"({t_held}+{t_blocks}>{quota} blocks)",
                        self._retry_after_locked(
                            block_deficit=t_held + t_blocks - quota))
            if fleet and fleet.get("total"):
                now = time.monotonic()
                self._pending_blocks = [
                    (t, b) for t, b in self._pending_blocks
                    if now - t < self.pending_window_s]
                pending = sum(b for _, b in self._pending_blocks)
                need = self.blocks_for_admit(
                    n, fleet.get("block_size") or 16)
                free = int(fleet.get("free", 0))
                if need + pending > free:
                    self._shed += 1
                    raise Overloaded(
                        f"fleet KV blocks exhausted (need {need}, "
                        f"free {free}, pending {pending})",
                        self._retry_after_locked(
                            block_deficit=need + pending - free))
                self._pending_blocks.append((now, need))
            self._depth += 1
            self._tokens += n
            if tenant:
                self._tenant_tokens[t_name] = (
                    self._tenant_tokens.get(t_name, 0) + n)
                self._tenant_blocks[t_name] = (
                    self._tenant_blocks.get(t_name, 0) + t_blocks)
        return Ticket(self, n, tenant=t_name if tenant else "",
                      blocks=t_blocks)

    def _release(self, tokens: int, tenant: str = "", blocks: int = 0):
        now = time.monotonic()
        with self._lock:
            self._depth = max(0, self._depth - 1)
            self._tokens = max(0, self._tokens - tokens)
            if tenant in self._tenant_tokens:
                left = self._tenant_tokens[tenant] - tokens
                if left > 0:
                    self._tenant_tokens[tenant] = left
                else:
                    self._tenant_tokens.pop(tenant, None)
            if tenant in self._tenant_blocks:
                left = self._tenant_blocks[tenant] - blocks
                if left > 0:
                    self._tenant_blocks[tenant] = left
                else:
                    self._tenant_blocks.pop(tenant, None)
            dt = max(1e-3, now - self._last_release)
            self._last_release = now
            inst = tokens / dt
            self._rate = inst if self._rate == 0 else (
                0.8 * self._rate + 0.2 * inst)

    def _note_fleet_locked(self, fleet: dict):
        """Fold one fleet free-block sample into the freed-blocks/s EWMA.
        Only positive deltas count: a rising free count is the fleet
        draining; admissions pulling it down are not drain throughput.
        Unchanged samples (the replicas' stats TTL cache) are skipped so
        they neither decay nor inflate the rate."""
        now = time.monotonic()
        free = int(fleet.get("free", 0))
        if self._last_fleet is not None:
            t0, f0 = self._last_fleet
            dt = now - t0
            freed = free - f0
            if dt >= 1e-3 and freed > 0:
                inst = freed / dt
                self._blocks_rate = inst if self._blocks_rate == 0 else (
                    0.8 * self._blocks_rate + 0.2 * inst)
        self._last_fleet = (now, free)

    def _retry_after_locked(self, block_deficit: int = 0) -> int:
        if block_deficit > 0 and self._blocks_rate > 0:
            # block-denominated: wait until the fleet has freed the
            # blocks this admit is short by, at the observed drain rate
            est = block_deficit / self._blocks_rate
        elif self._rate > 0:
            est = self._tokens / self._rate
        else:
            est = float(self.max_retry_after_s)
        return int(min(self.max_retry_after_s,
                       max(self.min_retry_after_s, round(est))))

    # -------------------------------------------------------------- reports
    @property
    def depth(self) -> int:
        with self._lock:
            return self._depth

    @property
    def queued_tokens(self) -> int:
        with self._lock:
            return self._tokens

    @property
    def shed_count(self) -> int:
        with self._lock:
            return self._shed

    def tenant_usage(self) -> dict:
        """Per-tenant in-flight reservations (tokens and priced blocks)
        — the gateway restates these as dtx_gateway_tenant_* gauges."""
        with self._lock:
            return {"tokens": dict(self._tenant_tokens),
                    "blocks": dict(self._tenant_blocks)}
