// Native data-path hot loops: batch buffer filling + first-fit packing.
//
// The reference delegates its data plane to Ray Data + HF collators (reference
// cmd/tuning/train.py:329-351, :282-286); our TPU loader needs static-shape
// batches assembled host-side every step, which in Python costs a per-example
// interpreter loop. These loops are the framework's native (C++) component —
// built once with g++ into dtx_native.so and bound via ctypes
// (datatunerx_tpu/native/__init__.py), with a pure-Python fallback.
//
// Exposed (extern "C"):
//   dtx_fill_batch:  scatter variable-length token/label rows into fixed
//                    [B, block] int32 buffers (pad_id / ignore_index padding)
//   dtx_first_fit:   greedy first-fit-decreasing bin packing of row lengths
//   dtx_fill_packed: scatter rows into packed buffers with segment ids,
//                    per-segment positions, and boundary label masking

#include <cstdint>
#include <cstring>

extern "C" {

// tokens/labels: concatenated example arrays; offsets[i]..offsets[i+1] is
// example i. Rows are right-padded to block; labels padded with ignore_index.
void dtx_fill_batch(
    const int32_t* tokens, const int32_t* labels, const int64_t* offsets,
    int64_t n_examples, int64_t block, int32_t pad_id, int32_t ignore_index,
    int32_t* out_tokens, int32_t* out_labels, int32_t* out_attn) {
  for (int64_t i = 0; i < n_examples; ++i) {
    int64_t start = offsets[i];
    int64_t len = offsets[i + 1] - start;
    if (len > block) len = block;
    int32_t* trow = out_tokens + i * block;
    int32_t* lrow = out_labels + i * block;
    int32_t* arow = out_attn + i * block;
    std::memcpy(trow, tokens + start, len * sizeof(int32_t));
    std::memcpy(lrow, labels + start, len * sizeof(int32_t));
    for (int64_t t = 0; t < len; ++t) arow[t] = 1;
    for (int64_t t = len; t < block; ++t) {
      trow[t] = pad_id;
      lrow[t] = ignore_index;
      arow[t] = 0;
    }
  }
}

// lengths: per-example lengths SORTED DESCENDING by the caller (with
// `order` carrying original indices). Assigns each example a row id via
// greedy first-fit; returns the number of rows used.
int64_t dtx_first_fit(
    const int64_t* lengths, int64_t n, int64_t block,
    int64_t* row_of,  // out: row id per (sorted) example
    int64_t* row_used  // scratch+out: capacity n, bytes used per row
) {
  int64_t n_rows = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t len = lengths[i] < block ? lengths[i] : block;
    int64_t placed = -1;
    for (int64_t r = 0; r < n_rows; ++r) {
      if (row_used[r] + len <= block) {
        placed = r;
        break;
      }
    }
    if (placed < 0) {
      placed = n_rows++;
      row_used[placed] = 0;
    }
    row_of[i] = placed;
    row_used[placed] += len;
  }
  return n_rows;
}

// Scatter examples into packed rows. row_of/row_offset are per-example
// (row id, starting column) computed by the caller from dtx_first_fit.
// seg_of[i] is the 1-based segment index within its row.
void dtx_fill_packed(
    const int32_t* tokens, const int32_t* labels, const int64_t* offsets,
    const int64_t* row_of, const int64_t* row_offset, const int64_t* seg_of,
    int64_t n_examples, int64_t block, int32_t ignore_index,
    int32_t* out_tokens, int32_t* out_labels, int32_t* out_attn,
    int32_t* out_segs, int32_t* out_pos) {
  for (int64_t i = 0; i < n_examples; ++i) {
    int64_t start = offsets[i];
    int64_t len = offsets[i + 1] - start;
    int64_t off = row_offset[i];
    if (len > block - off) len = block - off;
    if (len <= 0) continue;
    int64_t row = row_of[i];
    int32_t* trow = out_tokens + row * block + off;
    int32_t* lrow = out_labels + row * block + off;
    int32_t* arow = out_attn + row * block + off;
    int32_t* srow = out_segs + row * block + off;
    int32_t* prow = out_pos + row * block + off;
    std::memcpy(trow, tokens + start, len * sizeof(int32_t));
    std::memcpy(lrow, labels + start, len * sizeof(int32_t));
    // shifted-CE boundary: never train a segment's first token from the
    // previous segment's last (mirrors preprocess.pack_to_block)
    lrow[0] = ignore_index;
    for (int64_t t = 0; t < len; ++t) {
      arow[t] = 1;
      srow[t] = (int32_t)seg_of[i];
      prow[t] = (int32_t)t;
    }
  }
}

}  // extern "C"
