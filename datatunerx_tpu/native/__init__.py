"""Native extension loader: builds packer.cpp with g++ on first use and binds
it via ctypes. Every entry point has a pure-Python fallback in data/preprocess
— absence of a toolchain degrades performance, never correctness."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "packer.cpp")
_BUILD_DIR = os.path.join(_HERE, "_build")
_SO = os.path.join(_BUILD_DIR, "dtx_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")


def _build() -> Optional[str]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    # compile to a per-process temp name + atomic rename: concurrent processes
    # (operator + trainers) may build simultaneously and a partial .so must
    # never be visible at the final path
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return _SO
    except Exception:
        try:
            os.remove(tmp)
        except OSError:
            pass
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # serializing every caller behind the one-time first-use compile is
        # the point (a second concurrent g++ on the same .so would race);
        # the subprocess.run inside carries timeout=120
        so = _build()  # dtxlint: disable=DTX009 -- deliberate one-time build under lock
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
            lib.dtx_fill_batch.argtypes = [
                _i32p, _i32p, _i64p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int32, ctypes.c_int32, _i32p, _i32p, _i32p,
            ]
            lib.dtx_fill_batch.restype = None
            lib.dtx_first_fit.argtypes = [
                _i64p, ctypes.c_int64, ctypes.c_int64, _i64p, _i64p,
            ]
            lib.dtx_first_fit.restype = ctypes.c_int64
            lib.dtx_fill_packed.argtypes = [
                _i32p, _i32p, _i64p, _i64p, _i64p, _i64p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
                _i32p, _i32p, _i32p, _i32p, _i32p,
            ]
            lib.dtx_fill_packed.restype = None
        except (OSError, AttributeError):
            return None  # corrupt/stale artifact — Python fallback, never a crash
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def _concat(examples, key):
    lens = np.asarray([len(e[key]) for e in examples], np.int64)
    offsets = np.zeros(len(examples) + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    flat = np.empty(int(offsets[-1]), np.int32)
    for i, e in enumerate(examples):
        flat[offsets[i]: offsets[i + 1]] = e[key]
    return flat, offsets


def _lengths_consistent(examples) -> bool:
    """The C++ paths slice labels with the input_ids offsets; mismatched
    per-example lengths would misalign the memcpy — defer to Python."""
    return all(len(e["input_ids"]) == len(e["labels"]) for e in examples)


def fill_batch_native(examples, block: int, pad_id: int, ignore_index: int):
    lib = get_lib()
    if lib is None or not _lengths_consistent(examples):
        return None
    tokens, offsets = _concat(examples, "input_ids")
    labels, _ = _concat(examples, "labels")
    B = len(examples)
    out_t = np.empty((B, block), np.int32)
    out_l = np.empty((B, block), np.int32)
    out_a = np.empty((B, block), np.int32)
    lib.dtx_fill_batch(tokens, labels, offsets, B, block, pad_id, ignore_index,
                       out_t.reshape(-1), out_l.reshape(-1), out_a.reshape(-1))
    return {"input_ids": out_t, "labels": out_l, "attention_mask": out_a}


def pack_batch_native(examples, block: int, pad_id: int, ignore_index: int):
    lib = get_lib()
    if lib is None or not _lengths_consistent(examples):
        return None
    order = sorted(range(len(examples)),
                   key=lambda i: -len(examples[i]["input_ids"]))
    sorted_ex = [examples[i] for i in order]
    lengths = np.asarray(
        [min(len(e["input_ids"]), block) for e in sorted_ex], np.int64)
    n = len(sorted_ex)
    row_of = np.empty(n, np.int64)
    row_used = np.zeros(n, np.int64)
    n_rows = int(lib.dtx_first_fit(lengths, n, block, row_of, row_used))

    # per-example start column + 1-based segment index within its row
    row_fill = np.zeros(n_rows, np.int64)
    row_segs = np.zeros(n_rows, np.int64)
    row_offset = np.empty(n, np.int64)
    seg_of = np.empty(n, np.int64)
    for i in range(n):
        r = row_of[i]
        row_offset[i] = row_fill[r]
        row_fill[r] += lengths[i]
        row_segs[r] += 1
        seg_of[i] = row_segs[r]

    tokens, offsets = _concat(sorted_ex, "input_ids")
    labels, _ = _concat(sorted_ex, "labels")
    out = {
        "input_ids": np.full((n_rows, block), pad_id, np.int32),
        "labels": np.full((n_rows, block), ignore_index, np.int32),
        "attention_mask": np.zeros((n_rows, block), np.int32),
        "segment_ids": np.zeros((n_rows, block), np.int32),
        "positions": np.zeros((n_rows, block), np.int32),
    }
    lib.dtx_fill_packed(
        tokens, labels, offsets, row_of, row_offset, seg_of, n, block,
        ignore_index,
        out["input_ids"].reshape(-1), out["labels"].reshape(-1),
        out["attention_mask"].reshape(-1), out["segment_ids"].reshape(-1),
        out["positions"].reshape(-1),
    )
    return out
