"""Speculative decoding for the batched serving engine (ISSUE 14).

PR 13's Pallas kernel made each decode step cheap on HBM; this module makes
each TARGET step emit more than one token. A small draft model proposes ``k``
tokens autoregressively, the target model runs ONE verify-k forward over the
proposed positions, and a batched rejection/residual acceptance rule keeps the
longest agreeing prefix plus one corrected token — so a target forward
amortizes over ``1 + accepted`` emitted tokens while staying
**distribution-exact**:

- greedy (``temperature <= 0``): a proposal is accepted iff it equals the
  target argmax at its position, and the corrected token IS the target
  argmax — the emitted stream is token-identical to vanilla greedy decode;
- sampled: the standard speculative scheme (Leviathan et al. / Chen et al.):
  accept ``d_j`` with prob ``min(1, p_j(d_j)/q_j(d_j))``; on first rejection
  sample from the residual ``norm(max(p_j - q_j, 0))``; on full acceptance
  sample the bonus token from ``p_k``. Every emitted token is marginally
  distributed exactly as a sample from the target distribution, driven by the
  slot's live PRNG key (the same first-class key the KV-migration payload
  carries).

The engine-side state machine (``BatchedEngine._spec_decode_tick``) keeps
slots in **pending-token form**: the most recently emitted token's KV is not
yet written; each step feeds ``[pending, d_0..d_{k-1}]`` through the target so
the bonus/corrected token needs no extra forward. ``SpecPrograms`` below holds
the jitted device programs (process-memoized like the engine's ``_Programs``);
the acceptance math is pure and unit-testable.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from datatunerx_tpu.models.llama import forward, init_cache
from datatunerx_tpu.ops.attention import compact_window
from datatunerx_tpu.ops.pallas_sampling import fused_sample, sample_rows
from datatunerx_tpu.serving.engine import _sample_jit

SPEC_MODES = ("auto", "on", "off")
SAMPLING_EPILOGUES = ("auto", "on", "off")


# ------------------------------------------------------------- tree topology
@dataclasses.dataclass(frozen=True)
class TreeSpec:
    """``--spec_tree WxD``: W parallel draft chains of depth D sharing the
    pending root. The verify window flattens depth-major: column 0 is the
    pending token, node (depth j, branch b) sits at column
    ``1 + (j-1)*W + b`` with rope position ``pos + j`` — siblings SHARE a
    rope position, which is why tree verification needs the branch
    ancestry mask (``tree_verify_mask``) on top of the causal check."""

    width: int
    depth: int

    @property
    def step_tokens(self) -> int:
        """Tokens one tree step writes per slot (pending + all nodes) —
        the overshoot / window width / verify-column count."""
        return 1 + self.width * self.depth

    def __str__(self) -> str:
        return f"{self.width}x{self.depth}"


def parse_spec_tree(spec: str) -> TreeSpec:
    """Parse ``--spec_tree`` / ``serveConfig.specTree`` ``"WxD"`` strings."""
    err = (f"spec_tree must be 'WxD' (branch width x draft depth, e.g. "
           f"'4x3'), got {spec!r}")
    parts = str(spec).strip().lower().split("x")
    if len(parts) != 2:
        raise ValueError(err)
    try:
        width, depth = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(err) from None
    if not 1 <= width <= 64 or not 1 <= depth <= 16:
        raise ValueError(
            f"spec_tree {spec!r} out of range: width must be in [1, 64] "
            "and depth in [1, 16]")
    return TreeSpec(width, depth)


def _tree_col(j: int, b: int, width: int) -> int:
    """Verify-window column of tree node (depth ``j`` >= 1, branch ``b``)
    in the RECTANGLE layout (every depth ``width`` wide)."""
    return 1 + (j - 1) * width + b


def _widths_tuple(width, depth=None) -> tuple:
    """Canonical per-depth widths: ``(W, D)`` ints mean the fixed rectangle
    ``(W,) * D``; an explicit sequence is the learned ragged shape. Widths
    must be monotone NON-INCREASING — that makes every branch chain
    prefix-live (branch b exists at depth j ⇒ it exists at every shallower
    depth), which is what keeps ragged ancestry masks, clamped gathers and
    the chain acceptance rule correct."""
    if depth is not None:
        ws = (int(width),) * int(depth)
    else:
        ws = tuple(int(w) for w in width)
    if not ws or any(w < 1 for w in ws):
        raise ValueError(f"tree widths must all be >= 1, got {ws}")
    if any(b > a for a, b in zip(ws, ws[1:])):
        raise ValueError(
            f"tree widths must be non-increasing (branch chains must be "
            f"prefix-live), got {ws}")
    return ws


def _width_offsets(ws: tuple) -> list:
    """Flattened-window column of each depth's first node: depth j
    (1-indexed) occupies columns ``offs[j-1] .. offs[j-1]+ws[j-1]-1``;
    column 0 is the pending root."""
    offs, c = [], 1
    for w in ws:
        offs.append(c)
        c += w
    return offs


def tree_verify_mask(width, depth=None) -> np.ndarray:
    """Static [T, T] branch ancestry mask for the verify forward: query
    column c may attend window column c' iff c' is on c's root-to-self
    path. Combined with the causal check inside ``attention_allow`` (which
    still excludes unwritten sentinel lanes), this is exactly the oracle
    bias a sequential per-branch verify would build.

    Accepts ``(W, D)`` ints (the fixed rectangle) or one per-depth widths
    tuple (the learned ragged shape, ``T = 1 + sum(widths)``)."""
    ws = _widths_tuple(width, depth)
    offs = _width_offsets(ws)
    T = 1 + sum(ws)
    mask = np.zeros((T, T), dtype=bool)
    mask[0, 0] = True
    for j, w in enumerate(ws, start=1):
        for b in range(w):
            c = offs[j - 1] + b
            mask[c, 0] = True
            for i in range(1, j + 1):
                mask[c, offs[i - 1] + b] = True
    return mask


def tree_draft_mask(width, j: int) -> np.ndarray:
    """Static window mask for the draft's depth-``j`` forward: branch b's
    query attends the pending root, its own ancestors, and its own write
    lane — never a sibling chain. ``width`` is an int (rectangle: shape
    ``[W, 1 + j*W]``) or the per-depth widths tuple (ragged: shape
    ``[ws[j-1], 1 + sum(ws[:j])]``)."""
    ws = _widths_tuple(width, j) if isinstance(width, int) else \
        _widths_tuple(width)
    offs = _width_offsets(ws)
    w = ws[j - 1]
    mask = np.zeros((w, 1 + sum(ws[:j])), dtype=bool)
    for b in range(w):
        mask[b, 0] = True
        for i in range(1, j + 1):
            mask[b, offs[i - 1] + b] = True
    return mask


# ------------------------------------------------------------- sampling math
def sampling_probs(logits: jnp.ndarray, temperature, top_p,
                   exact_topp: bool = True) -> jnp.ndarray:
    """The probability vector ``_sample_jit`` samples from ([V] float32).

    Greedy (``temperature <= 0``) is a one-hot argmax; otherwise the top-p
    truncated, renormalized softmax of ``logits / temperature`` — computed in
    the same sorted space as ``_sample_jit`` so the two agree exactly (the
    categorical over ``filtered`` logits IS the renormalized kept mass).
    The acceptance rule must divide/subtract these, so they are materialized
    here instead of re-deriving the filter at every use site.

    ``exact_topp=False`` is a STATIC fast path for batches where no live row
    actually filters (every ``top_p >= 1``): the cut never triggers, so the
    distribution is plain ``softmax(logits/t)`` and the full-vocab sort —
    the single most expensive op in the verify program — never compiles.
    The caller asserts the batch property; passing a filtering row through
    the fast path would be WRONG, not just slow."""
    V = logits.shape[-1]
    greedy = jax.nn.one_hot(jnp.argmax(logits), V, dtype=jnp.float32)

    t = jnp.maximum(temperature, 1e-6)
    scaled = logits / t
    if exact_topp:
        sorted_idx = jnp.argsort(-scaled)
        sorted_logits = scaled[sorted_idx]
        probs = jax.nn.softmax(sorted_logits)
        cum = jnp.cumsum(probs)
        cut = (cum - probs > top_p) & (top_p < 1.0)
        kept = jnp.where(cut, 0.0, probs)
        kept = kept / jnp.maximum(kept.sum(), 1e-30)
        sampled = jnp.zeros((V,), jnp.float32).at[sorted_idx].set(kept)
    else:
        sampled = jax.nn.softmax(scaled)

    return jnp.where(temperature <= 0.0, greedy, sampled)


def accept_tokens(p_probs: jnp.ndarray, q_probs: jnp.ndarray,
                  draft_toks: jnp.ndarray, temperature, rng,
                  spec_on) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One row's rejection/residual acceptance (traceable; vmapped by the
    verify program, unit-tested directly).

    ``p_probs`` [k+1, V]: target distributions at the k proposed positions
    plus the bonus position; ``q_probs`` [k, V]: the draft distributions each
    proposal was sampled from; ``draft_toks`` [k]. Returns ``(n_accept,
    extra_token, new_rng)`` — the row emits ``draft_toks[:n_accept]`` then
    ``extra_token`` (subject to the engine's stop/budget truncation).

    ``spec_on=False`` rows force zero acceptances AND a zero draft
    distribution, so the "residual" degenerates to the plain target
    distribution ``p_0`` — the row takes an ordinary single-token step
    inside the same program."""
    k = draft_toks.shape[0]
    rng, u_key, x_key = jax.random.split(rng, 3)
    us = jax.random.uniform(u_key, (k,))
    idx = jnp.arange(k)
    p_at = p_probs[idx, draft_toks]
    q_at = q_probs[idx, draft_toks]
    greedy = temperature <= 0.0
    tgt_argmax = jnp.argmax(p_probs, axis=-1)  # [k+1]
    # u < min(1, p/q) in the division-free form; the q_at > 0 guard is
    # belt-and-braces (the draft sampled the token FROM q, so q_at > 0 in
    # any real flow) and applies to the ratio test only — greedy acceptance
    # is pure argmax comparison and never consults q
    ok_sampled = (us * q_at <= p_at) & (q_at > 0.0)
    ok_greedy = draft_toks == tgt_argmax[:k]
    ok = jnp.where(greedy, ok_greedy, ok_sampled) & spec_on
    acc_prefix = jnp.cumprod(ok.astype(jnp.int32))
    a = jnp.sum(acc_prefix).astype(jnp.int32)  # 0..k, first rejection stops

    p_a = p_probs[a]
    q_pad = jnp.concatenate([q_probs, jnp.zeros_like(q_probs[:1])], axis=0)
    q_a = jnp.where(spec_on, q_pad[a], jnp.zeros_like(p_a))
    resid = jnp.clip(p_a - q_a, 0.0, None)
    tot = resid.sum()
    # numerically-empty residual (p ≈ q): any sample from p_a is correct
    resid = jnp.where(tot > 0.0, resid / jnp.maximum(tot, 1e-30), p_a)
    extra_sampled = jax.random.categorical(
        x_key, jnp.log(jnp.maximum(resid, 1e-30))).astype(jnp.int32)
    extra = jnp.where(greedy, tgt_argmax[a], extra_sampled).astype(jnp.int32)
    return a, extra, rng


def accept_tree_tokens(p_cols: jnp.ndarray, q_tree: jnp.ndarray,
                       d_toks: jnp.ndarray, temperature, rng, spec_on,
                       *, width: int = 0, depth: int = 0,
                       widths: Optional[tuple] = None):
    """One row's tree acceptance (traceable; vmapped by the tree-verify
    program, unit-tested directly).

    ``p_cols`` [T, V]: target distributions at every verify column (column
    0 = pending, node (j, b) at ``_tree_col``); ``q_tree`` [D, W, V]: the
    draft distribution each node's token was sampled from (``q_tree[0]``
    is the shared root distribution all depth-1 siblings were drawn iid
    from); ``d_toks`` [D, W]. Returns ``(n_accept, branch, extra_token,
    new_rng)`` — the row emits the chosen branch's first ``n_accept``
    tokens then ``extra_token``.

    Exactness:

    - greedy (``temperature <= 0``): a node survives iff its token equals
      the target argmax at its parent column; the deepest surviving branch
      wins and the corrected/bonus token is the argmax at the divergence —
      the emitted stream is token-identical to sequential greedy decode
      (siblings are distinct by top-k, so at most one survives depth 1).
    - sampled: SpecInfer-style recursive rejection across the depth-1
      siblings — test each against the running residual (``r ← norm(max(r
      - q, 0))`` after every rejection), which keeps the emitted marginal
      EXACTLY ``p`` no matter how many siblings are tried — then the
      standard Leviathan/Chen chain rule down the accepted branch, with
      the usual residual at the first chain rejection and the bonus
      distribution at full depth.

    ``spec_on=False`` rows reject every sibling WITHOUT consuming residual
    mass (the update is gated), so the final "residual" is the plain
    target distribution ``p_0`` — the row takes an ordinary single-token
    step inside the same program, exactly like ``accept_tokens``.

    ``widths`` (learned ragged shapes, ISSUE 20): a monotone non-increasing
    per-depth widths tuple. ``p_cols`` is then the ragged flattened window
    ``[1 + sum(widths), V]`` (node (j, b) at ``_width_offsets(widths)[j-1]
    + b``) while ``q_tree``/``d_toks`` STAY the ``[D, W, V]`` / ``[D, W]``
    rectangle with ``W = widths[0]`` — the caller zero-pads dead ``q_tree``
    lanes and sets dead ``d_toks`` lanes to -1. Dead lanes then lose every
    test for free: a -1 token never equals a target argmax, and a zero
    ``q_at`` fails the ratio guard — so a branch's chain stops at its live
    depth, and the residual row at exactly the live depth degenerates to
    ``norm(clip(p - 0, 0)) = p``, which IS the bonus distribution."""
    ws = _widths_tuple(widths) if widths is not None else \
        _widths_tuple(width, depth)
    W, D = ws[0], len(ws)
    offs = _width_offsets(ws)
    rng, u_key, x_key = jax.random.split(rng, 3)
    us = jax.random.uniform(u_key, (W + D - 1,)) if W + D - 1 else \
        jnp.zeros((0,))
    greedy = temperature <= 0.0

    # ---- sampled: W-round sibling rejection at depth 1
    r = p_cols[0]
    q0 = q_tree[0, 0]
    b_star = jnp.asarray(-1, jnp.int32)
    accepted = jnp.asarray(False)
    for b in range(W):
        x = d_toks[0, b]
        q_at = q0[x]
        ok = (~accepted) & spec_on & (q_at > 0.0) & (us[b] * q_at <= r[x])
        b_star = jnp.where(ok, jnp.asarray(b, jnp.int32), b_star)
        accepted = accepted | ok
        r_new = jnp.clip(r - q0, 0.0, None)
        tot = r_new.sum()
        r_new = jnp.where(tot > 0.0, r_new / jnp.maximum(tot, 1e-30), r)
        r = jnp.where((~accepted) & spec_on, r_new, r)

    # ---- chain rule down the accepted branch (depths 2..D)
    bsafe = jnp.maximum(b_star, 0)
    toks_b = d_toks[:, bsafe]                                   # [D]
    # clamped per-depth column gather: a branch past its live depth reads
    # the depth's LAST live column — the value is never consulted (its
    # zero q_at already failed the chain), the clamp only keeps the
    # gather in-bounds for ragged widths
    col_tab = jnp.asarray(
        np.array([[offs[j] + min(b, ws[j] - 1) for b in range(W)]
                  for j in range(D)], np.int32))                # [D, W]
    cols_b = col_tab[:, bsafe]                                  # [D]
    p_b = p_cols[cols_b]                                        # [D, V]
    q_b = q_tree[:, bsafe]                                      # [D, V]
    if D > 1:
        jidx = jnp.arange(D - 1)
        p_at = p_b[jidx, toks_b[1:]]
        q_at = q_b[jidx + 1, toks_b[1:]]
        ok_chain = (us[W + jidx] * q_at <= p_at) & (q_at > 0.0)
        nacc = jnp.sum(jnp.cumprod(ok_chain.astype(jnp.int32)))
    else:
        nacc = jnp.asarray(0, jnp.int32)
    a_sampled = jnp.where(accepted, 1 + nacc, 0).astype(jnp.int32)

    # extra-token distribution table indexed by the acceptance count:
    # row 0 = the post-sibling residual, rows 1..D-1 = the chain-rejection
    # residuals, row D = the full-acceptance bonus distribution
    resid = jnp.clip(p_b[:-1] - q_b[1:], 0.0, None)  # [D-1, V]
    tots = resid.sum(axis=-1, keepdims=True)
    resid = jnp.where(tots > 0.0, resid / jnp.maximum(tots, 1e-30),
                      p_b[:-1])
    table = jnp.concatenate([r[None], resid, p_b[-1:]], axis=0)  # [D+1, V]
    extra_sampled = jax.random.categorical(
        x_key, jnp.log(jnp.maximum(table[a_sampled], 1e-30))
    ).astype(jnp.int32)

    # ---- greedy: pure argmax comparison per node (never consults q)
    tgt = jnp.argmax(p_cols, axis=-1).astype(jnp.int32)          # [T]
    pred = np.zeros((D, W), np.int64)  # parent column of node (j+1, b)
    for j in range(1, D):
        for b in range(W):
            pred[j, b] = offs[j - 1] + min(b, ws[j - 1] - 1)
    live = jnp.asarray(
        np.array([[b < ws[j] for b in range(W)] for j in range(D)]))
    ok_g = (d_toks == tgt[pred]) & live & spec_on
    a_per_b = jnp.sum(jnp.cumprod(ok_g.astype(jnp.int32), axis=0), axis=0)
    b_greedy = jnp.argmax(a_per_b).astype(jnp.int32)  # first max wins
    a_greedy = a_per_b[b_greedy]
    offs_arr = jnp.asarray(np.array(offs, np.int32))
    leaf = jnp.where(a_greedy == 0, 0,
                     offs_arr[jnp.maximum(a_greedy - 1, 0)] + b_greedy)
    extra_greedy = tgt[leaf]

    a = jnp.where(greedy, a_greedy, a_sampled)
    branch = jnp.where(greedy, b_greedy, bsafe)
    extra = jnp.where(greedy, extra_greedy, extra_sampled).astype(jnp.int32)
    return a, branch, extra, rng


# --------------------------------------------------------------- draft model
def build_draft(spec_draft: str, target_cfg, target_params,
                target_vocab: Optional[int] = None):
    """Resolve ``--spec_draft_config`` into ``(draft_cfg, draft_params)``.

    - ``take:N`` — self-speculative layer truncation (Draft & Verify): the
      draft is the target's FIRST N transformer blocks with the target's own
      embedding, final norm and unembedding (shared device buffers — zero
      extra HBM for those leaves). Same tokenizer/vocab by construction.
    - anything else — a model path / ``preset:`` spec loaded via the normal
      model loader; its vocab must match the target's (the acceptance rule
      compares distributions over one vocabulary).
    """
    if spec_draft.startswith("take:"):
        n = int(spec_draft.split(":", 1)[1])
        if not 1 <= n <= target_cfg.num_layers:
            raise ValueError(
                f"spec draft take:{n} out of range for a "
                f"{target_cfg.num_layers}-layer target")
        dcfg = dataclasses.replace(
            target_cfg, num_layers=n, name=f"{target_cfg.name}-take{n}",
            paged_kernel=False)
        layers = {
            name: {leaf: arr[:n] for leaf, arr in sub.items()}
            for name, sub in target_params["layers"].items()
        }
        dparams = dict(target_params)
        dparams["layers"] = layers
        return dcfg, dparams
    from datatunerx_tpu.utils.model_loader import load_model_and_tokenizer

    dcfg, dparams, _ = load_model_and_tokenizer(spec_draft,
                                                dtype=jnp.bfloat16)
    want = target_vocab or target_cfg.vocab_size
    if dcfg.vocab_size != want:
        raise ValueError(
            f"spec draft vocab {dcfg.vocab_size} != target vocab {want}; "
            "speculative verification needs one shared vocabulary")
    if getattr(dcfg, "paged_kernel", False):
        dcfg = dataclasses.replace(dcfg, paged_kernel=False)
    return dcfg, dparams


# ---------------------------------------------------------------- controller
class AdaptiveK:
    """Host-side acceptance-rate controller: per-slot EMAs gate individual
    rows out of drafting, the global EMA shrinks ``k`` and (``mode="auto"``)
    falls back to the plain pending-form decode program entirely — spec must
    never be slower than the non-spec path it replaces. Disabled state
    re-probes every ``probe_every`` plain steps so a workload shift can win
    spec back.

    Thread-safety: observed from the scheduler thread only; read (stats,
    /metrics) from HTTP threads — the lock keeps the tiny dicts consistent.
    """

    def __init__(self, k_max: int, mode: str = "auto", floor: float = 0.35,
                 alpha: float = 0.25, min_obs: int = 4,
                 probe_every: int = 64, tree: Optional[TreeSpec] = None):
        if k_max < 1:
            raise ValueError(f"spec_k must be >= 1, got {k_max}")
        self.k_max = int(k_max)
        self.tree = tree
        self.mode = mode
        self.floor = float(floor)
        self.alpha = float(alpha)
        self.min_obs = int(min_obs)
        self.probe_every = int(probe_every)
        self.global_ema: Optional[float] = None
        self._slot_ema: Dict[int, Tuple[float, int]] = {}
        self._slot_off: Dict[int, bool] = {}
        self._plain_streak = 0
        self.disabled_events = 0
        self._lock = threading.Lock()

    # ---- scheduler-side
    def observe(self, rows: List[Tuple[int, int, int]]):
        """``rows`` = [(slot, accepted, k)] for every row that drafted this
        step."""
        with self._lock:
            for slot, accepted, k in rows:
                rate = accepted / k if k else 0.0
                ema, n = self._slot_ema.get(slot, (rate, 0))
                ema = ema + self.alpha * (rate - ema)
                self._slot_ema[slot] = (ema, n + 1)
                if n + 1 >= self.min_obs and ema < self.floor:
                    if not self._slot_off.get(slot):
                        self.disabled_events += 1
                    self._slot_off[slot] = True
                g = self.global_ema if self.global_ema is not None else rate
                self.global_ema = g + self.alpha * (rate - g)
            if rows:
                self._plain_streak = 0

    def note_plain_step(self):
        with self._lock:
            self._plain_streak += 1

    def reset_slot(self, slot: int):
        """A finished request releases its slot; the next tenant starts with
        a clean acceptance history (spec re-enabled)."""
        with self._lock:
            self._slot_ema.pop(slot, None)
            self._slot_off.pop(slot, None)

    def force_off_slot(self, slot: int):
        """Hard per-slot disable (e.g. the draft could not be primed)."""
        with self._lock:
            self._slot_off[slot] = True
            self._slot_ema[slot] = (0.0, self.min_obs)

    # ---- decisions
    def slot_enabled(self, slot: int) -> bool:
        with self._lock:
            return not self._slot_off.get(slot, False)

    def current_k(self) -> int:
        """Shrink the proposal depth as global acceptance collapses: full k
        while acceptance holds, half on mediocre acceptance, 1 near the
        floor. Bounded set of distinct k values = bounded set of compiled
        verify programs."""
        with self._lock:
            return self.current_k_locked()

    def use_spec(self) -> bool:
        """Whether this tick runs the draft/verify program at all. ``on``
        pins it; ``auto`` backs off to the plain pending-form program when
        the global EMA sits under the floor (with periodic probes)."""
        if self.mode == "on":
            return True
        with self._lock:
            g = self.global_ema
            streak = self._plain_streak
        if g is None or g >= self.floor:
            return True
        return streak >= self.probe_every  # probe: one spec step, re-measure

    def current_plan(self) -> tuple:
        """The step shape this tick runs: ``("chain", k)`` or ``("tree",
        widths)`` where ``widths`` is the per-depth width tuple. The fixed
        tree controller degrades along WIDTH as global acceptance collapses
        (full W while it holds, half on mediocre, a width-1
        chain-of-depth-D near the floor) — same thresholds, same
        bounded-program-set property as ``current_k``. No tree configured
        = degenerate chain = byte-identical PR 14 behavior. ``AdaptiveTree``
        overrides the tree branch with LEARNED per-depth widths."""
        with self._lock:
            return self.current_plan_locked()

    def current_plan_locked(self) -> tuple:
        if self.tree is None:
            return ("chain", self.current_k_locked())
        g = self.global_ema
        if g is None or g >= 0.6:
            w = self.tree.width
        elif g >= 0.3:
            w = max(1, self.tree.width // 2)
        else:
            w = 1
        return ("tree", (w,) * self.tree.depth)

    # ---- observability
    def snapshot(self) -> dict:
        with self._lock:
            plan = self.current_plan_locked()
            return {
                "k": self.current_k_locked(),
                "plan": [list(p) if isinstance(p, tuple) else p
                         for p in plan],
                "global_ema": self.global_ema,
                "slots": {s: round(e, 4)
                          for s, (e, _) in self._slot_ema.items()},
                "slots_off": sorted(s for s, off in self._slot_off.items()
                                    if off),
                "disabled_events": self.disabled_events,
            }

    def current_k_locked(self) -> int:
        g = self.global_ema
        if g is None or g >= 0.6:
            return self.k_max
        if g >= 0.3:
            return max(1, self.k_max // 2)
        return 1

    # ---- migration (dtx-kv-session payload "spec" sub-document)
    def export_slot_state(self, slot: int) -> dict:
        """JSON-safe controller state riding the session payload: the
        slot's own acceptance EMA plus the learned global signals, so an
        importer does not restart the controller cold (ISSUE 20)."""
        with self._lock:
            ema = self._slot_ema.get(slot)
            plan = self.current_plan_locked()
            return {
                "slot_ema": list(ema) if ema is not None else None,
                "slot_off": bool(self._slot_off.get(slot, False)),
                "global_ema": self.global_ema,
                "plan": [list(p) if isinstance(p, tuple) else p
                         for p in plan],
            }

    def import_slot_state(self, slot: int, state) -> None:
        """Warm this controller from an imported session's exported state.
        The slot EMA/off flag are restored verbatim (they ARE that
        session's history); the global EMA is adopted only when this
        controller has none — one migrating tenant must not overwrite a
        live fleet member's own evidence."""
        if not isinstance(state, dict):
            return
        with self._lock:
            ema = state.get("slot_ema")
            if isinstance(ema, (list, tuple)) and len(ema) == 2:
                self._slot_ema[slot] = (float(ema[0]), int(ema[1]))
            if state.get("slot_off"):
                self._slot_off[slot] = True
            g = state.get("global_ema")
            if g is not None and self.global_ema is None:
                self.global_ema = float(g)


class AdaptiveTree(AdaptiveK):
    """Learned tree shapes (ISSUE 20): the fixed ``WxD`` rectangle becomes
    a per-depth width VECTOR recomputed from acceptance evidence at tick
    granularity.

    - per-depth survival EMAs (fraction of drafting rows whose accepted
      prefix reached depth j) pick each depth's width from the bounded
      bucket set ``{1, ceil(W/2), W}`` with the same 0.6/0.3 thresholds as
      ``current_k`` — a bounded width set means a bounded compiled-program
      set, so adaptation never fragments the tree-step memo (the SAN003
      compile-budget gate asserts this);
    - widths are forced monotone non-increasing (each depth capped by the
      one above), which keeps every branch chain prefix-live — the
      invariant the ragged masks and clamped gathers rely on;
    - a DECISIVE-margin EMA tracks how often the draft root's top-1 logit
      margin is decisive; when it is nearly always decisive the depth-1
      width is capped at 1 — the draft-side early exit: sibling roots are
      pure draft FLOPs when the top token wins anyway.
    """

    DECISIVE_MARGIN = 4.0   # root top-2 logit gap that settles the branch
    DECISIVE_EMA = 0.9      # "nearly always": cap depth-1 width at 1

    def __init__(self, k_max: int, mode: str = "auto",
                 tree: Optional[TreeSpec] = None, **kw):
        if tree is None:
            raise ValueError("AdaptiveTree requires a TreeSpec")
        super().__init__(k_max, mode=mode, tree=tree, **kw)
        self._depth_ema: List[Optional[float]] = [None] * tree.depth
        self._decisive_ema: Optional[float] = None

    # ---- scheduler-side
    def observe_tree(self, depth_fracs, decisive_frac) -> None:
        """Per-tick tree evidence: ``depth_fracs[j]`` = fraction of
        drafting rows whose accepted prefix reached depth ``j+1``;
        ``decisive_frac`` = fraction whose draft root margin cleared
        ``DECISIVE_MARGIN``."""
        with self._lock:
            for j, f in enumerate(depth_fracs[:len(self._depth_ema)]):
                e = self._depth_ema[j]
                self._depth_ema[j] = float(f) if e is None else \
                    e + self.alpha * (float(f) - e)
            d = self._decisive_ema
            self._decisive_ema = float(decisive_frac) if d is None else \
                d + self.alpha * (float(decisive_frac) - d)

    def _bucket(self, ema: Optional[float]) -> int:
        W = self.tree.width
        if ema is None or ema >= 0.6:
            return W
        if ema >= 0.3:
            return max(1, -(-W // 2))
        return 1

    def current_plan_locked(self) -> tuple:
        g = self.global_ema
        if g is not None and g < 0.3:
            # near-floor global acceptance: width-1 chain-of-depth-D, the
            # same last resort the fixed controller takes
            return ("tree", (1,) * self.tree.depth)
        ws, cap = [], self.tree.width
        for j in range(self.tree.depth):
            w = min(self._bucket(self._depth_ema[j]), cap)
            if j == 0 and self._decisive_ema is not None \
                    and self._decisive_ema >= self.DECISIVE_EMA:
                w = 1  # draft-side early exit
            ws.append(w)
            cap = w
        return ("tree", tuple(ws))

    # ---- observability / migration
    def snapshot(self) -> dict:
        doc = super().snapshot()
        with self._lock:
            doc["depth_ema"] = [None if e is None else round(e, 4)
                                for e in self._depth_ema]
            doc["decisive_ema"] = None if self._decisive_ema is None \
                else round(self._decisive_ema, 4)
        return doc

    def export_slot_state(self, slot: int) -> dict:
        state = super().export_slot_state(slot)
        with self._lock:
            state["depth_ema"] = list(self._depth_ema)
            state["decisive_ema"] = self._decisive_ema
        return state

    def import_slot_state(self, slot: int, state) -> None:
        super().import_slot_state(slot, state)
        if not isinstance(state, dict):
            return
        with self._lock:
            de = state.get("depth_ema")
            if isinstance(de, (list, tuple)):
                for j, e in enumerate(de[:len(self._depth_ema)]):
                    if e is not None and self._depth_ema[j] is None:
                        self._depth_ema[j] = float(e)
            d = state.get("decisive_ema")
            if d is not None and self._decisive_ema is None:
                self._decisive_ema = float(d)


# ------------------------------------------------------------ device programs
# Bounded process-wide memo, the engine _Programs pattern: twin engines
# (bench spec-on/off, parity tests) built from equal (target cfg, draft cfg,
# max_seq_len, kv_quant) share one set of jitted spec programs — draft and
# target params, caches and per-slot state all arrive as ARGUMENTS.
_SPEC_MEMO: "collections.OrderedDict" = collections.OrderedDict()
_SPEC_MEMO_MAX = 8


def spec_programs(tcfg, dcfg, max_seq_len: int, kv_quant,
                  epilogue: str = "off") -> "SpecPrograms":
    try:
        key = (repr(tcfg), repr(dcfg), int(max_seq_len), kv_quant, epilogue)
    except Exception:  # noqa: BLE001 — memoization is best-effort
        key = None
    progs = None if key is None else _SPEC_MEMO.get(key)
    if progs is None:
        progs = SpecPrograms(tcfg, dcfg, max_seq_len, kv_quant,
                             epilogue=epilogue)
        if key is not None:
            _SPEC_MEMO[key] = progs
            while len(_SPEC_MEMO) > _SPEC_MEMO_MAX:
                _SPEC_MEMO.popitem(last=False)
    else:
        _SPEC_MEMO.move_to_end(key)
    return progs


class SpecPrograms:
    """Jitted programs of the speculative state machine. All slots live in
    PENDING-TOKEN form while spec is enabled: the last emitted token's KV is
    not yet written, so a verify forward of ``[pending, d_0..d_{k-1}]``
    yields target distributions for positions ``pos+1..pos+k+1`` in one shot
    and the corrected/bonus token becomes the next pending — no second
    target forward per step.

    Ragged per-row advance: the verify forward writes ``k+1`` tokens for
    every row and rolls each row's cursor back to ``old + 1 + accepted``.
    Rejected-lane KV/positions are stale but sit at cursors strictly beyond
    every live write head, where monotonic rope positions + the causal check
    mask them until the next contiguous write overwrites them — the same
    argument that already covers recycled blocks. Paged rows reserve
    ``spec_k + 1`` tokens of block overshoot at admission
    (``ops.paged_attention.blocks_for_depth``) so verify writes stay
    physical; dense rows rely on the scatter's drop-OOB mode exactly like
    the existing decode program."""

    def __init__(self, tcfg, dcfg, max_seq_len: int, kv_quant,
                 epilogue: str = "off"):
        self.tcfg = tcfg
        self.dcfg = dcfg
        self.max_seq_len = max_seq_len
        self.kv_quant = kv_quant
        # "off" = the legacy argsort sampler everywhere (byte-identical
        # pre-epilogue programs); "kernel" / "xla" = the fused sampling
        # epilogue with that implementation (ops/pallas_sampling.py)
        self.epilogue = epilogue
        self.enter = jax.jit(self._enter_impl, static_argnames=("mode",))
        self.prime = jax.jit(self._prime_impl)
        self.step = jax.jit(self._step_impl, static_argnames=("k", "mode"))
        self.tree_step = jax.jit(
            self._tree_step_impl,
            static_argnames=("widths", "mode"))
        self.decode = jax.jit(self._decode_pending_impl,
                              static_argnames=("K", "mode"))
        self.settle = jax.jit(self._settle_impl)

    # ---- one batched token draw, epilogue-aware
    def _draw(self, logits, temps, top_ps, rng, mode: str):
        """The legacy split + ``_sample_jit`` pair when the epilogue is off
        (``mode == "off"``) — byte-identical pre-epilogue programs — else
        the fused epilogue with the same key-split order, so the per-slot
        PRNG stream evolves identically either way."""
        if mode == "off" or self.epilogue == "off":
            split = jax.vmap(jax.random.split)(rng)
            rng2, sub = split[:, 0], split[:, 1]
            return jax.vmap(_sample_jit)(logits, temps, top_ps, sub), rng2
        return sample_rows(logits, temps, top_ps, rng, mode=mode,
                           impl=self.epilogue)

    def _draw_keys(self, logits, temps, top_ps, keys, mode: str):
        """One draw from PRE-SPLIT per-row keys (the tree step's W iid
        sibling draws)."""
        if mode == "off" or self.epilogue == "off":
            return jax.vmap(_sample_jit)(logits, temps, top_ps, keys)
        return fused_sample(logits, temps, top_ps, keys, mode=mode,
                            impl=self.epilogue)

    # ---- logits-form → pending-form transition (first emitted token)
    def _enter_impl(self, logits, pending, remaining, active, rng,
                    temps, top_ps, stops, fresh, *, mode: str = "off"):
        """Sample one token from each fresh row's held logits (the same
        split-then-sample the plain decode step would do), emit it, and make
        it the row's pending token. Cache and cursor untouched — the token's
        KV is written by the row's first verify/pending forward. ``mode``
        is the engine's static batch sampling mode when the fused epilogue
        is on, or the ``"off"`` sentinel (one compiled variant, the legacy
        sampler) when it is not."""
        nxt, rng2 = self._draw(logits, temps, top_ps, rng, mode)
        is_stop = jnp.any(nxt[:, None] == stops, axis=1)
        emit = fresh & active & ~is_stop & (remaining > 0)
        emitted = jnp.where(emit, nxt, -1)
        new_active = jnp.where(fresh, emit & (remaining > 1), active)
        remaining = remaining - emit.astype(jnp.int32)
        pending = jnp.where(emit, nxt, pending)
        rng = jnp.where(fresh[:, None], rng2, rng)
        return emitted, pending, remaining, new_active, rng

    # ---- draft prefill of one slot's context row
    def _prime_impl(self, dparams, dcache, slot, tokens, mask, positions,
                    prime_len):
        """Prefill ``tokens`` (left-pad-bucketed prompt + settled emitted
        tokens) through the DRAFT into a fresh full-width row, then install
        it as ``slot``'s row of the per-slot draft cache. Priming feeds only
        acceptance quality — verification guarantees exactness regardless —
        so an approximate re-primed context after import is correct by
        construction."""
        W = dcache["k"].shape[2]
        row = init_cache(self.dcfg, 1, W, dtype=jnp.bfloat16)
        _, row = forward(
            dparams, tokens, self.dcfg, positions=positions,
            attention_mask=mask, cache=row, compute_dtype=jnp.bfloat16,
        )
        out = dict(dcache)
        out["k"] = jax.lax.dynamic_update_slice(
            dcache["k"], row["k"], (0, slot, 0, 0, 0))
        out["v"] = jax.lax.dynamic_update_slice(
            dcache["v"], row["v"], (0, slot, 0, 0, 0))
        out["pos"] = jax.lax.dynamic_update_slice(
            dcache["pos"], row["pos"], (slot, 0))
        out["len"] = dcache["len"].at[slot].set(prime_len)
        return out

    # ---- the speculative super-step: propose k, verify once, accept
    def _step_impl(self, tparams, dparams, lora, tcache, dcache,
                   pending, pos, remaining, active, rng, temps, top_ps,
                   stops, adapter_idx, spec_on, *, k: int,
                   mode: str = "topp"):
        """``mode`` is a STATIC batch property the engine derives from its
        live requests each tick (bounded set of compiled variants):

        - ``"greedy"`` — every drafting row has ``temperature <= 0``:
          acceptance is pure argmax comparison, so no distribution (and no
          full-vocab sort) is ever materialized;
        - ``"simple"`` — sampled rows exist but none filters
          (``top_p >= 1``): distributions are plain softmax;
        - ``"topp"`` — the fully general sorted top-p path.
        Each is exact for the batches it is selected for; greedy rows
        inside a sampled batch still resolve exactly via the traced
        ``temperature <= 0`` selects."""
        S = pending.shape[0]
        participate = active
        drow = participate & spec_on

        # draft propose: k+1 single-token forwards in one scan. Iteration i
        # feeds the previous token (pending at i=0) at rope position pos+i
        # and samples proposal d_i from the draft's distribution q_i. The
        # (k+1)-th iteration's sample is discarded — it runs only to write
        # d_{k-1}'s KV so a fully-accepted row's draft cache stays complete.
        d_len0 = dcache["len"]

        def dstep(carry, i):
            cur, dc, r = carry
            dlogits, dc = forward(
                dparams, cur[:, None], self.dcfg,
                positions=(pos + i)[:, None],
                attention_mask=drow[:, None].astype(jnp.int32),
                cache=dc, compute_dtype=jnp.bfloat16,
            )
            last = dlogits[:, -1]
            if mode == "greedy":
                nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
                q = jnp.zeros((S, 1), jnp.float32)  # placeholder, unused
            else:
                nxt, r = self._draw(last, temps, top_ps, r, mode)
                q = jax.vmap(
                    lambda lg, t, tp: sampling_probs(
                        lg, t, tp, exact_topp=(mode == "topp"))
                )(last, temps, top_ps)
            return (nxt, dc, r), (nxt, q)

        (_, dcache, rng), (d_all, q_all) = jax.lax.scan(
            dstep, (pending, dcache, rng),
            jnp.arange(k + 1, dtype=jnp.int32))
        d_toks = jnp.transpose(d_all[:k])              # [S, k]

        # verify: ONE target forward over [pending, d_0..d_{k-1}] — the
        # chunked-prefill/extend machinery's multi-token path, so the paged
        # cache, pooled LoRA adapters and int8 kv_quant all keep working.
        # Rows not drafting mask out the proposal columns and take a plain
        # single-token step on column 0.
        t_len0 = tcache["len"]
        vtoks = jnp.concatenate([pending[:, None], d_toks], axis=1)
        vpos = pos[:, None] + jnp.arange(k + 1, dtype=jnp.int32)[None, :]
        vmask = jnp.concatenate(
            [participate[:, None],
             jnp.broadcast_to(drow[:, None], (S, k))], axis=1)
        vlogits, tcache = forward(
            tparams, vtoks, self.tcfg, positions=vpos,
            attention_mask=vmask.astype(jnp.int32), cache=tcache, lora=lora,
            lora_adapter_idx=(adapter_idx if lora is not None else None),
            compute_dtype=jnp.bfloat16,
        )
        if mode == "greedy":
            # acceptance without distributions: a proposal survives iff it
            # IS the target argmax at its position, and the corrected/bonus
            # token is the argmax at the first divergence — token-identical
            # to sequential greedy decode by construction
            tgt_argmax = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)
            ok = (d_toks == tgt_argmax[:, :k]) & drow[:, None]
            acc_prefix = jnp.cumprod(ok.astype(jnp.int32), axis=1)
            a = jnp.sum(acc_prefix, axis=1).astype(jnp.int32)
            extra = jnp.take_along_axis(
                tgt_argmax, a[:, None], axis=1)[:, 0]
        else:
            q_dists = jnp.transpose(q_all[:k], (1, 0, 2))  # [S, k, V]
            p_dists = jax.vmap(
                lambda row_logits, t, tp: jax.vmap(
                    lambda lg: sampling_probs(
                        lg, t, tp, exact_topp=(mode == "topp")))(row_logits)
            )(vlogits, temps, top_ps)  # [S, k+1, V]
            a, extra, rng = jax.vmap(accept_tokens)(
                p_dists, q_dists, d_toks, temps, rng, drow)
        a = jnp.where(participate, a, 0)

        # emission: accepted prefix + corrected/bonus token, truncated by
        # the row's stop set and token budget exactly as the sequential
        # decode loop would have (a stop token is never emitted; the budget
        # bounds emitted count; either truncation deactivates the row)
        idx = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
        d_ext = jnp.concatenate(
            [d_toks, jnp.full((S, 1), -1, jnp.int32)], axis=1)
        cand = jnp.where(idx < a[:, None], d_ext,
                         jnp.where(idx == a[:, None], extra[:, None], -1))
        is_stop = jnp.any(cand[:, :, None] == stops[:, None, :], axis=2) \
            & (cand >= 0)
        navail = a + 1
        stop_idx = jnp.min(jnp.where(is_stop, idx, k + 2), axis=1)
        n_emit = jnp.minimum(jnp.minimum(navail, stop_idx), remaining)
        n_emit = jnp.where(participate, n_emit, 0)
        emitted = jnp.where(idx < n_emit[:, None], cand, -1)
        new_remaining = remaining - n_emit
        new_active = participate & (n_emit == navail) & (new_remaining > 0)
        pending = jnp.where(new_active, extra, pending)

        # ragged advance: each row's cursor moves by 1 + accepted (the old
        # pending plus the kept proposals); rejected-lane writes beyond the
        # new cursor are dead — masked by causal position until overwritten
        adv = jnp.where(participate, 1 + a, 0)
        pos = pos + adv
        tcache = dict(tcache)
        tcache["len"] = t_len0 + adv
        dcache = dict(dcache)
        dcache["len"] = d_len0 + jnp.where(drow, adv, 0)
        return (emitted, a, tcache, dcache, pending, pos, new_remaining,
                new_active, rng)

    # ---- the tree super-step: draft a widths-shaped tree, verify once
    def _tree_step_impl(self, tparams, dparams, lora, tcache, dcache,
                       pending, pos, remaining, active, rng, temps, top_ps,
                       stops, adapter_idx, spec_on, *, widths: tuple,
                       mode: str = "topp"):
        """The ``_step_impl`` shape with a TREE of drafts per slot:
        ``widths[j-1]`` parallel branches at depth j sharing the pending
        root, flattened into ``1 + sum(widths)`` verify columns under the
        branch ancestry mask, ONE target forward, longest-surviving-path
        acceptance (``accept_tree_tokens``). ``widths`` is the monotone
        non-increasing per-depth width tuple — the fixed ``WxD`` rectangle
        is ``(W,) * D``, and ``AdaptiveTree`` shrinks individual depths
        from acceptance evidence. Each depth's draft forward runs only its
        OWN ``widths[j-1]`` live lanes (the learned-shape FLOP saving);
        dead rectangle lanes exist only in the acceptance inputs, as -1
        tokens with zero draft mass, and lose every test by construction.

        Also returns the draft root's top-2 logit margin per row — the
        decisiveness signal ``AdaptiveTree`` turns into the draft-side
        early exit.

        Tree windows BREAK the chain's stale-lane safety argument (a
        rejected sibling shares its rope position with an accepted one, so
        causal masking alone would admit it on a later read); after
        acceptance the chosen path is compacted into the contiguous cursor
        lanes and every other window lane's position is scrubbed to the
        sentinel (``compact_window``), restoring the chain invariant the
        settle / export / migration paths assume."""
        ws = _widths_tuple(widths)
        W, D = ws[0], len(ws)
        offs = _width_offsets(ws)
        T = 1 + sum(ws)
        S = pending.shape[0]
        participate = active
        drow = participate & spec_on
        d_len0 = dcache["len"]
        t_len0 = tcache["len"]
        exact = mode == "topp"

        # ---- draft: the pending root, then D ragged tree forwards (the
        # last one exists only to write the leaves' KV — samples discarded)
        dlogits, dcache = forward(
            dparams, pending[:, None], self.dcfg, positions=pos[:, None],
            attention_mask=drow[:, None].astype(jnp.int32),
            cache=dcache, compute_dtype=jnp.bfloat16,
        )
        l0 = dlogits[:, -1]
        top2, _ = jax.lax.top_k(l0, 2)
        margin = top2[:, 0] - top2[:, 1]  # root decisiveness, host EMA'd
        if mode == "greedy":
            # distinct top-W roots: at most one can match the target
            # argmax, and the verify walks every branch anyway
            _, topw = jax.lax.top_k(l0, W)
            cur = topw.astype(jnp.int32)                        # [S, W]
            q0 = jnp.zeros((S, 1), jnp.float32)  # placeholder, unused
        else:
            split = jax.vmap(lambda r: jax.random.split(r, W + 1))(rng)
            rng = split[:, 0]
            cur = jnp.stack(
                [self._draw_keys(l0, temps, top_ps, split[:, 1 + b],
                                 "off" if self.epilogue == "off" else mode)
                 for b in range(W)], axis=1)                    # iid from q0
            q0 = jax.vmap(
                lambda lg, t, tp: sampling_probs(lg, t, tp,
                                                 exact_topp=exact)
            )(l0, temps, top_ps)
        d_depth, q_depth = [cur], [q0]
        for j in range(1, D + 1):
            wj = ws[j - 1]
            wmask = jnp.asarray(tree_draft_mask(ws, j))
            dlogits, dcache = forward(
                dparams, cur[:, :wj], self.dcfg,
                positions=jnp.broadcast_to((pos + j)[:, None], (S, wj)),
                attention_mask=jnp.broadcast_to(
                    drow[:, None], (S, wj)).astype(jnp.int32),
                cache=dcache, compute_dtype=jnp.bfloat16,
                window_mask=jnp.broadcast_to(
                    wmask[None], (S, wj, 1 + sum(ws[:j]))),
                window_start=d_len0,
            )
            if j == D:
                break
            wn = ws[j]  # next depth's width (<= wj: prefix-live chains)
            if mode == "greedy":
                nxt = jnp.argmax(dlogits[:, :wn], axis=-1).astype(jnp.int32)
                qj = jnp.zeros((S, W, 1), jnp.float32)
            else:
                split = jax.vmap(lambda r: jax.random.split(r, wn + 1))(rng)
                rng = split[:, 0]
                nxt = jnp.stack(
                    [self._draw_keys(
                        dlogits[:, b], temps, top_ps, split[:, 1 + b],
                        "off" if self.epilogue == "off" else mode)
                     for b in range(wn)], axis=1)
                qn = jax.vmap(
                    lambda row, t, tp: jax.vmap(
                        lambda lg: sampling_probs(lg, t, tp,
                                                  exact_topp=exact))(row)
                )(dlogits[:, :wn], temps, top_ps)              # [S, wn, V]
                # dead rectangle lanes carry ZERO draft mass — the
                # acceptance rule's q_at > 0 guard retires them for free
                qj = jnp.pad(qn, ((0, 0), (0, W - wn), (0, 0)))
            # dead-lane tokens are -1: never equal to any target argmax
            cur = jnp.pad(nxt, ((0, 0), (0, W - wn)), constant_values=-1)
            d_depth.append(cur)
            q_depth.append(qj)
        d_toks = jnp.stack(d_depth, axis=1)                     # [S, D, W]

        # ---- verify: ONE target forward over the ragged flattened tree
        vtoks = jnp.concatenate(
            [pending[:, None]]
            + [d_toks[:, j, :ws[j]] for j in range(D)], axis=1)  # [S, T]
        depth_of = np.concatenate(
            [[0]] + [[j] * ws[j - 1]
                     for j in range(1, D + 1)]).astype(np.int32)
        vpos = pos[:, None] + jnp.asarray(depth_of)[None, :]
        vmask = jnp.concatenate(
            [participate[:, None],
             jnp.broadcast_to(drow[:, None], (S, T - 1))], axis=1)
        wmask_v = jnp.asarray(tree_verify_mask(ws))
        vlogits, tcache = forward(
            tparams, vtoks, self.tcfg, positions=vpos,
            attention_mask=vmask.astype(jnp.int32), cache=tcache, lora=lora,
            lora_adapter_idx=(adapter_idx if lora is not None else None),
            compute_dtype=jnp.bfloat16,
            window_mask=jnp.broadcast_to(wmask_v[None], (S, T, T)),
            window_start=t_len0,
        )
        if mode == "greedy":
            tgt = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)  # [S, T]
            pred = np.zeros((D, W), np.int64)  # parent column per node
            for j in range(1, D):
                for b in range(W):
                    pred[j, b] = offs[j - 1] + min(b, ws[j - 1] - 1)
            live = jnp.asarray(
                np.array([[b < ws[j] for b in range(W)]
                          for j in range(D)]))
            ok = (d_toks == tgt[:, pred]) & live[None] & drow[:, None, None]
            a_per_b = jnp.sum(
                jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)  # [S, W]
            b_sel = jnp.argmax(a_per_b, axis=1).astype(jnp.int32)
            a = jnp.take_along_axis(a_per_b, b_sel[:, None], axis=1)[:, 0]
            offs_arr = jnp.asarray(np.array(offs, np.int32))
            leaf = jnp.where(
                a == 0, 0, offs_arr[jnp.maximum(a - 1, 0)] + b_sel)
            extra = jnp.take_along_axis(tgt, leaf[:, None], axis=1)[:, 0]
        else:
            p_cols = jax.vmap(
                lambda row_logits, t, tp: jax.vmap(
                    lambda lg: sampling_probs(lg, t, tp,
                                              exact_topp=exact))(row_logits)
            )(vlogits, temps, top_ps)                          # [S, T, V]
            V = p_cols.shape[-1]
            q_tree = jnp.stack(
                [jnp.broadcast_to(q_depth[0][:, None], (S, W, V))]
                + q_depth[1:], axis=1)                         # [S, D, W, V]
            a, b_sel, extra, rng = jax.vmap(
                lambda p, q, d, t, r, s: accept_tree_tokens(
                    p, q, d, t, r, s, widths=ws)
            )(p_cols, q_tree, d_toks, temps, rng, drow)
        a = jnp.where(participate, a, 0)
        b_sel = jnp.where(drow, b_sel, 0)

        # ---- emission: the chosen branch's accepted prefix + extra token
        path = jnp.take_along_axis(
            d_toks, b_sel[:, None, None], axis=2)[:, :, 0]      # [S, D]
        idx = jnp.arange(D + 1, dtype=jnp.int32)[None, :]
        p_ext = jnp.concatenate(
            [path, jnp.full((S, 1), -1, jnp.int32)], axis=1)
        cand = jnp.where(idx < a[:, None], p_ext,
                         jnp.where(idx == a[:, None], extra[:, None], -1))
        is_stop = jnp.any(cand[:, :, None] == stops[:, None, :], axis=2) \
            & (cand >= 0)
        navail = a + 1
        stop_idx = jnp.min(jnp.where(is_stop, idx, D + 2), axis=1)
        n_emit = jnp.minimum(jnp.minimum(navail, stop_idx), remaining)
        n_emit = jnp.where(participate, n_emit, 0)
        emitted = jnp.where(idx < n_emit[:, None], cand, -1)
        new_remaining = remaining - n_emit
        new_active = participate & (n_emit == navail) & (new_remaining > 0)
        pending = jnp.where(new_active, extra, pending)

        # ---- compact the window: accepted path → contiguous cursor lanes,
        # everything else scrubbed to the sentinel (both caches share the
        # window column layout). The per-depth clamp keeps the gather
        # in-bounds for ragged widths — clamped entries sit at depths
        # beyond the accepted length, where compact_window never reads.
        col_tab = jnp.asarray(
            np.array([[offs[j] + min(b, ws[j] - 1) for j in range(D)]
                      for b in range(W)], np.int32))            # [W, D]
        src_cols = col_tab[b_sel]                               # [S, D]
        tcache = compact_window(tcache, participate, t_len0, src_cols, a,
                                pos, T)
        dcache = compact_window(dcache, drow, d_len0, src_cols, a, pos, T)
        adv = jnp.where(participate, 1 + a, 0)
        pos = pos + adv
        tcache = dict(tcache)
        tcache["len"] = t_len0 + adv
        dcache = dict(dcache)
        dcache["len"] = d_len0 + jnp.where(drow, adv, 0)
        return (emitted, a, tcache, dcache, pending, pos, new_remaining,
                new_active, rng, margin)

    # ---- plain decode in pending form (the never-slower fallback)
    def _decode_pending_impl(self, tparams, lora, tcache, pending, pos,
                             remaining, active, rng, temps, top_ps, stops,
                             adapter_idx, *, K: int, mode: str = "off"):
        """K-token chunked decode over pending-form slots: forward the
        pending token, sample its successor from the resulting logits, make
        that the new pending. Per-token cost identical to the non-spec
        ``_decode_impl`` (one forward + one sample), so the adaptive
        controller's fallback never costs more than spec-off decode.
        ``mode`` as in ``_enter_impl``."""
        def step(carry, _):
            pending, tcache, pos, remaining, active, rng = carry
            prev_len = tcache["len"]
            logits, tcache = forward(
                tparams, pending[:, None], self.tcfg,
                positions=pos[:, None],
                attention_mask=active[:, None].astype(jnp.int32),
                cache=tcache, lora=lora,
                lora_adapter_idx=(adapter_idx if lora is not None else None),
                compute_dtype=jnp.bfloat16,
            )
            tcache = dict(tcache)
            tcache["len"] = prev_len + active.astype(jnp.int32)
            pos = pos + active.astype(jnp.int32)
            nxt, rng = self._draw(logits[:, -1], temps, top_ps, rng, mode)
            is_stop = jnp.any(nxt[:, None] == stops, axis=1)
            emit = active & ~is_stop & (remaining > 0)
            emitted = jnp.where(emit, nxt, -1)
            new_active = emit & (remaining > 1)
            remaining = remaining - emit.astype(jnp.int32)
            pending = jnp.where(emit, nxt, pending)
            return (pending, tcache, pos, remaining, new_active, rng), emitted

        (pending, tcache, pos, remaining, active, rng), emitted = \
            jax.lax.scan(step, (pending, tcache, pos, remaining, active, rng),
                         None, length=K)
        return emitted, tcache, pending, pos, remaining, active, rng

    # ---- pending-form → logits-form (export/migration)
    def _settle_impl(self, tparams, lora, tcache, pending, pos, adapter_idx,
                     onehot):
        """Write ONE slot's pending token through the target (mask one-hot;
        every other row's cursor restored) and return the resulting
        next-token logits — the slot is then in the standard logits-form
        state the KV-migration wire format expects."""
        prev_len = tcache["len"]
        logits, tcache = forward(
            tparams, pending[:, None], self.tcfg, positions=pos[:, None],
            attention_mask=onehot[:, None].astype(jnp.int32), cache=tcache,
            lora=lora,
            lora_adapter_idx=(adapter_idx if lora is not None else None),
            compute_dtype=jnp.bfloat16,
        )
        tcache = dict(tcache)
        tcache["len"] = prev_len + onehot.astype(jnp.int32)
        pos = pos + onehot.astype(jnp.int32)
        return logits[:, -1], tcache, pos
