"""Inference engine: jitted prefill + KV-cache decode for chat serving.

Replaces the reference's Ray Serve ``LlamaDeployment`` (deployed from a zip,
reference internal/controller/finetune/finetunejob_controller.go:378-384; env
contract BASE_MODEL_DIR + CHECKPOINT_DIR, pkg/util/generate/generate.go:288-294).
TPU-native: the base model + (optionally) a LoRA adapter checkpoint are loaded
directly (no image bake) and merged for serving; generation runs as a jitted
per-token decode step over a static-shape KV cache (JetStream-style decode loop,
SURVEY.md §7.1).
"""

from __future__ import annotations

import collections
import os
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from datatunerx_tpu.data.templates import Template, get_template
from datatunerx_tpu.models.llama import forward, init_cache
from datatunerx_tpu.utils.model_loader import load_model_and_tokenizer

# Bounded LRU of shared _EnginePrograms — see the memo note in
# InferenceEngine.__init__. Entries pin only the model config (params arrive
# as arguments), so a dead donor engine's weights are never kept resident;
# the dict evicts least-recently-used configs.
_ENGINE_MEMO: collections.OrderedDict = collections.OrderedDict()
_ENGINE_MEMO_MAX = 8


def _engine_memo_key(cfg):
    """Hashable program identity, or None when it can't be established
    (memoization is best-effort; the dataclass repr covers every field)."""
    try:
        return repr(cfg)
    except Exception:  # noqa: BLE001
        return None


class _EnginePrograms:
    """The engine's jitted (prefill, decode_loop) pair, factored OFF the
    engine (the BatchedEngine ``_Programs`` pattern) so the process-wide memo
    pins only what tracing actually reads — the model config. Params, cache,
    and sampling state all arrive as arguments, which is what makes the
    programs shareable across engines in the first place."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.prefill = jax.jit(self._prefill_impl,
                               static_argnames=("prompt_len",))
        # whole decode loop in ONE device program (lax.while_loop): per-token
        # Python dispatch costs ~RTT each — fatal over a tunneled accelerator
        self.decode_loop = jax.jit(self._decode_loop_impl,
                                   static_argnames=("max_new_tokens",))

    def _prefill_impl(self, params, tokens, mask, positions, cache, prompt_len):
        logits, cache = forward(
            params, tokens, self.cfg, positions=positions,
            attention_mask=mask, cache=cache, compute_dtype=jnp.bfloat16,
        )
        return logits[:, prompt_len - 1], cache

    def _decode_loop_impl(self, params, first_logits, cache, start_pos,
                          stop_arr, rng, temperature, top_p, limit, *,
                          max_new_tokens: int):
        """Greedy/sampled decode as one lax.while_loop program. Returns
        (tokens [max_new_tokens buffer], n_generated); `limit` is the dynamic
        request cap within the static buffer."""
        out0 = jnp.zeros((max_new_tokens,), jnp.int32)

        def sample(logits, rng):
            return _sample_jit(logits, temperature, top_p, rng)

        def cond(carry):
            i, logits, cache, rng, out, stopped = carry
            return (~stopped) & (i < limit)

        def body(carry):
            i, logits, cache, rng, out, stopped = carry
            rng, sub = jax.random.split(rng)
            nxt = sample(logits[0], sub)
            stopped = jnp.any(nxt == stop_arr)
            out = jnp.where(stopped, out, out.at[i].set(nxt))
            logits2, cache = forward(
                params, nxt[None, None], self.cfg,
                positions=(start_pos + i)[None, None],
                cache=cache, compute_dtype=jnp.bfloat16,
            )
            return (i + jnp.where(stopped, 0, 1), logits2[:, -1], cache, rng,
                    out, stopped)

        i, _, _, _, out, _ = jax.lax.while_loop(
            cond, body,
            (jnp.zeros((), jnp.int32), first_logits, cache, rng, out0,
             jnp.zeros((), bool)),
        )
        return out, i


class InferenceEngine:
    def __init__(
        self,
        model_path: str,
        checkpoint_path: Optional[str] = None,
        template: str = "llama2",
        max_seq_len: int = 1024,
        dtype=jnp.bfloat16,
        quantization: Optional[str] = None,
    ):
        self.cfg, self.params, self.tokenizer = load_model_and_tokenizer(
            model_path, dtype=dtype
        )
        if checkpoint_path:
            self._apply_checkpoint(checkpoint_path)
        if quantization:
            # serve-time weight quantization (int8 ≈ half, nf4 ≈ quarter of
            # bf16 HBM). Quantize on the HOST, then upload only the quantized
            # tree — quantizing on-device would need full-precision + quantized
            # resident simultaneously, OOMing exactly the big-model case this
            # feature exists for.
            import dataclasses

            from datatunerx_tpu.ops.quant import quantize_model_params

            host_params = jax.device_get(self.params)
            cpu = jax.devices("cpu")[0] if jax.default_backend() != "cpu" else None
            if cpu is not None:
                with jax.default_device(cpu):
                    qparams = quantize_model_params(host_params, quantization)
                self.params = jax.device_put(jax.device_get(qparams))
            else:
                self.params = quantize_model_params(host_params, quantization)
            self.cfg = dataclasses.replace(self.cfg, quantization=quantization)
        self.template: Template = get_template(template, self.tokenizer)
        self.max_seq_len = min(max_seq_len, self.cfg.max_seq_len)
        # Process-wide program memo (the BatchedEngine / Trainer step-memo
        # pattern): the traced programs depend on the engine only through cfg
        # — params, cache, and sampling state all arrive as arguments — so
        # engines with an equal config share one set of jitted callables and
        # jax's in-memory executable cache (N single-slot engines in one
        # process compile once, not N times).
        key = _engine_memo_key(self.cfg)
        progs = None if key is None else _ENGINE_MEMO.get(key)
        if progs is None:
            progs = _EnginePrograms(self.cfg)
            if key is not None:
                _ENGINE_MEMO[key] = progs
                while len(_ENGINE_MEMO) > _ENGINE_MEMO_MAX:
                    _ENGINE_MEMO.popitem(last=False)
        else:
            _ENGINE_MEMO.move_to_end(key)
        self._prefill = progs.prefill
        self._decode_loop = progs.decode_loop

    # ---------------------------------------------------------- checkpoint
    def _apply_checkpoint(self, checkpoint_path: str):
        """Merge a trained adapter (or swap full params) from an Orbax
        TrainState checkpoint or an exported model.npz directory."""
        if os.path.isdir(checkpoint_path) and os.path.exists(
            os.path.join(checkpoint_path, "model.npz")
        ):
            from datatunerx_tpu.utils.hf_convert import convert_hf_state_dict

            sd = dict(np.load(os.path.join(checkpoint_path, "model.npz")))
            self.params = convert_hf_state_dict(sd, self.cfg, dtype=np.float32)
            return
        # Orbax checkpoint dir (…/checkpoints or …/checkpoints/<step>)
        import orbax.checkpoint as ocp

        root = checkpoint_path.rstrip("/")
        step: Optional[int] = None
        if os.path.basename(root).isdigit():
            step = int(os.path.basename(root))
            root = os.path.dirname(root)
        mngr = ocp.CheckpointManager(root)
        step = step if step is not None else mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {checkpoint_path}")
        from datatunerx_tpu.training.checkpoint import restore_raw_state

        restored = restore_raw_state(mngr, step)
        mngr.close()
        state = restored if isinstance(restored, dict) else dict(restored)
        lora = state.get("lora")
        if lora:
            from datatunerx_tpu.models.lora import lora_scaling, merge_lora

            rank = next(iter(lora["layers"].values()))["a"].shape[-1]
            scaling = self._manifest_lora_scaling(root)
            if scaling is None:
                # manifest absent (ad-hoc checkpoint dir): fall back to the
                # reference defaults alpha=32 / r (cmd/tuning/parser.py:138-145)
                scaling = lora_scaling(32.0, rank)
            self.params = merge_lora(self.params, lora, scaling)
        elif state.get("params"):
            self.params = state["params"]

    @staticmethod
    def _manifest_lora_scaling(ckpt_root: str):
        """The completion manifest (written next to the checkpoints dir by
        tuning/train.py) records the trained adapter's alpha/rank scaling;
        merging with any other value serves a silently-wrong model."""
        from datatunerx_tpu.training.checkpoint import read_manifest

        run_dir = os.path.dirname(ckpt_root.rstrip("/"))
        try:
            manifest = read_manifest(os.path.dirname(run_dir),
                                     os.path.basename(run_dir))
            val = (manifest or {}).get("lora_scaling")
            return float(val) if val is not None else None
        except (OSError, ValueError, TypeError):
            return None

    # ------------------------------------------------------------ generate
    def generate(
        self,
        prompt_ids: List[int],
        max_new_tokens: int = 128,
        temperature: float = 0.0,
        top_p: float = 1.0,
        seed: int = 0,
        stop_ids: Optional[set] = None,
    ) -> List[int]:
        import numbers

        from datatunerx_tpu.utils.decoding import prepare_prompt

        stop_ids = {int(s) for s in (stop_ids or set())
                    if isinstance(s, numbers.Integral)}
        stop_ids.add(self.tokenizer.eos_token_id)
        ids, mask, positions, plen, n_prompt, max_new, buf = prepare_prompt(
            prompt_ids, self.tokenizer.eos_token_id, self.max_seq_len,
            max_new_tokens,
        )

        cache = init_cache(self.cfg, 1, plen + buf, dtype=jnp.bfloat16)
        logits, cache = self._prefill(
            self.params, jnp.asarray([ids], jnp.int32),
            jnp.asarray([mask], jnp.int32), jnp.asarray([positions], jnp.int32),
            cache, prompt_len=plen,
        )
        stop_arr = jnp.asarray(sorted(stop_ids), jnp.int32)
        out, n = self._decode_loop(
            self.params, logits, cache,
            jnp.asarray(n_prompt, jnp.int32), stop_arr,
            jax.random.PRNGKey(seed),
            jnp.asarray(temperature, jnp.float32),
            jnp.asarray(top_p, jnp.float32),
            jnp.asarray(max_new, jnp.int32),
            max_new_tokens=buf,
        )
        n = int(n)
        return np.asarray(out).tolist()[:n]  # ONE device->host fetch

    def perplexity(self, prompt_ids: List[int], completion_ids: List[int]) -> dict:
        """Mean NLL of the completion given the prompt (LoRA already merged
        at load for this engine)."""
        if not hasattr(self, "_nll"):
            self._nll = jax.jit(
                lambda params, tokens, mask: nll_impl(params, self.cfg, tokens, mask)
            )
        tokens, mask, _ = prepare_nll_inputs(
            prompt_ids, completion_ids, self.tokenizer.eos_token_id,
            self.max_seq_len,
        )
        nll_sum, n_tok = self._nll(self.params, tokens, mask)
        return nll_result(float(nll_sum), int(n_tok))

    def chat(
        self,
        messages: List[dict],
        max_new_tokens: int = 128,
        temperature: float = 0.0,
        top_p: float = 1.0,
        seed: int = 0,
    ) -> str:
        """OpenAI-ish messages → templated prompt → completion text."""
        prompt_ids, stop_ids = encode_chat_messages(
            self.template, self.tokenizer, messages
        )
        out_ids = self.generate(
            prompt_ids, max_new_tokens=max_new_tokens, temperature=temperature,
            top_p=top_p, seed=seed, stop_ids=stop_ids,
        )
        return self.tokenizer.decode(out_ids, skip_special_tokens=True)


def encode_chat_messages(template: Template, tokenizer, messages: List[dict]):
    """OpenAI-ish messages → (prompt_ids, stop_ids) via the chat template.
    Shared by the single-request and continuous-batching engines so template
    semantics can never diverge between them."""
    system = None
    history: List[tuple] = []
    pending: Optional[str] = None
    for m in messages:
        role, content = m.get("role"), m.get("content", "")
        if role == "system":
            system = content
        elif role == "user":
            if pending is not None:
                history.append((pending, ""))
            pending = content
        elif role == "assistant" and pending is not None:
            history.append((pending, content))
            pending = None
    prompt_ids, _ = template.encode_oneturn(
        tokenizer, pending or "", "", history or None, system
    )
    stop_ids = {tokenizer.eos_token_id}
    for w in template.stop_words:
        tid = tokenizer.convert_tokens_to_ids(w)
        if isinstance(tid, int):  # no-unk fast tokenizers return None
            stop_ids.add(tid)
    return prompt_ids, stop_ids


def nll_result(nll_sum: float, n_tok: int) -> dict:
    import math

    mean = nll_sum / max(n_tok, 1)
    return {"nll_sum": nll_sum, "num_tokens": n_tok,
            "mean_nll": mean, "perplexity": math.exp(mean)}


def nll_impl(params, cfg, tokens, target_mask, **fw_kwargs):
    """Sum of -log p(token) over masked target positions + token count.

    ``target_mask`` marks completion tokens in the ORIGINAL index space;
    column j of the shifted targets corresponds to token j+1, so the mask is
    sliced accordingly. Backs the serving /perplexity endpoint (dataset-driven
    perplexity scoring, scoring/dataset_scoring.py)."""
    logits, _ = forward(params, tokens, cfg, compute_dtype=jnp.bfloat16,
                        **fw_kwargs)
    logprobs = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    tgt = tokens[:, 1:]
    ll = jnp.take_along_axis(logprobs, tgt[..., None], axis=-1)[..., 0]
    w = target_mask[:, 1:].astype(jnp.float32)
    return jnp.sum(-ll * w), jnp.sum(w)


def prepare_nll_inputs(prompt_ids, completion_ids, eos_id, max_seq_len,
                       bucket: int = 64):
    """Right-pad prompt+completion to a compile bucket; completion tokens get
    mask 1. Long inputs truncate from the LEFT, keeping the completion."""
    ids = list(prompt_ids) + list(completion_ids)
    if len(ids) > max_seq_len:
        ids = ids[-max_seq_len:]
    n_completion = min(len(completion_ids), len(ids) - 1)
    total = len(ids)
    padded = min(-(-total // bucket) * bucket, max_seq_len)
    mask = [0] * (total - n_completion) + [1] * n_completion
    ids = ids + [eos_id] * (padded - total)
    mask = mask + [0] * (padded - total)
    return (jnp.asarray([ids], jnp.int32), jnp.asarray([mask], jnp.int32),
            n_completion)


def _sample_jit(logits: jnp.ndarray, temperature, top_p, rng) -> jnp.ndarray:
    """Traceable sampling: greedy when temperature<=0, else top-p sampling.
    All branches computed and selected with where (cheap at vocab scale)."""
    greedy = jnp.argmax(logits).astype(jnp.int32)

    t = jnp.maximum(temperature, 1e-6)
    scaled = logits / t
    sorted_idx = jnp.argsort(-scaled)
    sorted_logits = scaled[sorted_idx]
    probs = jax.nn.softmax(sorted_logits)
    cum = jnp.cumsum(probs)
    cut = (cum - probs > top_p) & (top_p < 1.0)
    filtered = jnp.where(cut, -jnp.inf, sorted_logits)
    choice = jax.random.categorical(rng, filtered)
    sampled = sorted_idx[choice].astype(jnp.int32)

    return jnp.where(temperature <= 0.0, greedy, sampled)
