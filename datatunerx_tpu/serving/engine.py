"""Inference engine: jitted prefill + KV-cache decode for chat serving.

Replaces the reference's Ray Serve ``LlamaDeployment`` (deployed from a zip,
reference internal/controller/finetune/finetunejob_controller.go:378-384; env
contract BASE_MODEL_DIR + CHECKPOINT_DIR, pkg/util/generate/generate.go:288-294).
TPU-native: the base model + (optionally) a LoRA adapter checkpoint are loaded
directly (no image bake) and merged for serving; generation runs as a jitted
per-token decode step over a static-shape KV cache (JetStream-style decode loop,
SURVEY.md §7.1).
"""

from __future__ import annotations

import os
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from datatunerx_tpu.data.templates import Template, get_template
from datatunerx_tpu.models.llama import forward, init_cache
from datatunerx_tpu.utils.model_loader import load_model_and_tokenizer


class InferenceEngine:
    def __init__(
        self,
        model_path: str,
        checkpoint_path: Optional[str] = None,
        template: str = "llama2",
        max_seq_len: int = 1024,
        dtype=jnp.bfloat16,
    ):
        self.cfg, self.params, self.tokenizer = load_model_and_tokenizer(
            model_path, dtype=dtype
        )
        if checkpoint_path:
            self._apply_checkpoint(checkpoint_path)
        self.template: Template = get_template(template, self.tokenizer)
        self.max_seq_len = min(max_seq_len, self.cfg.max_seq_len)
        self._decode_step = jax.jit(self._decode_step_impl)
        self._prefill = jax.jit(self._prefill_impl, static_argnames=("prompt_len",))

    # ---------------------------------------------------------- checkpoint
    def _apply_checkpoint(self, checkpoint_path: str):
        """Merge a trained adapter (or swap full params) from an Orbax
        TrainState checkpoint or an exported model.npz directory."""
        if os.path.isdir(checkpoint_path) and os.path.exists(
            os.path.join(checkpoint_path, "model.npz")
        ):
            from datatunerx_tpu.utils.hf_convert import convert_hf_state_dict

            sd = dict(np.load(os.path.join(checkpoint_path, "model.npz")))
            self.params = convert_hf_state_dict(sd, self.cfg, dtype=np.float32)
            return
        # Orbax checkpoint dir (…/checkpoints or …/checkpoints/<step>)
        import orbax.checkpoint as ocp

        root = checkpoint_path.rstrip("/")
        step: Optional[int] = None
        if os.path.basename(root).isdigit():
            step = int(os.path.basename(root))
            root = os.path.dirname(root)
        mngr = ocp.CheckpointManager(root)
        step = step if step is not None else mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {checkpoint_path}")
        restored = mngr.restore(step)
        mngr.close()
        state = restored if isinstance(restored, dict) else dict(restored)
        lora = state.get("lora")
        if lora:
            from datatunerx_tpu.models.lora import lora_scaling, merge_lora

            # scaling travels in the manifest; default alpha/r = 32/8 matches
            # the reference defaults (cmd/tuning/parser.py:138-145)
            rank = next(iter(lora["layers"].values()))["a"].shape[-1]
            self.params = merge_lora(self.params, lora, lora_scaling(32.0, rank))
        elif state.get("params"):
            self.params = state["params"]

    # ------------------------------------------------------------ generate
    def _prefill_impl(self, params, tokens, cache, prompt_len):
        positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)[None]
        logits, cache = forward(
            params, tokens, self.cfg, positions=positions, cache=cache,
            compute_dtype=jnp.bfloat16,
        )
        return logits[:, prompt_len - 1], cache

    def _decode_step_impl(self, params, token, position, cache):
        logits, cache = forward(
            params, token, self.cfg, positions=position[None, None],
            cache=cache, compute_dtype=jnp.bfloat16,
        )
        return logits[:, -1], cache

    def generate(
        self,
        prompt_ids: List[int],
        max_new_tokens: int = 128,
        temperature: float = 0.0,
        top_p: float = 1.0,
        seed: int = 0,
        stop_ids: Optional[set] = None,
    ) -> List[int]:
        stop_ids = stop_ids or {self.tokenizer.eos_token_id}
        prompt_ids = prompt_ids[-(self.max_seq_len - max_new_tokens):]
        total = len(prompt_ids) + max_new_tokens
        cache = init_cache(self.cfg, 1, total, dtype=jnp.bfloat16)

        tokens = jnp.asarray([prompt_ids], jnp.int32)
        logits, cache = self._prefill(self.params, tokens, cache,
                                      prompt_len=len(prompt_ids))
        rng = jax.random.PRNGKey(seed)
        out: List[int] = []
        pos = len(prompt_ids)
        for i in range(max_new_tokens):
            rng, sub = jax.random.split(rng)
            nxt = int(_sample(logits[0], temperature, top_p, sub))
            if nxt in stop_ids:
                break
            out.append(nxt)
            logits, cache = self._decode_step(
                self.params, jnp.asarray([[nxt]], jnp.int32),
                jnp.asarray(pos, jnp.int32), cache,
            )
            pos += 1
        return out

    def chat(
        self,
        messages: List[dict],
        max_new_tokens: int = 128,
        temperature: float = 0.0,
        top_p: float = 1.0,
        seed: int = 0,
    ) -> str:
        """OpenAI-ish messages → templated prompt → completion text."""
        system = None
        history: List[tuple] = []
        query = ""
        pending_user: Optional[str] = None
        for m in messages:
            role, content = m.get("role"), m.get("content", "")
            if role == "system":
                system = content
            elif role == "user":
                if pending_user is not None:
                    history.append((pending_user, ""))
                pending_user = content
            elif role == "assistant" and pending_user is not None:
                history.append((pending_user, content))
                pending_user = None
        query = pending_user or ""

        prompt_ids, _ = self.template.encode_oneturn(
            self.tokenizer, query, "", history or None, system
        )
        stop_ids = {self.tokenizer.eos_token_id}
        for w in self.template.stop_words:
            stop_ids.add(self.tokenizer.convert_tokens_to_ids(w))
        out_ids = self.generate(
            prompt_ids, max_new_tokens=max_new_tokens, temperature=temperature,
            top_p=top_p, seed=seed, stop_ids=stop_ids,
        )
        return self.tokenizer.decode(out_ids, skip_special_tokens=True)


def _sample(logits: jnp.ndarray, temperature: float, top_p: float, rng) -> int:
    if temperature <= 0.0:
        return int(jnp.argmax(logits))
    logits = logits / temperature
    if top_p < 1.0:
        sorted_idx = jnp.argsort(-logits)
        sorted_logits = logits[sorted_idx]
        probs = jax.nn.softmax(sorted_logits)
        cum = jnp.cumsum(probs)
        cut = cum - probs > top_p  # keep tokens until cumulative mass > top_p
        sorted_logits = jnp.where(cut, -jnp.inf, sorted_logits)
        choice = jax.random.categorical(rng, sorted_logits)
        return int(sorted_idx[choice])
    return int(jax.random.categorical(rng, logits))
