"""Continuous-batching inference engine (JetStream-style decode, SURVEY §7.1).

The round-1 engine decoded one request at a time (batch=1, LoRA merged at
load). This engine runs a SINGLE jitted decode program over S cache slots and
admits new requests into free slots between decode chunks — the serving tier
the reference buys from Ray Serve (reference pkg/util/generate/
generate.go:160-329 deploys LlamaDeployment replicas), rebuilt TPU-first:

- per-slot KV cache cursors (models/llama.py ``init_cache(per_slot=True)``):
  rows sit at different depths inside one program; sentinel rope positions
  mask free/garbage slots, so no per-slot programs and no re-batching pauses;
- decode runs in CHUNKS of K tokens per program (``lax.scan`` over the
  single-token step): K amortizes dispatch latency (fatal over a tunneled
  accelerator at K=1) while keeping admission latency bounded at K tokens;
- UNMERGED multi-adapter LoRA: adapters are stacked ([L, E, d, r]) and each
  slot indexes its own adapter inside the matmul (models/llama.py _proj
  lora_idx) — one base model serves many tuned jobs concurrently;
- streaming: each emitted token lands on the request's queue as soon as its
  chunk completes (SSE transport in serving/server.py).
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from datatunerx_tpu.data.templates import Template, get_template
from datatunerx_tpu.models.llama import forward, init_cache
from datatunerx_tpu.models.lora import LORA_TARGETS, lora_scaling
from datatunerx_tpu.serving.engine import _sample_jit
from datatunerx_tpu.utils.model_loader import load_model_and_tokenizer

MAX_STOP = 8  # static per-slot stop-token capacity


class _PrefixCache:
    """Host-side LRU of prefilled single-row KV caches keyed by
    (prompt tokens, adapter). An exact hit skips prefill entirely; the longest
    strict-prefix hit turns prefill into a (shorter) suffix extension — the
    prefix-reuse tier of paged serving stacks (vLLM/JetStream), host-managed
    here because rows are full-width and slots are few.

    Lookup structure is a per-adapter token TRIE: ``longest_prefix`` walks at
    most ``len(tokens)`` nodes, so admission cost is O(prompt_len) instead of
    the round-2 O(entries × prompt_len) linear scan over all stored keys.
    The OrderedDict keeps only LRU recency + the entry payloads; the trie
    mirrors its key set (terminal nodes point back at the exact key).

    Entries: {"cache": row_cache, "logits": last-token logits,
    "cursor": cache write depth}. Stored row caches are immutable JAX
    arrays — inserting a row into a slot copies, and extension builds a new
    functional cache, so shared prefixes are safe.
    """

    def __init__(self, capacity: int):
        from collections import OrderedDict

        self.capacity = capacity
        self._d: "OrderedDict[tuple, dict]" = OrderedDict()
        # adapter -> trie root; node = [children {tok: node}, terminal key]
        self._roots: Dict[int, list] = {}
        self.evictions = 0

    def __len__(self):
        return len(self._d)

    def get(self, key):
        ent = self._d.get(key)
        if ent is not None:
            self._d.move_to_end(key)
        return ent

    def longest_prefix(self, tokens: tuple, adapter: int):
        """Longest stored strict prefix of ``tokens`` for this adapter —
        one trie descent, deepest terminal wins."""
        node = self._roots.get(adapter)
        if node is None:
            return None, None
        best_key = None
        for i in range(len(tokens) - 1):  # strict: depth < len(tokens)
            node = node[0].get(tokens[i])
            if node is None:
                break
            if node[1] is not None:
                best_key = node[1]
        if best_key is None:
            return None, None
        self._d.move_to_end(best_key)
        return best_key, self._d[best_key]

    def put(self, key, ent):
        is_new = key not in self._d
        self._d[key] = ent
        self._d.move_to_end(key)
        if is_new:
            ptoks, adapter = key
            node = self._roots.setdefault(adapter, [{}, None])
            for t in ptoks:
                node = node[0].setdefault(t, [{}, None])
            node[1] = key
        while len(self._d) > self.capacity:
            old_key, _ = self._d.popitem(last=False)
            self._trie_remove(old_key)
            self.evictions += 1

    def _trie_remove(self, key):
        ptoks, adapter = key
        root = self._roots.get(adapter)
        if root is None:
            return
        path, node = [root], root
        for t in ptoks:
            node = node[0].get(t)
            if node is None:
                return
            path.append(node)
        node[1] = None
        # prune now-useless nodes bottom-up so the trie never outgrows
        # capacity × prompt_len
        for i in range(len(path) - 1, 0, -1):
            n = path[i]
            if n[0] or n[1] is not None:
                break
            del path[i - 1][0][ptoks[i - 1]]
        if not root[0] and root[1] is None:
            del self._roots[adapter]


class Request:
    def __init__(self, prompt_ids: Sequence[int], max_new_tokens: int,
                 temperature: float, top_p: float, seed: int,
                 stop_ids: Sequence[int], adapter: int):
        self.prompt_ids = list(prompt_ids)
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_p = top_p
        self.seed = seed
        self.stop_ids = list(stop_ids)[:MAX_STOP]
        self.adapter = adapter
        self.tokens: List[int] = []
        self.stream: "queue.Queue[Optional[int]]" = queue.Queue()
        self.done = threading.Event()
        self.error: Optional[str] = None

    def push(self, token: int):
        self.tokens.append(token)
        self.stream.put(token)

    def finish(self, error: Optional[str] = None):
        self.error = error
        self.stream.put(None)
        self.done.set()


def load_checkpoint_state(checkpoint_path: str) -> dict:
    """Load an Orbax TrainState checkpoint dir (…/checkpoints[/<step>]) and
    return its raw state dict ({"lora": …} and/or {"params": …}), plus the
    recorded manifest lora scaling under "_scaling" when available."""
    import os

    import orbax.checkpoint as ocp

    from datatunerx_tpu.serving.engine import InferenceEngine

    root = checkpoint_path.rstrip("/")
    step: Optional[int] = None
    if os.path.basename(root).isdigit():
        step = int(os.path.basename(root))
        root = os.path.dirname(root)
    mngr = ocp.CheckpointManager(root)
    step = step if step is not None else mngr.latest_step()
    if step is None:
        raise FileNotFoundError(f"no checkpoint under {checkpoint_path}")
    restored = mngr.restore(step)
    mngr.close()
    state = restored if isinstance(restored, dict) else dict(restored)
    state["_scaling"] = InferenceEngine._manifest_lora_scaling(root)
    return state


class BatchedEngine:
    def __init__(
        self,
        model_path: str,
        checkpoint_path: Optional[str] = None,
        adapters: Optional[Dict[str, str]] = None,  # name -> checkpoint path
        template: str = "llama2",
        max_seq_len: int = 1024,
        slots: int = 4,
        decode_chunk: int = 8,
        dtype=jnp.bfloat16,
        kv_quant: Optional[str] = None,  # "int8" halves cache HBM
        prefix_cache: int = 0,  # LRU entries of reusable prefilled prefixes
    ):
        # serving is single-program: clear any mesh a Trainer left in the
        # process-global flash context before the engine's jits first trace
        from datatunerx_tpu.ops.flash_attention import set_flash_context

        set_flash_context(None)
        self.cfg, self.params, self.tokenizer = load_model_and_tokenizer(
            model_path, dtype=dtype
        )
        self.template: Template = get_template(template, self.tokenizer)
        self.max_seq_len = min(max_seq_len, self.cfg.max_seq_len)
        self.slots = slots
        self.chunk = max(1, decode_chunk)

        # ---- adapters: checkpoint_path becomes adapter "default" (unmerged);
        # full-param checkpoints swap the base instead
        named: Dict[str, str] = dict(adapters or {})
        if checkpoint_path:
            state = load_checkpoint_state(checkpoint_path)
            if state.get("lora"):
                named.setdefault("default", checkpoint_path)
            elif state.get("params"):
                self.params = jax.device_put(state["params"])
        self.adapter_ids: Dict[str, int] = {"": 0}  # 0 = base (zero adapter)
        self.lora_stack: Optional[tuple] = None
        if named:
            self._build_adapter_stack(named)

        self.kv_quant = kv_quant or None
        self._cache = init_cache(self.cfg, slots, self.max_seq_len,
                                 dtype=jnp.bfloat16, per_slot=True,
                                 quantize=self.kv_quant)
        V = self.cfg.vocab_size
        self._logits = jnp.zeros((slots, V), jnp.float32)
        self._pos = jnp.zeros((slots,), jnp.int32)
        self._remaining = jnp.zeros((slots,), jnp.int32)
        self._active = jnp.zeros((slots,), bool)
        self._rng = jnp.stack([jax.random.PRNGKey(i) for i in range(slots)])
        self._temps = jnp.zeros((slots,), jnp.float32)
        self._top_ps = jnp.ones((slots,), jnp.float32)
        self._stops = jnp.full((slots, MAX_STOP), -1, jnp.int32)
        self._adapter_idx = jnp.zeros((slots,), jnp.int32)

        self._slot_req: List[Optional[Request]] = [None] * slots
        self._waiting: "queue.Queue[Request]" = queue.Queue()
        self._wake = threading.Event()
        self._shutdown = threading.Event()

        self._prefill = jax.jit(self._prefill_impl,
                                static_argnames=("prompt_len",))
        self._extend = jax.jit(self._extend_impl,
                               static_argnames=("suffix_len",))
        self._insert = jax.jit(self._insert_impl)
        self._decode = jax.jit(self._decode_impl, static_argnames=("K",))

        self._prefix = _PrefixCache(prefix_cache) if prefix_cache > 0 else None
        # observability: how admissions were served (tests + /metrics)
        self.prefill_stats = {"full": 0, "reuse": 0, "extend": 0}

        self._thread = threading.Thread(target=self._scheduler, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- adapters
    def _build_adapter_stack(self, named: Dict[str, str]):
        """Stack named adapter checkpoints into [L, E, …] leaves (entry 0 is
        the all-zero base adapter). Mixed ranks are padded to the max rank
        (zero cols/rows leave the delta unchanged); mixed target sets take
        the union with zeros where an adapter lacks a target."""
        from datatunerx_tpu.models.lora import target_dims

        loaded: List[Tuple[str, dict, float]] = []
        for name, path in named.items():
            state = load_checkpoint_state(path)
            lora = state.get("lora")
            if not lora:
                raise ValueError(f"adapter {name!r}: no lora tree in {path}")
            layers = lora["layers"]
            rank = next(iter(layers.values()))["a"].shape[-1]
            scaling = state.get("_scaling")
            if scaling is None:
                scaling = lora_scaling(32.0, rank)
            loaded.append((name, layers, float(scaling)))

        targets = sorted({t for _, layers, _ in loaded for t in layers}
                         & set(LORA_TARGETS))
        max_rank = max(
            layers[t]["a"].shape[-1]
            for _, layers, _ in loaded for t in layers
        )
        L = self.cfg.num_layers
        E = len(loaded) + 1  # + base zero adapter
        stack: Dict[str, dict] = {}
        for t in targets:
            d_in, d_out = target_dims(self.cfg, t)
            a = np.zeros((L, E, d_in, max_rank), np.float32)
            b = np.zeros((L, E, max_rank, d_out), np.float32)
            for e, (_, layers, _) in enumerate(loaded, start=1):
                if t not in layers:
                    continue
                ar = np.asarray(layers[t]["a"], np.float32)  # [L, d_in, r]
                br = np.asarray(layers[t]["b"], np.float32)
                r = ar.shape[-1]
                a[:, e, :, :r] = ar
                b[:, e, :r, :] = br
            stack[t] = {"a": jnp.asarray(a), "b": jnp.asarray(b)}
        scales = jnp.asarray([0.0] + [s for _, _, s in loaded], jnp.float32)
        self.lora_stack = ({"layers": stack}, scales)
        for e, (name, _, _) in enumerate(loaded, start=1):
            self.adapter_ids[name] = e

    def _lora_args(self):
        if self.lora_stack is None:
            return {"lora": None}
        tree, scales = self.lora_stack
        return {"lora": (tree, scales)}

    # --------------------------------------------------------------- jitted
    def _prefill_impl(self, params, tokens, mask, positions, adapter_idx, *,
                      prompt_len: int):
        cache = init_cache(self.cfg, 1, self.max_seq_len, dtype=jnp.bfloat16,
                           quantize=self.kv_quant)
        logits, cache = forward(
            params, tokens, self.cfg, positions=positions,
            attention_mask=mask, cache=cache,
            lora_adapter_idx=(adapter_idx[None]
                              if self.lora_stack is not None else None),
            compute_dtype=jnp.bfloat16, **self._lora_args(),
        )
        return logits[0, prompt_len - 1], cache

    def _extend_impl(self, params, row_cache, tokens, mask, positions,
                     adapter_idx, *, suffix_len: int):
        """Append a (left-pad-bucketed) prompt suffix onto a cached prefix
        row: pads get sentinel rope positions so only the real tokens exist
        for attention, exactly as in full prefill."""
        logits, cache = forward(
            params, tokens, self.cfg, positions=positions,
            attention_mask=mask, cache=row_cache,
            lora_adapter_idx=(adapter_idx[None]
                              if self.lora_stack is not None else None),
            compute_dtype=jnp.bfloat16, **self._lora_args(),
        )
        return logits[0, suffix_len - 1], cache

    def _insert_impl(self, cache, logits_all, pos, remaining, active, temps,
                     top_ps, stops, adapter_idx, rng,
                     slot, row_cache, row_logits, plen, n_prompt, max_new,
                     temp, top_p, stop_row, adapter, seed):
        cache = dict(cache)
        cache["k"] = jax.lax.dynamic_update_slice(
            cache["k"], row_cache["k"], (0, slot, 0, 0, 0))
        cache["v"] = jax.lax.dynamic_update_slice(
            cache["v"], row_cache["v"], (0, slot, 0, 0, 0))
        if "k_scale" in cache:
            cache["k_scale"] = jax.lax.dynamic_update_slice(
                cache["k_scale"], row_cache["k_scale"], (0, slot, 0, 0))
            cache["v_scale"] = jax.lax.dynamic_update_slice(
                cache["v_scale"], row_cache["v_scale"], (0, slot, 0, 0))
        cache["pos"] = jax.lax.dynamic_update_slice(
            cache["pos"], row_cache["pos"], (slot, 0))
        cache["len"] = cache["len"].at[slot].set(plen)
        return (
            cache,
            logits_all.at[slot].set(row_logits),
            pos.at[slot].set(n_prompt),
            remaining.at[slot].set(max_new),
            active.at[slot].set(True),
            temps.at[slot].set(temp),
            top_ps.at[slot].set(top_p),
            stops.at[slot].set(stop_row),
            adapter_idx.at[slot].set(adapter),
            rng.at[slot].set(jax.random.PRNGKey(seed)),
        )

    def _decode_impl(self, params, cache, logits, pos, remaining, active, rng,
                     temps, top_ps, stops, adapter_idx, *, K: int):
        lora_kw = self._lora_args()

        def step(carry, _):
            logits, cache, pos, remaining, active, rng = carry
            split = jax.vmap(jax.random.split)(rng)
            rng, sub = split[:, 0], split[:, 1]
            nxt = jax.vmap(_sample_jit)(logits, temps, top_ps, sub)
            is_stop = jnp.any(nxt[:, None] == stops, axis=1)
            emit = active & ~is_stop & (remaining > 0)
            emitted = jnp.where(emit, nxt, -1)
            new_active = emit & (remaining > 1)
            remaining = remaining - emit.astype(jnp.int32)

            prev_len = cache["len"]
            tok = jnp.where(emit, nxt, 0)[:, None]
            logits2, cache = forward(
                params, tok, self.cfg, positions=pos[:, None],
                attention_mask=emit[:, None].astype(jnp.int32), cache=cache,
                lora_adapter_idx=(adapter_idx
                                  if self.lora_stack is not None else None),
                compute_dtype=jnp.bfloat16, **lora_kw,
            )
            # forward advances every cursor; only emitting slots really moved
            cache = dict(cache)
            cache["len"] = prev_len + emit.astype(jnp.int32)
            pos = pos + emit.astype(jnp.int32)
            return (logits2[:, -1], cache, pos, remaining, new_active, rng), emitted

        (logits, cache, pos, remaining, active, rng), emitted = jax.lax.scan(
            step, (logits, cache, pos, remaining, active, rng), None, length=K
        )
        return emitted, logits, cache, pos, remaining, active, rng

    # ------------------------------------------------------------ scheduler
    def _prefill_row(self, ids, mask, positions, plen, n_prompt, adapter,
                     budget_needed: int = 1):
        """Produce (last-token logits, row cache, cache cursor) for a prompt,
        going through the prefix cache when enabled: exact hit = no compute,
        prefix hit = suffix-only extension, miss = full prefill (+ store).

        Reuse must never change the response: a cached row whose cursor sits
        deeper than this request's own plen (extension padding accumulates)
        is only used when it still leaves ``budget_needed`` decode room —
        otherwise the cold path runs, so budget and output match a cache-cold
        server exactly."""
        from datatunerx_tpu.utils.decoding import DECODE_BUCKET

        used = tuple(ids[plen - n_prompt:])
        key = (used, adapter)
        # the decode room the cold path would provide; reuse may not shrink
        # the effective budget below min(requested, cold)
        cold_budget = self.max_seq_len - plen
        need = min(budget_needed, cold_budget)
        if self._prefix is not None:
            ent = self._prefix.get(key)
            if ent is not None and self.max_seq_len - ent["cursor"] >= need:
                self.prefill_stats["reuse"] += 1
                return ent["logits"], ent["cache"], ent["cursor"]
            pkey, pent = self._prefix.longest_prefix(used, adapter)
            if pent is not None:
                n_pref = len(pkey[0])
                suffix = list(used[n_pref:])
                pad = (-len(suffix)) % DECODE_BUCKET
                stoks = [self.tokenizer.eos_token_id or 0] * pad + suffix
                smask = [0] * pad + [1] * len(suffix)
                spos = [0] * pad + list(range(n_pref, len(used)))
                cursor = pent["cursor"] + len(stoks)
                if self.max_seq_len - cursor >= need:
                    row_logits, row_cache = self._extend(
                        self.params, pent["cache"],
                        jnp.asarray([stoks], jnp.int32),
                        jnp.asarray([smask], jnp.int32),
                        jnp.asarray([spos], jnp.int32),
                        jnp.asarray(adapter, jnp.int32),
                        suffix_len=len(stoks),
                    )
                    self.prefill_stats["extend"] += 1
                    self._prefix.put(key, {"cache": row_cache,
                                           "logits": row_logits,
                                           "cursor": cursor})
                    return row_logits, row_cache, cursor

        row_logits, row_cache = self._prefill(
            self.params, jnp.asarray([ids], jnp.int32),
            jnp.asarray([mask], jnp.int32), jnp.asarray([positions], jnp.int32),
            jnp.asarray(adapter, jnp.int32), prompt_len=plen,
        )
        self.prefill_stats["full"] += 1
        if self._prefix is not None:
            self._prefix.put(key, {"cache": row_cache, "logits": row_logits,
                                   "cursor": plen})
        return row_logits, row_cache, plen

    def _admit(self, req: Request, slot: int):
        from datatunerx_tpu.utils.decoding import prepare_prompt

        ids, mask, positions, plen, n_prompt, max_new, _ = prepare_prompt(
            req.prompt_ids, self.tokenizer.eos_token_id,
            self.max_seq_len, req.max_new_tokens,
        )
        row_logits, row_cache, cursor = self._prefill_row(
            ids, mask, positions, plen, n_prompt, req.adapter,
            budget_needed=max_new)
        max_new = max(1, min(max_new, self.max_seq_len - cursor))
        stop_row = np.full((MAX_STOP,), -1, np.int32)
        stop_row[: len(req.stop_ids)] = req.stop_ids
        (self._cache, self._logits, self._pos, self._remaining, self._active,
         self._temps, self._top_ps, self._stops, self._adapter_idx,
         self._rng) = self._insert(
            self._cache, self._logits, self._pos, self._remaining, self._active,
            self._temps, self._top_ps, self._stops, self._adapter_idx, self._rng,
            jnp.asarray(slot, jnp.int32), row_cache, row_logits,
            # the slot's write cursor continues from the row's real KV depth
            # (prefix reuse can sit deeper than this request's own plen)
            jnp.asarray(cursor, jnp.int32), jnp.asarray(n_prompt, jnp.int32),
            jnp.asarray(max_new, jnp.int32),
            jnp.asarray(req.temperature, jnp.float32),
            jnp.asarray(req.top_p, jnp.float32),
            jnp.asarray(stop_row), jnp.asarray(req.adapter, jnp.int32),
            jnp.asarray(req.seed, jnp.uint32),
        )
        self._slot_req[slot] = req

    def _scheduler(self):
        while not self._shutdown.is_set():
            admitted = False
            for slot in range(self.slots):
                if self._slot_req[slot] is not None:
                    continue
                try:
                    req = self._waiting.get_nowait()
                except queue.Empty:
                    break
                try:
                    self._admit(req, slot)
                    admitted = True
                except Exception as e:  # noqa: BLE001 — fail the request, not the loop
                    req.finish(error=str(e))

            if not any(r is not None for r in self._slot_req):
                self._wake.wait(timeout=0.1)
                self._wake.clear()
                continue

            try:
                (emitted, self._logits, self._cache, self._pos,
                 self._remaining, self._active, self._rng) = self._decode(
                    self.params, self._cache, self._logits, self._pos,
                    self._remaining, self._active, self._rng, self._temps,
                    self._top_ps, self._stops, self._adapter_idx, K=self.chunk,
                )
                # the decode loop's ONE designed sync point: K tokens per
                # chunk cross to host here so req.push can stream them
                emitted_np = np.asarray(emitted)  # [K, S]  # dtxlint: disable=DTX001
                active_np = np.asarray(self._active)  # [S]  # dtxlint: disable=DTX001
            except Exception as e:  # noqa: BLE001 — device fault: fail all in-flight
                for slot, req in enumerate(self._slot_req):
                    if req is not None:
                        req.finish(error=str(e))
                        self._slot_req[slot] = None
                continue

            for k in range(emitted_np.shape[0]):
                for slot in range(self.slots):
                    # emitted_np is host-side numpy already — no device sync
                    t = int(emitted_np[k, slot])  # dtxlint: disable=DTX001
                    req = self._slot_req[slot]
                    if t >= 0 and req is not None:
                        req.push(t)
            for slot in range(self.slots):
                req = self._slot_req[slot]
                if req is not None and not bool(active_np[slot]):
                    req.finish()
                    self._slot_req[slot] = None
            # `admitted` intentionally unused beyond debugging
            del admitted

    # ---------------------------------------------------------------- API
    def submit(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int = 128,
        temperature: float = 0.0,
        top_p: float = 1.0,
        seed: int = 0,
        stop_ids: Optional[set] = None,
        adapter: str = "",
    ) -> Request:
        if adapter not in self.adapter_ids:
            raise KeyError(
                f"unknown adapter {adapter!r}; loaded: "
                f"{sorted(n for n in self.adapter_ids if n)}"
            )
        stops = {int(s) for s in (stop_ids or set())}
        stops.add(int(self.tokenizer.eos_token_id))
        req = Request(prompt_ids, max_new_tokens, temperature, top_p, seed,
                      sorted(stops), self.adapter_ids[adapter])
        self._waiting.put(req)
        self._wake.set()
        return req

    def generate(self, prompt_ids, timeout: float = 300.0, **kw) -> List[int]:
        req = self.submit(prompt_ids, **kw)
        if not req.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if req.error:
            raise RuntimeError(req.error)
        return req.tokens

    def _encode_chat(self, messages: List[dict]):
        from datatunerx_tpu.serving.engine import encode_chat_messages

        return encode_chat_messages(self.template, self.tokenizer, messages)

    def perplexity(self, prompt_ids: Sequence[int],
                   completion_ids: Sequence[int], adapter: str = "") -> dict:
        """Mean completion NLL under the (optionally adapter-indexed) model —
        the unmerged stack scores through the same lora_idx path decode uses."""
        from datatunerx_tpu.serving.engine import (
            nll_impl,
            nll_result,
            prepare_nll_inputs,
        )

        if adapter not in self.adapter_ids:
            raise KeyError(f"unknown adapter {adapter!r}")
        if not hasattr(self, "_nll"):
            def impl(params, tokens, mask, aidx):
                return nll_impl(
                    params, self.cfg, tokens, mask,
                    lora_adapter_idx=(aidx[None] if self.lora_stack is not None
                                      else None),
                    **self._lora_args(),
                )

            self._nll = jax.jit(impl)
        tokens, mask, _ = prepare_nll_inputs(
            list(prompt_ids), list(completion_ids),
            self.tokenizer.eos_token_id, self.max_seq_len,
        )
        nll_sum, n_tok = self._nll(
            self.params, tokens, mask,
            jnp.asarray(self.adapter_ids[adapter], jnp.int32),
        )
        return nll_result(float(nll_sum), int(n_tok))

    def chat(self, messages: List[dict], max_new_tokens: int = 128,
             temperature: float = 0.0, top_p: float = 1.0, seed: int = 0,
             adapter: str = "") -> str:
        prompt_ids, stop_ids = self._encode_chat(messages)
        out = self.generate(prompt_ids, max_new_tokens=max_new_tokens,
                            temperature=temperature, top_p=top_p, seed=seed,
                            stop_ids=stop_ids, adapter=adapter)
        return self.tokenizer.decode(out, skip_special_tokens=True)

    def chat_stream(self, messages: List[dict], max_new_tokens: int = 128,
                    temperature: float = 0.0, top_p: float = 1.0,
                    seed: int = 0, adapter: str = ""):
        """Yields text deltas as tokens stream off the decode chunks."""
        prompt_ids, stop_ids = self._encode_chat(messages)
        req = self.submit(prompt_ids, max_new_tokens=max_new_tokens,
                          temperature=temperature, top_p=top_p, seed=seed,
                          stop_ids=stop_ids, adapter=adapter)
        sent = ""
        acc: List[int] = []
        while True:
            t = req.stream.get()
            if t is None:
                break
            acc.append(t)
            text = self.tokenizer.decode(acc, skip_special_tokens=True)
            if len(text) > len(sent) and not text.endswith("�"):
                yield text[len(sent):]
                sent = text
        if req.error:
            raise RuntimeError(req.error)

    def close(self):
        self._shutdown.set()
        self._wake.set()
        self._thread.join(timeout=10)
